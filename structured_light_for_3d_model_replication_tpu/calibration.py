"""Camera–projector stereo calibration.

Reimplements the reference's calibration stack (`server/sl_system.py:114-417`):

* corner detection with the same enhancement chain — Gaussian blur + CLAHE
  before ``findChessboardCorners``, sub-pixel refinement on the raw gray
  (`server/sl_system.py:229-240`),
* Gray-decode of the projector coordinate at each detected corner — the
  reference XOR-accumulates per-bit at 49 corner pixels in Python
  (`:257-288`); here the WHOLE stack is decoded in one jitted TPU kernel
  (`ops.decode.decode_stack`) and sampled at the corner pixels, identical
  values by construction (same int truncation of the sub-pixel coordinate),
* quick per-pose reprojection errors for pose culling (`:307-327`),
* final stereo calibration: ``calibrateCamera`` x2 then ``stereoCalibrate``
  with ``CALIB_FIX_INTRINSIC`` (`:335-343`).

Bundle-adjusted intrinsics over a handful of 49-corner poses are host-side
LM solves — CPU work in any design (SURVEY.md §2d keeps the OpenCV oracle
path). Everything downstream of (K, R, T) — the ray grid and the 3000 light
planes — is the vmapped JAX precompute in `ops.triangulate.make_calibration`.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from .config import CheckerboardConfig, ProjectorConfig
from .io import layout as _layout
from .io.images import list_frames, load_stack
from .io.matcal import save_calibration_mat
from .ops.decode import decode_bits, split_stack
from .ops.triangulate import Calibration, make_calibration


@dataclasses.dataclass
class StereoResult:
    cam_K: np.ndarray
    cam_dist: np.ndarray
    proj_K: np.ndarray
    proj_dist: np.ndarray
    R: np.ndarray
    T: np.ndarray
    rms: float


def object_points(board: CheckerboardConfig) -> np.ndarray:
    """Planar board corner coordinates in mm (`server/sl_system.py:206-209`)."""
    objp = np.zeros((board.rows * board.cols, 3), np.float32)
    objp[:, :2] = np.mgrid[0:board.rows, 0:board.cols].T.reshape(-1, 2)
    return objp * board.square_mm


def detect_chessboard(img_gray: np.ndarray, board: CheckerboardConfig):
    """(found, corners (N,1,2) float32) with the reference's enhancement chain
    (`server/sl_system.py:229-240`): blur+CLAHE for detection, sub-pixel
    refinement against the raw gray image."""
    import cv2

    blurred = cv2.GaussianBlur(img_gray, (5, 5), 0)
    clahe = cv2.createCLAHE(clipLimit=2.0, tileGridSize=(8, 8))
    enhanced = clahe.apply(blurred)
    found, corners = cv2.findChessboardCorners(
        enhanced, (board.rows, board.cols), None)
    if not found:
        return False, None
    # The reference refines with a fixed (11, 11) half-window
    # (`server/sl_system.py:240`), sized for full-res captures. A window
    # wider than half the square spacing makes cornerSubPix stray to the
    # neighboring corners, so cap it by the observed corner pitch.
    pts = np.asarray(corners, np.float32).reshape(-1, 2)
    pitch = np.linalg.norm(np.diff(pts[: board.rows], axis=0), axis=-1).min()
    win = int(np.clip(0.4 * pitch, 2, 11))
    corners = cv2.cornerSubPix(
        img_gray, corners, (win, win), (-1, -1),
        (cv2.TERM_CRITERIA_EPS + cv2.TERM_CRITERIA_MAX_ITER, 30, 0.001))
    # OpenCV version drift: normalize to the classic (N, 1, 2) layout.
    return True, np.asarray(corners, np.float32).reshape(-1, 1, 2)


def decode_at_corners(
    stack: np.ndarray,
    corners: np.ndarray,
    proj: ProjectorConfig,
) -> np.ndarray:
    """Projector (u, v) at each corner pixel, (N, 2) float32.

    One jitted decode of the full stack, then a gather at the int-truncated
    corner coordinates — bit-for-bit the reference's per-corner XOR loop
    (`server/sl_system.py:257-296`, `vp = img_p[y.astype(int), x.astype(int)]`).
    """
    import jax.numpy as jnp

    dev = jnp.asarray(stack)
    _, _, col_pairs, row_pairs = split_stack(dev, proj.col_bits, proj.row_bits)
    # Same coarse-code -> projector-pixel rescale as decode_stack
    # (ops/decode.py): stripe index * D + stripe-center offset.
    d = proj.downsample
    col_map = np.asarray(decode_bits(col_pairs)) * d + (d - 1) // 2
    row_map = np.asarray(decode_bits(row_pairs)) * d + (d - 1) // 2
    x = corners[:, 0, 0].astype(int)
    y = corners[:, 0, 1].astype(int)
    return np.stack([col_map[y, x], row_map[y, x]], axis=-1).astype(np.float32)


@dataclasses.dataclass
class CalibData:
    obj_pts: list          # per pose (N, 3) float32
    cam_pts: list          # per pose (N, 1, 2) float32
    proj_pts: list         # per pose (N, 1, 2) float32
    img_shape: tuple       # (w, h)
    poses: list            # pose dir names that survived detection


def load_calib_data(
    pose_dirs: list[str],
    proj: ProjectorConfig = ProjectorConfig(),
    board: CheckerboardConfig = CheckerboardConfig(),
) -> CalibData:
    """Detect + decode every pose folder (`server/sl_system.py:204-305`)."""
    import cv2

    objp = object_points(board)
    data = CalibData([], [], [], None, [])
    for path in pose_dirs:
        files = list_frames(path)
        img = cv2.imread(files[0], cv2.IMREAD_GRAYSCALE)
        if img is None:
            continue
        if data.img_shape is None:
            data.img_shape = (img.shape[1], img.shape[0])
        found, corners = detect_chessboard(img, board)
        if not found:
            continue
        if len(files) < proj.n_frames:
            continue
        stack = load_stack(path, expected_frames=None)[: proj.n_frames]
        uv = decode_at_corners(stack, corners, proj)
        data.obj_pts.append(objp)
        data.cam_pts.append(corners)
        data.proj_pts.append(uv.reshape(-1, 1, 2))
        data.poses.append(os.path.basename(path))
    return data


def reprojection_errors(
    data: CalibData,
    proj: ProjectorConfig = ProjectorConfig(),
) -> dict[str, tuple[float, float]]:
    """Per-pose (camera_err, projector_err) for manual pose culling
    (`server/sl_system.py:307-327`)."""
    import cv2

    _, mc, dc, rvc, tvc = cv2.calibrateCamera(
        data.obj_pts, data.cam_pts, data.img_shape, None, None)
    _, mp, dp, rvp, tvp = cv2.calibrateCamera(
        data.obj_pts, data.proj_pts, (proj.width, proj.height), None, None)
    errors = {}
    for i, pose in enumerate(data.poses):
        p2c, _ = cv2.projectPoints(data.obj_pts[i], rvc[i], tvc[i], mc, dc)
        ec = cv2.norm(data.cam_pts[i], p2c.astype(np.float32),
                      cv2.NORM_L2) / len(p2c)
        p2p, _ = cv2.projectPoints(data.obj_pts[i], rvp[i], tvp[i], mp, dp)
        ep = cv2.norm(data.proj_pts[i].astype(np.float32),
                      p2p.astype(np.float32), cv2.NORM_L2) / len(p2p)
        errors[pose] = (float(ec), float(ep))
    return errors


def analyze_calibration(
    calib_dir: str,
    proj: ProjectorConfig = ProjectorConfig(),
    board: CheckerboardConfig = CheckerboardConfig(),
):
    """(errors, pose_names) for the pose-selection step
    (`server/sl_system.py:187-202`; >= 3 poses required)."""
    pose_dirs = _layout.numeric_sort([
        os.path.join(calib_dir, d) for d in os.listdir(calib_dir)
        if os.path.isdir(os.path.join(calib_dir, d))])
    if len(pose_dirs) < 3:
        raise ValueError(f"need at least 3 pose folders in {calib_dir}")
    data = load_calib_data(pose_dirs, proj, board)
    if len(data.obj_pts) < 3:
        raise ValueError(
            f"chessboard detected in only {len(data.obj_pts)} of "
            f"{len(pose_dirs)} poses; need >= 3")
    return reprojection_errors(data, proj), data.poses


def stereo_calibrate(
    data: CalibData,
    proj: ProjectorConfig = ProjectorConfig(),
) -> StereoResult:
    """calibrateCamera x2 + stereoCalibrate(FIX_INTRINSIC)
    (`server/sl_system.py:335-343`). X_p = R X_c + T."""
    import cv2

    _, mc, dc, _, _ = cv2.calibrateCamera(
        data.obj_pts, data.cam_pts, data.img_shape, None, None)
    _, mp, dp, _, _ = cv2.calibrateCamera(
        data.obj_pts, data.proj_pts, (proj.width, proj.height), None, None)
    rms, K1, D1, K2, D2, R, T, _, _ = cv2.stereoCalibrate(
        data.obj_pts, data.cam_pts, data.proj_pts, mc, dc, mp, dp,
        data.img_shape, flags=cv2.CALIB_FIX_INTRINSIC)
    return StereoResult(K1, D1, K2, D2, R, T.reshape(3), float(rms))


def refine_stereo_jax(
    data: CalibData,
    stereo: StereoResult,
    iterations: int = 30,
) -> StereoResult:
    """JAX Levenberg–Marquardt refinement of the stereo solve.

    Re-derives the optimization inside ``cv2.stereoCalibrate`` (SURVEY §7's
    "optionally re-derive the LM optimization in JAX"): joint LM over the
    stereo extrinsics (ω, t) and the per-pose board extrinsics (ωᵢ, tᵢ),
    intrinsics FIXED (the CALIB_FIX_INTRINSIC semantics the reference uses,
    `server/sl_system.py:341-343`), minimizing the combined camera +
    projector reprojection error. The residual model is an ideal pinhole —
    matching how the precomputed rays/planes consume the result
    (`ops/triangulate.py`) — so the OBSERVATIONS are first undistorted
    (``cv2.undistortPoints`` with ``P=K``) using the lens models OpenCV
    estimated jointly with the intrinsics: raw corner detections on a real
    lens do not satisfy the pinhole projection, and LM against them would
    drift R/T away from the cv2 solution while reporting an RMS that is not
    comparable to ``stereo.rms``.

    The problem is tiny and dense (6 + 6·P parameters, ~4·P·N residuals):
    one ``jacfwd`` Jacobian + a damped normal-equations solve per step, all
    jitted. Initialized from the OpenCV solution; returns a StereoResult
    with the refined R/T and the refined RMS (pixels).
    """
    import cv2
    import jax
    import jax.numpy as jnp

    n_poses = len(data.obj_pts)
    n_pts = min(len(o) for o in data.obj_pts)
    obj = jnp.asarray(np.stack([o[:n_pts] for o in data.obj_pts]),
                      jnp.float32)                      # (P, N, 3)

    def _undistort(pts, K, D):
        # Ideal-pinhole observations re-projected through K (P=K). A zero/
        # absent distortion model is the identity here (synthetic rigs).
        if D is None or not np.any(np.abs(np.asarray(D)) > 0):
            return pts.reshape(-1, 2)
        und = cv2.undistortPoints(
            np.asarray(pts, np.float64).reshape(-1, 1, 2),
            np.asarray(K, np.float64), np.asarray(D, np.float64),
            P=np.asarray(K, np.float64))
        return und.reshape(-1, 2).astype(np.float32)

    cam_np = np.stack([_undistort(c[:n_pts], stereo.cam_K, stereo.cam_dist)
                       for c in data.cam_pts])
    cam = jnp.asarray(cam_np, jnp.float32)
    prj = jnp.asarray(np.stack(
        [_undistort(q[:n_pts], stereo.proj_K, stereo.proj_dist)
         for q in data.proj_pts]), jnp.float32)
    cam_K = jnp.asarray(stereo.cam_K, jnp.float32)
    proj_K = jnp.asarray(stereo.proj_K, jnp.float32)

    # Init: stereo from OpenCV; per-pose extrinsics from solvePnP.
    rvec0, _ = cv2.Rodrigues(np.asarray(stereo.R, np.float64))
    x0 = [np.asarray(rvec0, np.float32).reshape(3),
          np.asarray(stereo.T, np.float32).reshape(3)]
    for i in range(n_poses):
        ok, rv, tv = cv2.solvePnP(
            np.asarray(data.obj_pts[i][:n_pts], np.float64),
            np.asarray(cam_np[i], np.float64),  # undistorted, dist = None
            np.asarray(stereo.cam_K, np.float64), None)
        if not ok:
            raise RuntimeError(f"solvePnP failed for pose {i}")
        x0.append(np.asarray(rv, np.float32).reshape(3))
        x0.append(np.asarray(tv, np.float32).reshape(3))
    x0 = jnp.concatenate([jnp.asarray(v) for v in x0])

    from .ops.registration import exp_so3 as rodrigues

    def project(K, X):
        uvw = X @ K.T
        return uvw[..., :2] / jnp.maximum(uvw[..., 2:3], 1e-9)

    hi = jax.lax.Precision.HIGHEST

    def residuals(x):
        R_st = rodrigues(x[0:3])
        t_st = x[3:6]
        res = []
        for i in range(n_poses):
            o = 6 + 6 * i
            R_i = rodrigues(x[o:o + 3])
            t_i = x[o + 3:o + 6]
            Xc = jnp.einsum("ij,nj->ni", R_i, obj[i], precision=hi) + t_i
            Xp = jnp.einsum("ij,nj->ni", R_st, Xc, precision=hi) + t_st
            res.append((project(cam_K, Xc) - cam[i]).reshape(-1))
            res.append((project(proj_K, Xp) - prj[i]).reshape(-1))
        return jnp.concatenate(res)

    @jax.jit
    def lm(x0):
        def step(carry, _):
            x, lam = carry
            r = residuals(x)
            J = jax.jacfwd(residuals)(x)
            H = J.T @ J
            g = J.T @ r
            dx = jnp.linalg.solve(
                H + lam * jnp.eye(H.shape[0], dtype=H.dtype), g)
            x_new = x - dx
            better = jnp.sum(residuals(x_new) ** 2) < jnp.sum(r ** 2)
            x = jnp.where(better, x_new, x)
            lam = jnp.where(better, lam * 0.5, lam * 4.0)
            return (x, lam), None

        (x, _), _ = jax.lax.scan(step, (x0, jnp.float32(1e-3)), None,
                                 length=iterations)
        r = residuals(x).reshape(-1, 2)
        # cv2.stereoCalibrate convention: RMS over point-OBSERVATIONS of
        # the 2-D reprojection error magnitude (not over scalar
        # components, which would read sqrt(2) lower).
        rms = jnp.sqrt(jnp.mean(jnp.sum(r ** 2, axis=1)))
        return x, rms

    # Sub-pixel refinement needs true fp32 everywhere — TPU matmuls
    # (projection X @ Kᵀ, JᵀJ, JᵀR, the solve) default to bf16 otherwise.
    with jax.default_matmul_precision("highest"):
        x, rms = lm(x0)
    R = np.asarray(rodrigues(x[0:3]))
    T = np.asarray(x[3:6])
    return StereoResult(stereo.cam_K, stereo.cam_dist, stereo.proj_K,
                        stereo.proj_dist, R, T, float(rms))


def calibrate_final(
    pose_dirs: list[str],
    output_mat: str | None = None,
    proj: ProjectorConfig = ProjectorConfig(),
    board: CheckerboardConfig = CheckerboardConfig(),
) -> tuple[Calibration, StereoResult]:
    """Full final calibration (`server/sl_system.py:329-417`): stereo solve on
    the selected poses, then the JAX ray-grid/light-plane precompute, then the
    reference-layout .mat artifact."""
    data = load_calib_data(pose_dirs, proj, board)
    if len(data.obj_pts) < 3:
        raise ValueError(
            f"chessboard detected in only {len(data.obj_pts)} poses; need >= 3")
    stereo = stereo_calibrate(data, proj)
    w, h = data.img_shape
    calib = make_calibration(
        stereo.cam_K, stereo.proj_K, stereo.R, stereo.T, h, w,
        proj_width=proj.width, proj_height=proj.height)
    if output_mat:
        os.makedirs(os.path.dirname(output_mat) or ".", exist_ok=True)
        save_calibration_mat(output_mat, calib)
    return calib, stereo
