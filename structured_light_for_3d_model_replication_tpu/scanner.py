"""Scan orchestration: capture workflows over the hardware abstractions.

Headless equivalent of the reference's L5/L3 capture machinery — the Tkinter
GUI's worker-thread workflows (`server/gui.py`) and ``SLSystem``'s
display-then-trigger loops (`server/sl_system.py:114-182,422-481`) — written
against the :mod:`.hw` interfaces so the same code drives a physical rig
(window projector + phone + ESP32) or the virtual one (:class:`~.hw.rig
.VirtualRig`). No UI thread: callers run it directly or on their own worker.

Workflows:

* :meth:`Scanner.capture_stack` — project the protocol-ordered frame stack
  (white, black, then col/row bit pattern+inverse pairs —
  `server/sl_system.py:133-150,436-470`), capturing one camera image per
  frame into ``{idx:02d}.png``. Where the reference aborts the whole scan on
  the FIRST capture timeout (`server/sl_system.py:468-471`), each frame is
  retried under a :class:`RetryPolicy` (deterministic backoff, re-projection
  before every retry) and verified on disk (a truncated upload is a failed
  capture, not a poison pill for the decoder); only an exhausted frame
  raises.
* :meth:`Scanner.capture_calibration_pose` — the same stack at the
  calibration dwell into ``calib/pose_N/`` (`server/sl_system.py:114-182`).
* :meth:`Scanner.auto_scan_360` — the flagship loop (`server/gui.py:686-773`):
  capture a stop, rotate, wait for DONE (warn-but-continue on timeout,
  `server/gui.py:760-762`), settle, repeat; with per-stop progress timing
  (`server/gui.py:727-731`), RESUME (stops whose folders already hold a full
  stack are skipped, `io/layout.completed_stops`) and per-stop failure
  containment: a stop that exhausts its capture attempts is recorded in the
  :class:`~.health.ScanHealthReport` and SKIPPED — the turntable still
  advances, so the remaining stops land at their correct angles and the
  downstream gates (`models/scan360`) bridge the ring across the hole.

Error taxonomy: every failure raises a :class:`~.health.ScanFault` subclass
(:class:`ScanAborted` for exhausted captures, :class:`~.hw.turntable
.TurntableError` for the serial layer) so orchestration can contain scan
faults without masking programming errors.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import time
import uuid
from typing import Callable

import numpy as np

from .config import ProjectorConfig
from .health import CaptureError, ScanHealthReport
from .io.layout import SessionLayout, frame_name
from .ops.patterns import pattern_stack_for
from .utils import events
from .utils.log import get_logger

log = get_logger(__name__)

SCAN_DWELL_MS = 200    # server/sl_system.py:465
CALIB_DWELL_MS = 250   # server/sl_system.py:172
SETTLE_S = 0.5         # server/gui.py:763
ROTATE_TIMEOUT_S = 10.0  # server/gui.py:760


class ScanAborted(CaptureError):
    """A frame capture failed after all retries — the stack is incomplete
    and unusable."""


@dataclasses.dataclass(frozen=True)
class ScanTimings:
    """Every wall-clock constant of the capture loop in one place, so
    chaos tests and :class:`~.hw.rig.VirtualRig` runs can shrink them to
    ~zero instead of sleeping real time. Defaults are the reference's."""

    scan_dwell_ms: int = SCAN_DWELL_MS      # server/sl_system.py:465
    calib_dwell_ms: int = CALIB_DWELL_MS    # server/sl_system.py:172
    settle_s: float = SETTLE_S              # server/gui.py:763
    rotate_timeout_s: float = ROTATE_TIMEOUT_S  # server/gui.py:760


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capture retry knobs. Backoff is DETERMINISTIC (no jitter): chaos
    schedules and their health reports replay bit-identically.

    ``frame_attempts`` is per-frame scope (a flaky frame is re-projected
    and re-captured in place); ``stop_attempts`` is per-stop scope (a stop
    whose frame exhausts its attempts is re-captured from the top that
    many times before the stop is declared failed).
    """

    frame_attempts: int = 3
    stop_attempts: int = 2
    backoff_s: float = 0.1
    backoff_factor: float = 2.0

    def backoff(self, attempt: int) -> float:
        """Delay before retry ``attempt + 1`` (attempt is 0-based)."""
        return self.backoff_s * self.backoff_factor ** attempt


@dataclasses.dataclass
class ScanProgress:
    """Per-stop timing surfaced to UIs (`server/gui.py:727-731`)."""

    stop: int
    total_stops: int
    elapsed_s: float
    avg_stop_s: float
    remaining_s: float


def frame_file_ok(path: str) -> bool:
    """Cheap on-disk verification of a captured frame: exists, non-empty,
    and the container's end-of-stream marker is present — a truncated
    upload (connection dropped mid-POST) fails here and is retried as a
    capture failure instead of crashing the decoder later.

    Sniffs CONTENT, not the extension: the phone cameras write the
    uploaded JPEG bytes verbatim to whatever path the protocol names
    (``{idx:02d}.png`` — `hw/camera.py`, `hw/command_server.py`), and the
    stack loader is equally content-agnostic. PNG needs its IEND chunk,
    JPEG its EOI marker; unknown containers pass on the size check alone.
    """
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    if size == 0:
        return False
    with open(path, "rb") as f:
        head = f.read(8)
        f.seek(max(0, size - 32))
        tail = f.read()
    if head.startswith(b"\x89PNG\r\n\x1a\n"):
        return b"IEND" in tail
    if head.startswith(b"\xff\xd8"):
        return b"\xff\xd9" in tail
    return True


class Scanner:
    def __init__(
        self,
        camera,
        projector,
        turntable=None,
        proj: ProjectorConfig = ProjectorConfig(),
        layout: SessionLayout | None = None,
        settle_s: float | None = None,
        timings: ScanTimings | None = None,
        retry: RetryPolicy | None = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.camera = camera
        self.projector = projector
        self.turntable = turntable
        self.proj = proj
        self.layout = layout or SessionLayout.today().ensure()
        self.timings = timings or ScanTimings()
        # settle_s kept as a direct override for existing callers; the
        # timings dataclass is the one source of defaults.
        if settle_s is not None:
            self.timings = dataclasses.replace(self.timings,
                                               settle_s=settle_s)
        self.retry = retry or RetryPolicy()
        self._sleep = sleep
        self._frames: np.ndarray | None = None

    @property
    def settle_s(self) -> float:
        return self.timings.settle_s

    def _pattern_frames(self) -> np.ndarray:
        if self._frames is None:
            self._frames = np.asarray(pattern_stack_for(self.proj))
        return self._frames

    # ------------------------------------------------------------------
    # Single-stop capture
    # ------------------------------------------------------------------

    def _capture_frame(self, frame: np.ndarray, path: str, dwell_ms: int,
                       stop_health=None) -> None:
        """One frame under the retry policy: project, capture, verify; on
        failure back off deterministically, re-project, retry. Raises
        :class:`ScanAborted` when the policy is exhausted."""
        for attempt in range(self.retry.frame_attempts):
            if attempt > 0:
                self._sleep(self.retry.backoff(attempt - 1))
            self.projector.show(frame, dwell_ms=dwell_ms)
            if self.camera.capture(path) and frame_file_ok(path):
                if attempt > 0 and stop_health is not None:
                    stop_health.retries += attempt
                return
            log.warning("capture attempt %d/%d failed (%s)", attempt + 1,
                        self.retry.frame_attempts, path)
            events.record(
                "capture_retry", severity="warning",
                message=f"attempt {attempt + 1}/"
                        f"{self.retry.frame_attempts} failed",
                frame=os.path.basename(path), attempt=attempt)
            if stop_health is not None:
                stop_health.faults.append(
                    f"{os.path.basename(path)}:attempt{attempt}")
        raise ScanAborted(
            f"capture failed after {self.retry.frame_attempts} attempts "
            f"({path})")

    def capture_stack(self, out_dir: str, dwell_ms: int | None = None,
                      ext: str = "png", stop_health=None) -> list[str]:
        """Project every protocol frame and capture it to
        ``out_dir/{idx:02d}.{ext}`` (1-based numbering like the reference's
        `{idx:02d}` scheme, `server/sl_system.py:436-451`). ``dwell_ms``
        defaults to ``timings.scan_dwell_ms``."""
        if dwell_ms is None:
            dwell_ms = self.timings.scan_dwell_ms
        os.makedirs(out_dir, exist_ok=True)
        frames = self._pattern_frames()
        paths = []
        for i, frame in enumerate(frames):
            path = os.path.join(out_dir, frame_name(i + 1, ext))
            self._capture_frame(frame, path, dwell_ms,
                                stop_health=stop_health)
            paths.append(path)
        return paths

    def capture_scan(self, name: str, dwell_ms: int | None = None
                     ) -> str:
        """One scan folder under ``scans/`` (`SLSystem.capture_scan`,
        `server/sl_system.py:422-481`). Returns the folder path."""
        out = self.layout.scan_dir(name)
        self.capture_stack(out, dwell_ms=dwell_ms)
        log.info("scan %s captured (%d frames)", name,
                 self.proj.n_frames)
        return out

    def capture_calibration_pose(self, pose: int,
                                 dwell_ms: int | None = None) -> str:
        """One checkerboard pose under ``calib/pose_N/``
        (`SLSystem.capture_calibration`, `server/sl_system.py:114-182`).
        ``dwell_ms`` defaults to ``timings.calib_dwell_ms``."""
        out = self.layout.pose_dir(pose)
        self.capture_stack(out, dwell_ms=self.timings.calib_dwell_ms
                           if dwell_ms is None else dwell_ms)
        log.info("calibration pose %d captured", pose)
        return out

    # ------------------------------------------------------------------
    # Auto 360°
    # ------------------------------------------------------------------

    def _capture_stop(self, out: str, dwell_ms: int, stop_health) -> bool:
        """One stop under the per-stop retry scope. True on success; False
        when the stop is declared failed (recorded, never raised — the 360°
        loop skips it and keeps going)."""
        for stop_attempt in range(self.retry.stop_attempts):
            stop_health.stop_attempts = stop_attempt + 1
            try:
                self.capture_stack(out, dwell_ms=dwell_ms,
                                   stop_health=stop_health)
                return True
            except CaptureError as e:
                log.warning("stop capture attempt %d/%d failed: %s",
                            stop_attempt + 1, self.retry.stop_attempts, e)
        stop_health.status = "failed"
        # Scrub the partial stack: a folder with SOME frames would be
        # picked up by downstream folder scans (`cli/scan_360.has_frames`)
        # and crash the ragged np.stack — and resume treats any incomplete
        # folder as "recapture me" either way.
        removed = 0
        for ext in ("png", "jpg", "jpeg", "bmp"):
            for f in glob.glob(os.path.join(out, f"*.{ext}")):
                try:
                    os.remove(f)
                    removed += 1
                except OSError:
                    pass
        if removed:
            log.info("scrubbed %d partial frames from failed stop %s",
                     removed, out)
        return False

    def auto_scan_360(
        self,
        base_name: str,
        degrees_per_turn: float = 30.0,
        turns: int = 12,
        dwell_ms: int | None = None,
        resume: bool = True,
        on_progress: Callable[[ScanProgress], None] | None = None,
        health: ScanHealthReport | None = None,
        scan_id: str | None = None,
        on_stop: Callable[[int, str], None] | None = None,
    ) -> list[str]:
        """The flagship capture loop (`server/gui.py:686-773`). Returns the
        list of per-stop folders (``{base}_{angle}deg_scan``) that hold a
        COMPLETE stack — a stop that exhausts its retry budget is recorded
        in ``health``, skipped, and excluded from the return value (the
        turntable still advances past it). Raises :class:`ScanAborted` only
        when EVERY stop failed.

        Without a turntable the rotation is skipped entirely and the caller
        is expected to turn the object — the reference's "Simulation mode"
        prompt (`server/gui.py:690-693`) maps to passing a
        :class:`~.hw.turntable.SimulatedTurntable`.

        Resume contract: rotations are RELATIVE, and the loop still rotates
        through skipped stops, so a resumed session recaptures missing stops
        at the correct angles iff the turntable starts at the 0° home
        position (re-home the table — or restart the virtual rig, whose
        simulated table boots at 0°).

        ``on_stop`` is the STREAMING hook (docs/STREAMING.md): called with
        ``(stop_index, folder)`` the moment a stop's complete stack is on
        disk (captured or resumed) — feed it to a
        `stream.IncrementalSession` to fuse stops while the turntable is
        still moving. Consumer failures are CONTAINED (logged + journaled);
        a broken preview pipeline must never abort a 20-minute capture.
        """
        health = health if health is not None else ScanHealthReport()
        scan_id = scan_id or uuid.uuid4().hex[:12]
        health.scan_id = scan_id
        done_before = set(
            self.layout.completed_stops(base_name, degrees_per_turn,
                                        self.proj.n_frames)
            if resume else [])
        t0 = time.monotonic()
        stops = []
        captured = 0
        events.record("scan_started", scan_id=scan_id, base=base_name,
                      turns=turns, step_deg=degrees_per_turn)
        for i in range(turns):
            angle = i * degrees_per_turn
            out = self.layout.stop_dir(base_name, degrees_per_turn, angle)
            rec = health.stop(i, angle_deg=angle)
            # Correlation context: every event (and ScanFault) out of this
            # stop's capture — frame retries, exhausted stops — carries
            # the scan_id + stop index into the flight journal.
            with events.context(scan_id=scan_id, stop=i):
                landed = False
                if out in done_before:
                    log.info("stop %d/%d (%.0f°) already complete — "
                             "resumed past", i + 1, turns, angle)
                    rec.status = "resumed"
                    stops.append(out)
                    landed = True
                elif self._capture_stop(out, dwell_ms, rec):
                    captured += 1
                    stops.append(out)
                    landed = True
                else:
                    log.error("stop %d/%d (%.0f°) failed after %d stop "
                              "attempts — skipping (degraded ring)", i + 1,
                              turns, angle, self.retry.stop_attempts)
                    events.record(
                        "stop_failed", severity="error",
                        message=f"stop {i} exhausted "
                                f"{self.retry.stop_attempts} attempts",
                        angle_deg=angle)

                if landed and on_stop is not None:
                    try:
                        on_stop(i, out)
                    except Exception as e:
                        # Containment: the streaming consumer (fusion,
                        # previews) is best-effort relative to capture.
                        log.warning("on_stop consumer failed at stop %d:"
                                    " %s", i, e)
                        events.record("stream_consumer_failed",
                                      severity="warning", message=str(e),
                                      exc_type=type(e).__name__)

                if on_progress is not None:
                    elapsed = time.monotonic() - t0
                    avg = elapsed / max(captured, 1)
                    remaining = avg * sum(
                        1 for j in range(i + 1, turns)
                        if self.layout.stop_dir(base_name, degrees_per_turn,
                                                j * degrees_per_turn)
                        not in done_before)
                    on_progress(ScanProgress(i + 1, turns, elapsed, avg,
                                             remaining))

                if i < turns - 1 and self.turntable is not None:
                    self.turntable.rotate(degrees_per_turn)
                    if not self.turntable.wait_for_done(
                            self.timings.rotate_timeout_s):
                        log.warning("rotation %d DONE timeout — continuing",
                                    i)
                        events.record("rotate_timeout", severity="warning",
                                      angle_deg=angle)
                        health.rotate_timeouts += 1
                    self._sleep(self.timings.settle_s)
        if not stops:
            raise ScanAborted(
                f"auto 360 failed: all {turns} stops exhausted their "
                f"capture attempts")
        if health.failed_stops:
            health.note("auto 360 degraded: stops %s failed and were "
                        "skipped", health.failed_stops)
        log.info("auto 360 complete: %d/%d stops (%d captured, %d resumed, "
                 "%d failed) in %.1fs", len(stops), turns, captured,
                 len(done_before & set(stops)), len(health.failed_stops),
                 time.monotonic() - t0)
        events.record("scan_finished", scan_id=scan_id,
                      stops_ok=len(stops),
                      stops_failed=len(health.failed_stops),
                      elapsed_s=round(time.monotonic() - t0, 3))
        return stops
