"""Scan orchestration: capture workflows over the hardware abstractions.

Headless equivalent of the reference's L5/L3 capture machinery — the Tkinter
GUI's worker-thread workflows (`server/gui.py`) and ``SLSystem``'s
display-then-trigger loops (`server/sl_system.py:114-182,422-481`) — written
against the :mod:`.hw` interfaces so the same code drives a physical rig
(window projector + phone + ESP32) or the virtual one (:class:`~.hw.rig
.VirtualRig`). No UI thread: callers run it directly or on their own worker.

Workflows:

* :meth:`Scanner.capture_scan` — project the protocol-ordered frame stack
  (white, black, then col/row bit pattern+inverse pairs —
  `server/sl_system.py:133-150,436-470`), capturing one camera image per
  frame into ``{idx:02d}.png``; abort the scan if any capture times out
  (`server/sl_system.py:468-471`).
* :meth:`Scanner.capture_calibration_pose` — the same stack at the
  calibration dwell into ``calib/pose_N/`` (`server/sl_system.py:114-182`).
* :meth:`Scanner.auto_scan_360` — the flagship loop (`server/gui.py:686-773`):
  capture a stop, rotate, wait for DONE (warn-but-continue on timeout,
  `server/gui.py:760-762`), 0.5 s settle, repeat; with per-stop progress
  timing (elapsed / avg / remaining, `server/gui.py:727-731`) and RESUME —
  stops whose folders already hold a full stack are skipped
  (`io/layout.completed_stops`), which the reference cannot do.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable

import numpy as np

from .config import ProjectorConfig
from .io.layout import SessionLayout, frame_name
from .ops.patterns import pattern_stack_for
from .utils.log import get_logger

log = get_logger(__name__)

SCAN_DWELL_MS = 200    # server/sl_system.py:465
CALIB_DWELL_MS = 250   # server/sl_system.py:172
SETTLE_S = 0.5         # server/gui.py:763
ROTATE_TIMEOUT_S = 10.0  # server/gui.py:760


class ScanAborted(RuntimeError):
    """A frame capture timed out — the stack is incomplete and unusable."""


@dataclasses.dataclass
class ScanProgress:
    """Per-stop timing surfaced to UIs (`server/gui.py:727-731`)."""

    stop: int
    total_stops: int
    elapsed_s: float
    avg_stop_s: float
    remaining_s: float


class Scanner:
    def __init__(
        self,
        camera,
        projector,
        turntable=None,
        proj: ProjectorConfig = ProjectorConfig(),
        layout: SessionLayout | None = None,
        settle_s: float = SETTLE_S,
    ):
        self.camera = camera
        self.projector = projector
        self.turntable = turntable
        self.proj = proj
        self.layout = layout or SessionLayout.today().ensure()
        self.settle_s = settle_s
        self._frames: np.ndarray | None = None

    def _pattern_frames(self) -> np.ndarray:
        if self._frames is None:
            self._frames = np.asarray(pattern_stack_for(self.proj))
        return self._frames

    # ------------------------------------------------------------------
    # Single-stop capture
    # ------------------------------------------------------------------

    def capture_stack(self, out_dir: str, dwell_ms: int = SCAN_DWELL_MS,
                      ext: str = "png") -> list[str]:
        """Project every protocol frame and capture it to
        ``out_dir/{idx:02d}.{ext}`` (1-based numbering like the reference's
        `{idx:02d}` scheme, `server/sl_system.py:436-451`)."""
        os.makedirs(out_dir, exist_ok=True)
        frames = self._pattern_frames()
        paths = []
        for i, frame in enumerate(frames):
            self.projector.show(frame, dwell_ms=dwell_ms)
            path = os.path.join(out_dir, frame_name(i + 1, ext))
            if not self.camera.capture(path):
                raise ScanAborted(
                    f"capture timed out on frame {i + 1}/{len(frames)} "
                    f"({path})")
            paths.append(path)
        return paths

    def capture_scan(self, name: str, dwell_ms: int = SCAN_DWELL_MS
                     ) -> str:
        """One scan folder under ``scans/`` (`SLSystem.capture_scan`,
        `server/sl_system.py:422-481`). Returns the folder path."""
        out = self.layout.scan_dir(name)
        self.capture_stack(out, dwell_ms=dwell_ms)
        log.info("scan %s captured (%d frames)", name,
                 self.proj.n_frames)
        return out

    def capture_calibration_pose(self, pose: int,
                                 dwell_ms: int = CALIB_DWELL_MS) -> str:
        """One checkerboard pose under ``calib/pose_N/``
        (`SLSystem.capture_calibration`, `server/sl_system.py:114-182`)."""
        out = self.layout.pose_dir(pose)
        self.capture_stack(out, dwell_ms=dwell_ms)
        log.info("calibration pose %d captured", pose)
        return out

    # ------------------------------------------------------------------
    # Auto 360°
    # ------------------------------------------------------------------

    def auto_scan_360(
        self,
        base_name: str,
        degrees_per_turn: float = 30.0,
        turns: int = 12,
        dwell_ms: int = SCAN_DWELL_MS,
        resume: bool = True,
        on_progress: Callable[[ScanProgress], None] | None = None,
    ) -> list[str]:
        """The flagship capture loop (`server/gui.py:686-773`). Returns the
        list of per-stop folders (``{base}_{angle}deg_scan``).

        Without a turntable the rotation is skipped entirely and the caller
        is expected to turn the object — the reference's "Simulation mode"
        prompt (`server/gui.py:690-693`) maps to passing a
        :class:`~.hw.turntable.SimulatedTurntable`.

        Resume contract: rotations are RELATIVE, and the loop still rotates
        through skipped stops, so a resumed session recaptures missing stops
        at the correct angles iff the turntable starts at the 0° home
        position (re-home the table — or restart the virtual rig, whose
        simulated table boots at 0°).
        """
        done_before = set(
            self.layout.completed_stops(base_name, degrees_per_turn,
                                        self.proj.n_frames)
            if resume else [])
        t0 = time.monotonic()
        stops = []
        captured = 0
        for i in range(turns):
            angle = i * degrees_per_turn
            out = self.layout.stop_dir(base_name, degrees_per_turn, angle)
            if out in done_before:
                log.info("stop %d/%d (%.0f°) already complete — resumed past",
                         i + 1, turns, angle)
            else:
                self.capture_stack(out, dwell_ms=dwell_ms)
                captured += 1
            stops.append(out)

            if on_progress is not None:
                elapsed = time.monotonic() - t0
                avg = elapsed / max(captured, 1)
                remaining = avg * sum(
                    1 for j in range(i + 1, turns)
                    if self.layout.stop_dir(base_name, degrees_per_turn,
                                            j * degrees_per_turn)
                    not in done_before)
                on_progress(ScanProgress(i + 1, turns, elapsed, avg,
                                         remaining))

            if i < turns - 1 and self.turntable is not None:
                self.turntable.rotate(degrees_per_turn)
                if not self.turntable.wait_for_done(ROTATE_TIMEOUT_S):
                    log.warning("rotation %d DONE timeout — continuing", i)
                time.sleep(self.settle_s)
        log.info("auto 360 complete: %d stops (%d captured, %d resumed) "
                 "in %.1fs", turns, captured, len(done_before & set(stops)),
                 time.monotonic() - t0)
        return stops
