"""Configuration layer.

The reference keeps its knobs in a constants module (`server/config.py:10-30`)
plus ~30 Tk variables (`server/gui.py:27-83`). Here the same surface is a set of
frozen dataclasses so configs are hashable (usable as jit static args) and
serializable. A `PROCESSING_BACKEND` switch selects the compute path, as
required by BASELINE.json: "jax_tpu" (default) or "numpy_cv2" (the oracle).
"""

from __future__ import annotations

import dataclasses
import datetime
import math
import os

# Backend switch (BASELINE.json: PROCESSING_BACKEND in {'numpy_cv2', 'jax_tpu'}).
PROCESSING_BACKEND = os.environ.get("SL_PROCESSING_BACKEND", "jax_tpu")

VALID_BACKENDS = ("jax_tpu", "numpy_cv2")


@dataclasses.dataclass(frozen=True)
class ProjectorConfig:
    """Projector geometry; mirrors reference `server/config.py:16-22`."""

    width: int = 1920
    height: int = 1080
    # Second display sits to the right of the primary one.
    offset_x: int = 1920
    offset_y: int = 0
    brightness: int = 200
    # Pattern downsampling factor (reference D_SAMPLE_PROJ, applied at
    # `server/sl_system.py:144-146`): finest `downsample` bits are dropped.
    downsample: int = 1

    @property
    def col_bits(self) -> int:
        """Bits needed to code width/downsample coarse columns.

        Downsampling reduces the BIT COUNT (the reference's D_SAMPLE_PROJ
        projects coarser stripes and hence fewer planes,
        `server/sl_system.py:52-54,144-146`): 1920 @ D=2 -> ceil(log2(960)) =
        10 bits, giving the 42-frame stacks BASELINE.json describes.
        """
        return int(math.ceil(math.log2(math.ceil(self.width / self.downsample))))

    @property
    def row_bits(self) -> int:
        return int(math.ceil(math.log2(math.ceil(self.height / self.downsample))))

    @property
    def n_frames(self) -> int:
        """2 refs (white, black) + (pattern, inverse) per bit for cols + rows.

        1920x1080 @ D=1 -> 2 + 2*11 + 2*11 = 46 (`server/sl_system.py:52-54`);
        @ D=2 -> 2 + 2*10 + 2*10 = 42 (the BASELINE.json configuration).
        """
        return 2 + 2 * self.col_bits + 2 * self.row_bits


@dataclasses.dataclass(frozen=True)
class DecodeConfig:
    """Per-pixel validity-mask thresholds.

    Two variants exist in the reference and both must be supported (§7 of
    SURVEY.md): the adaptive one (`server/sl_system.py:526-535`) and the fixed
    one (`multi_point_cloud_process.py:36-38`).
    """

    mode: str = "adaptive"  # "adaptive" | "fixed"
    # adaptive: white > white_factor * percentile(black, black_percentile)
    #           AND (white-black) > contrast_frac * max(white-black)
    white_factor: float = 1.5
    black_percentile: float = 95.0
    contrast_frac: float = 0.05
    # fixed: white > white_thresh AND (white-black) > contrast_thresh
    white_thresh: float = 40.0
    contrast_thresh: float = 10.0


@dataclasses.dataclass(frozen=True)
class TriangulationConfig:
    """Ray-plane intersection options.

    The reference triangulates against column planes only — `row_map` is
    computed but never used (`server/sl_system.py:624-629`). "col" reproduces
    that; "row" triangulates against row planes instead, and "both" fuses the
    two independent ray-plane depth estimates by inverse variance (sensitivity
    to a one-index plane step). wPlaneRow is already part of the calibration
    container (`server/sl_system.py:403,410`); the reference just never uses it.
    """

    plane_axis: str = "col"  # "col" | "row" | "both"
    denom_eps: float = 1e-6
    # Reject points behind the camera or absurdly far.
    min_t: float = 0.0
    max_t: float = 1e5


@dataclasses.dataclass(frozen=True)
class CheckerboardConfig:
    """Calibration target; reference `server/config.py:24-27` (7x7 @ 35 mm)."""

    cols: int = 7
    rows: int = 7
    square_mm: float = 35.0


@dataclasses.dataclass(frozen=True)
class TurntableConfig:
    """360° schedule; reference `server/gui.py:79-80` defaults 12 x 30°."""

    turns: int = 12
    degrees_per_turn: float = 30.0
    baud: int = 115200
    done_timeout_s: float = 10.0
    settle_s: float = 0.5


@dataclasses.dataclass(frozen=True)
class MergeConfig:
    """Registration/merge knobs; reference `server/processing.py` defaults."""

    voxel_size: float = 0.02
    ransac_iters: int = 100_000
    ransac_confidence: float = 0.999
    icp_iters: int = 30
    sor_neighbors: int = 20
    sor_std_ratio: float = 2.0
    use_pose_graph: bool = False  # loop-closure LM variant (Old/360Merge.py)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Meshing knobs; reference `server/processing.py:184-310`."""

    method: str = "poisson"  # "poisson" | "ball_pivot"
    poisson_depth: int = 8  # grid = 2**depth per axis; guard like ref's >16
    density_trim_quantile: float = 0.02
    normal_orientation: str = "radial"  # "radial" | "tangent" | "camera"
    bpa_radius_multipliers: tuple = (1.0, 2.0, 4.0)
    # Deep (sparse, depth > 8) path defaults, recorded here like every
    # other MeshConfig field (this dataclass documents the meshing knob
    # surface; the LIVE knobs are mesh_from_cloud(preconditioner=,
    # extraction=) and `cli mesh --preconditioner/--extraction`). See
    # ops/poisson_sparse.PoissonParams / ops/marching.extract_sparse.
    poisson_preconditioner: str = "additive"  # | vcycle|chebyshev|jacobi
    extraction_engine: str = "auto"  # "auto" | "host" | "device"


@dataclasses.dataclass(frozen=True)
class CaptureConfig:
    """Capture-loop timing; reference `server/sl_system.py:465,103` etc."""

    frame_dwell_ms: int = 200
    calib_dwell_ms: int = 250
    capture_timeout_s: float = 20.0
    http_port: int = 5000
    push_port: int = 8765  # Android host push-mode port


def dated_output_root(base: str = ".") -> str:
    """Reference layout root `{dd_mm_YYYY}_3Dscan` (`server/config.py:10`)."""
    stamp = datetime.date.today().strftime("%d_%m_%Y")
    return os.path.join(base, f"{stamp}_3Dscan")


@dataclasses.dataclass(frozen=True)
class SystemConfig:
    projector: ProjectorConfig = ProjectorConfig()
    decode: DecodeConfig = DecodeConfig()
    triangulation: TriangulationConfig = TriangulationConfig()
    checkerboard: CheckerboardConfig = CheckerboardConfig()
    turntable: TurntableConfig = TurntableConfig()
    merge: MergeConfig = MergeConfig()
    mesh: MeshConfig = MeshConfig()
    capture: CaptureConfig = CaptureConfig()
    backend: str = PROCESSING_BACKEND

    def __post_init__(self):
        if self.backend not in VALID_BACKENDS:
            raise ValueError(
                f"backend must be one of {VALID_BACKENDS}, got {self.backend!r}"
            )


DEFAULT = SystemConfig()
