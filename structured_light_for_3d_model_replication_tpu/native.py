"""ctypes bindings for the native runtime layer (``native/``).

The split follows the hardware: data-parallel math lives on the TPU
(ops/*), while the pointer-chasing host work — file codecs, union-find
clustering, MST normal orientation, ball-pivoting front propagation, grid
KNN — lives in C++ (the role Open3D's C++ core plays for the reference).

The shared library is built lazily with ``make`` on first use and cached;
every caller has a pure-Python/JAX fallback, so the native layer is an
accelerator, never a hard dependency. ``available()`` reports status;
``SL_NATIVE=0`` disables it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from .utils.log import get_logger

log = get_logger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libslnative.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=300)
        return True
    except Exception as e:
        log.warning("native build failed (%s); using Python fallbacks", e)
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SL_NATIVE", "1") == "0":
            return None
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            log.warning("native library load failed: %s", e)
            return None
        _declare(lib)
        _lib = lib
        return _lib


def _declare(lib: ctypes.CDLL) -> None:
    i32, i64, u8 = ctypes.c_int32, ctypes.c_int64, ctypes.c_uint8
    f32p = ctypes.POINTER(ctypes.c_float)
    i32p = ctypes.POINTER(i32)
    u8p = ctypes.POINTER(u8)
    lib.sl_ply_write.argtypes = [ctypes.c_char_p, i64, f32p, u8p, f32p, i32]
    lib.sl_ply_write.restype = i32
    lib.sl_stl_write.argtypes = [ctypes.c_char_p, i64, f32p, i64, i32p]
    lib.sl_stl_write.restype = i32
    lib.sl_dbscan_labels.argtypes = [i32, i32, i32p, u8p, u8p, i32p]
    lib.sl_dbscan_labels.restype = i32
    lib.sl_mst_orient_normals.argtypes = [i32, i32, f32p, f32p, i32p, u8p,
                                          f32p]
    lib.sl_mst_orient_normals.restype = i32
    lib.sl_connected_components.argtypes = [i32, i32, i32p, u8p, i32p]
    lib.sl_connected_components.restype = i32
    lib.sl_ball_pivot.argtypes = [i32, f32p, f32p, f32p, i32, i32p, i32,
                                  i32]
    lib.sl_ball_pivot.restype = i32
    lib.sl_grid_knn.argtypes = [i32, f32p, i32, f32p, i32, ctypes.c_float,
                                i32, i32p, f32p]
    lib.sl_grid_knn.restype = None


def available() -> bool:
    return _load() is not None


def _f32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# Wrappers (None-safe: callers check available() or catch RuntimeError)
# ---------------------------------------------------------------------------


def ply_write(path: str, points, colors=None, normals=None,
              binary: bool = True) -> None:
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    pts = _f32(points)
    n = len(pts)
    col = None if colors is None else np.ascontiguousarray(colors, np.uint8)
    nrm = None if normals is None else _f32(normals)
    rc = lib.sl_ply_write(
        path.encode(), n, _ptr(pts, ctypes.c_float),
        None if col is None else _ptr(col, ctypes.c_uint8),
        None if nrm is None else _ptr(nrm, ctypes.c_float),
        1 if binary else 0)
    if rc != 0:
        raise IOError(f"native PLY write failed ({rc}): {path}")


def stl_write(path: str, vertices, faces) -> None:
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    v = _f32(vertices)
    f = np.ascontiguousarray(faces, np.int32)
    rc = lib.sl_stl_write(path.encode(), len(v), _ptr(v, ctypes.c_float),
                          len(f), _ptr(f, ctypes.c_int32))
    if rc != 0:
        raise IOError(f"native STL write failed ({rc}): {path}")


def dbscan_labels(nbr_idx, nbr_ok, core) -> tuple[np.ndarray, int]:
    """(labels (n,), n_clusters) from a KNN graph; -1 = noise."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    idx = np.ascontiguousarray(nbr_idx, np.int32)
    ok = np.ascontiguousarray(nbr_ok, np.uint8)
    co = np.ascontiguousarray(core, np.uint8)
    n, k = idx.shape
    labels = np.empty(n, np.int32)
    count = lib.sl_dbscan_labels(n, k, _ptr(idx, ctypes.c_int32),
                                 _ptr(ok, ctypes.c_uint8),
                                 _ptr(co, ctypes.c_uint8),
                                 _ptr(labels, ctypes.c_int32))
    return labels, int(count)


def mst_orient_normals(points, normals, nbr_idx, nbr_ok,
                       seed_dir=(0.0, 0.0, 0.0)) -> tuple[np.ndarray, int]:
    """Consistently oriented copy of ``normals`` + component count."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    pts = _f32(points)
    nrm = _f32(normals).copy()
    idx = np.ascontiguousarray(nbr_idx, np.int32)
    ok = np.ascontiguousarray(nbr_ok, np.uint8)
    sd = _f32(np.asarray(seed_dir, np.float32))
    n, k = idx.shape
    comps = lib.sl_mst_orient_normals(
        n, k, _ptr(pts, ctypes.c_float), _ptr(nrm, ctypes.c_float),
        _ptr(idx, ctypes.c_int32), _ptr(ok, ctypes.c_uint8),
        _ptr(sd, ctypes.c_float))
    return nrm, int(comps)


def connected_components(nbr_idx, nbr_ok) -> tuple[np.ndarray, int]:
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    idx = np.ascontiguousarray(nbr_idx, np.int32)
    ok = np.ascontiguousarray(nbr_ok, np.uint8)
    n, k = idx.shape
    labels = np.empty(n, np.int32)
    count = lib.sl_connected_components(n, k, _ptr(idx, ctypes.c_int32),
                                        _ptr(ok, ctypes.c_uint8),
                                        _ptr(labels, ctypes.c_int32))
    return labels, int(count)


def ball_pivot(points, normals, radii,
               max_hole_edges: int = 12) -> np.ndarray:
    """(T, 3) int32 triangle indices from ball-pivoting reconstruction.

    ``max_hole_edges`` fills residual boundary loops up to that edge count
    after the pivot passes (0 disables; large openings — e.g. the unseen
    bottom of a turntable scan — always stay open)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    pts = _f32(points)
    nrm = _f32(normals)
    rad = _f32(np.sort(np.asarray(radii, np.float32)))
    n = len(pts)
    cap = max(4 * n, 1024)
    for _ in range(2):
        out = np.empty((cap, 3), np.int32)
        rc = lib.sl_ball_pivot(n, _ptr(pts, ctypes.c_float),
                               _ptr(nrm, ctypes.c_float),
                               _ptr(rad, ctypes.c_float), len(rad),
                               _ptr(out, ctypes.c_int32), cap,
                               int(max_hole_edges))
        if rc >= 0:
            return out[:rc].copy()
        cap = -rc  # buffer was too small; retry with the reported need
    raise RuntimeError("ball_pivot: buffer negotiation failed")


def grid_knn(points, k, queries=None, cell_size: float = 0.0,
             exclude_self: bool | None = None):
    """Exact host KNN: (d2 (m,k), idx (m,k)); idx -1 where fewer than k."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    pts = _f32(points)
    self_query = queries is None
    q = pts if self_query else _f32(queries)
    if exclude_self is None:
        exclude_self = self_query
    m, n = len(q), len(pts)
    idx = np.empty((m, k), np.int32)
    d2 = np.empty((m, k), np.float32)
    lib.sl_grid_knn(n, _ptr(pts, ctypes.c_float), m,
                    _ptr(q, ctypes.c_float), k, cell_size,
                    1 if exclude_self else 0, _ptr(idx, ctypes.c_int32),
                    _ptr(d2, ctypes.c_float))
    return d2, idx
