"""Vertex-colored iso-surface extraction from a TSDF brick volume.

Reuses the device-side sparse marching machinery of
:mod:`..ops.marching_jax` — the (M, 729) corner-frame assembly, the
prefix-sum cell compaction and the static tet tables — with two TSDF
additions:

* an **observation mask**: a cell emits triangles only when ALL 8 of its
  corners carry integration weight (> ``min_weight``). Unobserved space
  never interpolates, so open scenes extract as open surfaces instead of
  the phantom walls a fill value would mint — the non-watertight
  capability the Poisson path cannot offer.
* **color interpolation**: per-channel (M, 729) corner frames ride the
  same gathers as χ, and each triangle vertex linearly interpolates RGB
  with the exact ``t`` of its position — per-vertex color for free.

Capacities are bucketed with a caller-settable FLOOR (``cells_floor`` /
``tris_floor``): the streaming previewer pins generous floors once so a
growing model re-uses one compiled program per phase instead of minting
a fresh one each time the active-cell count crosses a power of two
(zero steady-state compiles, the stream acceptance bar). The host tail
(outward vote, weight trim, weld) mirrors ``extract_sparse_jax``, with
the weld carrying first-occurrence vertex colors through the dedup.
"""

from __future__ import annotations

import functools

import numpy as _np

import jax
import jax.numpy as jnp

from ..io.stl import TriangleMesh
from ..ops import marching_jax as mj
from ..ops import tsdf as tsdf_ops
from ..ops.marching import _CORNERS
from ..ops.poisson_sparse import BS
from ..utils.log import get_logger

log = get_logger(__name__)


@jax.jit
def _phase_frames(chi, weight, rgb, nbr, block_valid, min_weight):
    """Corner frames for χ / weight / RGB + the observation-masked
    active-cell mask. χ uses the own-brick clamp fallback (no spurious
    crossings, same as the marching extractors); weight falls back to 0
    (an absent neighbor is UNOBSERVED — its cells must not emit)."""
    m = chi.shape[0]
    nb8 = mj._nb8_table(nbr)
    rows = nb8[:, jnp.asarray(mj._CASE9, jnp.int32)]        # (M, 729)
    src = jnp.asarray(mj._SRC9, jnp.int32)[None, :]
    present = rows < m

    def frame(vals, clamp_fallback: bool):
        pad = jnp.concatenate([vals, jnp.zeros((1,) + vals.shape[1:],
                                               vals.dtype)])
        v = pad[rows, src]
        if clamp_fallback:
            fb = vals[:, jnp.asarray(mj._CLAMP9, jnp.int32)]
        else:
            fb = jnp.zeros_like(v)
        if v.ndim == 3:
            return jnp.where(present[..., None], v, fb)
        return jnp.where(present, v, fb)

    c9 = frame(chi, True)
    w9 = frame(weight, False)
    rgb9 = frame(rgb, True)

    inside = c9 > 0.0
    observed = w9 > min_weight
    any_in = all_in = all_obs = None
    for j in range(8):
        cidx = jnp.asarray(mj._CIDX[:, j], jnp.int32)
        blk = inside[:, cidx]
        obs = observed[:, cidx]
        any_in = blk if any_in is None else (any_in | blk)
        all_in = blk if all_in is None else (all_in & blk)
        all_obs = obs if all_obs is None else (all_obs & obs)
    active = any_in & ~all_in & all_obs & block_valid[:, None]
    return c9, rgb9, active, jnp.sum(active.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("T",))
def _phase_triangles_colored(cells, rgb9, weight, block_coords, T: int):
    """`marching_jax._phase_triangles` with RGB interpolation: returns
    (tris (T, 3, 3) grid coords, colors (T, 3, 3), density (T,) = the
    cell's own integration weight)."""
    bk, ck, v8, case = cells
    iso = jnp.float32(0.0)
    nt = jnp.asarray(mj._NTRI, jnp.int32)[case]              # (K, 6)
    tv = (jnp.arange(2, dtype=jnp.int32)[None, None, :]
          < nt[:, :, None]).reshape(-1)
    rank = jnp.cumsum(tv.astype(jnp.int32)) - 1
    dest = jnp.where(tv, jnp.minimum(rank, T), T)
    src = jnp.zeros((T + 1,), jnp.int32).at[dest].set(
        jnp.arange(tv.shape[0], dtype=jnp.int32), mode="drop")[:T]

    k = src // 12
    t = (src % 12) // 2
    j = src % 2
    caseT = case[k, t]
    epc = jnp.asarray(mj._EP_CUBE, jnp.int32)[t, caseT, j]   # (T, 3, 2)
    v8k = v8[k]                                              # (T, 8)
    va = jnp.take_along_axis(v8k, epc[:, :, 0], axis=1)
    vb = jnp.take_along_axis(v8k, epc[:, :, 1], axis=1)
    # Per-cell 8-corner colors, gathered once per triangle row.
    c8 = rgb9[bk[k][:, None], jnp.asarray(mj._CIDX, jnp.int32)[ck[k]]]
    ca = jnp.take_along_axis(c8, epc[:, :, 0, None], axis=1)  # (T, 3, 3)
    cb = jnp.take_along_axis(c8, epc[:, :, 1, None], axis=1)
    base = (block_coords[bk[k]] * BS
            + jnp.asarray(mj._CELL_XYZ, jnp.int32)[ck[k]])
    corners = jnp.asarray(_CORNERS, jnp.int32)
    pa = (base[:, None, :] + corners[epc[:, :, 0]]).astype(jnp.float32)
    pb = (base[:, None, :] + corners[epc[:, :, 1]]).astype(jnp.float32)
    denom = vb - va
    safe = jnp.abs(denom) > 1e-12
    tt = jnp.where(safe, (iso - va) / jnp.where(safe, denom, 1.0), 0.5)
    tt = jnp.clip(tt, 0.0, 1.0).astype(jnp.float32)
    tris = pa + tt[..., None] * (pb - pa)
    cols = ca + tt[..., None] * (cb - ca)
    flip = jnp.asarray(mj._FLIP, jnp.bool_)[t, caseT, j]
    tris = jnp.where(flip[:, None, None], tris[:, ::-1, :], tris)
    cols = jnp.where(flip[:, None, None], cols[:, ::-1, :], cols)
    dens = weight[bk[k], ck[k]]
    return tris, cols, dens


def _weld_colored(tris: _np.ndarray, cols: _np.ndarray,
                  decimals: int = 6):
    """`marching.weld` with first-occurrence vertex colors carried
    through the rounded-vertex dedup."""
    flat = tris.reshape(-1, 3)
    key = _np.round(flat, decimals)
    uniq, first, inv = _np.unique(key, axis=0, return_index=True,
                                  return_inverse=True)
    faces = inv.reshape(-1, 3).astype(_np.int32)
    good = ((faces[:, 0] != faces[:, 1]) & (faces[:, 1] != faces[:, 2])
            & (faces[:, 0] != faces[:, 2]))
    vcols = cols.reshape(-1, 3)[first]
    return uniq.astype(_np.float32), faces[good], vcols


def extract_colored(state, params, origin, voxel_size,
                    min_weight: float = 0.0,
                    quantile_trim: float = 0.0,
                    cells_floor: int = 4096,
                    tris_floor: int = 8192,
                    with_colors: bool = True) -> TriangleMesh:
    """TSDF volume → welded vertex-colored :class:`TriangleMesh`.

    ``min_weight`` masks under-observed corners (0.0 = any observation
    counts); ``quantile_trim`` drops the lowest-weight triangle fraction
    (the Poisson density-trim semantics applied to integration weight).
    ``cells_floor``/``tris_floor`` pin the compaction capacities — pass
    generous floors from steady-state callers to avoid bucket-growth
    recompiles. Empty volumes return an empty mesh, never raise."""
    nbr, block_valid = tsdf_ops.neighbor_table(state, params)
    c9, rgb9, active, count = _phase_frames(
        state.tsdf, state.weight, state.rgb, nbr, block_valid,
        jnp.float32(min_weight))
    n_cells = int(count)
    if n_cells == 0:
        return TriangleMesh(_np.zeros((0, 3), _np.float32),
                            _np.zeros((0, 3), _np.int32))
    K = mj._bucket(n_cells, floor=cells_floor)
    if K > cells_floor:
        # Bounded re-bucket (a compile) — steady-state callers should
        # raise their floor to cover the surface they expect.
        log.debug("TSDF extraction outgrew cells_floor=%d (%d active "
                  "cells) — re-bucketed to %d", cells_floor, n_cells, K)
    cell_ids = mj._phase_cells(active, K)
    count_d, cells = mj._phase_count(c9, cell_ids, jnp.float32(0.0), K)
    nt = int(count_d)
    if nt == 0:
        return TriangleMesh(_np.zeros((0, 3), _np.float32),
                            _np.zeros((0, 3), _np.int32))
    T = mj._bucket(nt, floor=tris_floor)
    if T > tris_floor:
        log.debug("TSDF extraction outgrew tris_floor=%d (%d "
                  "triangles) — re-bucketed to %d", tris_floor, nt, T)
    tris_d, cols_d, dens_d = _phase_triangles_colored(
        cells, rgb9, state.weight, state.brick_coords, T)
    # Full-capacity readback, host slice — NOT the device per-nt slice
    # `marching_jax` uses: that mints a (cheap) compile per distinct
    # count, which the zero-steady-state-compile bar of the streaming
    # previewer forbids. The floors bound the readback (a few MB), and
    # the batch path amortizes it over one call.
    tris = _np.asarray(tris_d, _np.float64)[:nt]
    cols = _np.asarray(cols_d, _np.float64)[:nt]
    dens_np = _np.asarray(dens_d)[:nt]

    # Global outward decision (one all-or-nothing flip — the device
    # winding is already field-consistent, same as extract_sparse_jax).
    cen = tris.mean(axis=1)
    nrm = _np.cross(tris[:, 1] - tris[:, 0], tris[:, 2] - tris[:, 0])
    vote = _np.einsum("ij,ij->i", nrm, cen - cen.mean(axis=0))
    if _np.sum(_np.sign(vote)) <= 0:
        tris = tris[:, ::-1, :]
        cols = cols[:, ::-1, :]

    if quantile_trim > 0.0 and tris.shape[0]:
        keep = dens_np > _np.quantile(dens_np, quantile_trim)
        tris = tris[keep]
        cols = cols[keep]

    verts, faces, vcols = _weld_colored(tris, cols)
    # Samples live at voxel CENTERS: grid coord v maps to world
    # origin + (v + 0.5) * voxel.
    world = (verts + _np.float32(0.5)) * _np.float32(voxel_size) \
        + _np.asarray(origin, _np.float32)
    mesh = TriangleMesh(world.astype(_np.float32), faces)
    if with_colors:
        mesh.vertex_colors = _np.clip(_np.round(vcols), 0,
                                      255).astype(_np.uint8)
    if len(mesh.faces):
        mesh.compute_vertex_normals()
    return mesh
