"""fusion/ — the fused TSDF scene representation.

The second scene representation next to Poisson→marching (ROADMAP:
"fused TSDF/Gaussian backend"): a sparse brick-grid truncated-signed-
distance volume fused incrementally on device (`ops/tsdf.py`, donated
in-place integration, optional pallas combine kernel), extracted as a
VERTEX-COLORED mesh through the marching-tets compaction machinery
(`fusion/extract.py` over `ops/marching_jax.py`'s tables).

What it unlocks that the Poisson path cannot:

* **color** — the reference pipeline's per-point RGB survives into the
  mesh (`io/ply.write_ply_mesh` carries it out);
* **open scenes** — unobserved space extracts as NOTHING (observation-
  masked cells), not a hallucinated watertight closure;
* **incremental previews** — `fusion/preview.TSDFPreviewMesher`
  integrates each streaming stop into the persistent volume instead of
  re-solving the whole model (bench [11] `tsdf_preview_s`).

Dispatch: ``models/meshing.mesh_from_cloud(representation="tsdf")`` for
batch clouds (sign from oriented normals), ``StreamParams(
representation="tsdf")`` / the serve session option for streaming (sign
from the per-stop viewing rays). The Poisson path stays the watertight
print path and the NumPy TSDF oracle (`ops/tsdf.integrate_oracle`) pins
device parity. docs/MESHING.md and docs/STREAMING.md cover semantics.

The Gaussian/appearance tier (splat rendering on top of this SDF, per
Gaussian-Plus-SDF SLAM) is the remaining ROADMAP item above this layer.
"""

from ..ops.tsdf import TSDFParams, TSDFState, integrate_oracle
from .extract import extract_colored
from .preview import TSDFPreviewMesher
from .volume import TSDFVolume, fit_bounds

__all__ = [
    "TSDFParams",
    "TSDFState",
    "TSDFPreviewMesher",
    "TSDFVolume",
    "extract_colored",
    "fit_bounds",
    "integrate_oracle",
]
