"""TSDFVolume: the host-side handle on a device TSDF brick volume.

Owns the (donated) device state plus the world mapping (origin + voxel
size), logs capacity overflows (degrade to holes, never an error — the
model_cap rule of `stream/session.py` applied to bricks), and fronts the
two integration flavors:

* :meth:`integrate_from_camera` — streaming stops: inward directions
  along the viewing rays from the stop's camera center;
* :meth:`integrate_oriented` — batch clouds: inward = −oriented normal
  (the `models/meshing` dispatch path).

``fit_bounds`` picks the world mapping the way `ops/poisson.
normalize_points` does (isotropic padded cube), quantized so the brick
grid covers it exactly.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..io.stl import TriangleMesh
from ..ops import tsdf as tsdf_ops
from ..utils.log import get_logger
from .extract import extract_colored

log = get_logger(__name__)


def fit_bounds(lo, hi, params: tsdf_ops.TSDFParams,
               pad_frac: float = 0.15):
    """(origin, voxel_size) covering the padded isotropic cube around
    [lo, hi] with the volume's ``2^grid_depth`` voxels."""
    lo = np.asarray(lo, np.float32)
    hi = np.asarray(hi, np.float32)
    extent = float(np.max(hi - lo))
    extent = extent if extent > 1e-12 else 1.0
    side = extent * (1.0 + 2.0 * float(pad_frac))
    voxel = side / params.resolution
    center = 0.5 * (lo + hi)
    origin = center - 0.5 * side
    return origin.astype(np.float32), float(voxel)


class TSDFVolume:
    """One TSDF scene: fixed params, fixed world mapping, fused state."""

    def __init__(self, params: tsdf_ops.TSDFParams, origin, voxel_size,
                 use_pallas: bool | None = None):
        self.params = params
        self.origin = np.asarray(origin, np.float32)
        self.voxel_size = float(voxel_size)
        self.use_pallas = use_pallas
        self._state = tsdf_ops.init_state(params)
        self.n_bricks = 0
        self.n_dropped = 0
        self.stops_integrated = 0

    @classmethod
    def from_bounds(cls, params: tsdf_ops.TSDFParams, lo, hi,
                    pad_frac: float = 0.15,
                    use_pallas: bool | None = None) -> "TSDFVolume":
        origin, voxel = fit_bounds(lo, hi, params, pad_frac=pad_frac)
        return cls(params, origin, voxel, use_pallas=use_pallas)

    # ------------------------------------------------------------------

    def _integrate(self, points, colors, valid, dirs) -> int:
        self._state, n_wanted = tsdf_ops.integrate(
            self._state, self.params, points, colors, valid, dirs,
            self.origin, self.voxel_size, use_pallas=self.use_pallas)
        n_wanted = int(n_wanted)
        cap = int(self.params.max_bricks)
        if n_wanted > cap and self.n_dropped == 0:
            log.warning(
                "TSDF brick pool overflowed max_bricks=%d (%d wanted) — "
                "excess bricks dropped (holes in the extracted surface)",
                cap, n_wanted)
        self.n_dropped = max(self.n_dropped, n_wanted - cap)
        self.n_bricks = min(n_wanted, cap)
        self.stops_integrated += 1
        return n_wanted

    def integrate_from_camera(self, points, colors, valid, cam) -> int:
        """Fuse one stop observed from camera center ``cam`` (3,); all
        arrays world-frame (device or host). Returns wanted bricks."""
        dirs = tsdf_ops.camera_dirs(jnp.asarray(points, jnp.float32),
                                    jnp.asarray(cam, jnp.float32))
        return self._integrate(points, colors, valid, dirs)

    def integrate_oriented(self, points, colors, valid, normals) -> int:
        """Fuse an oriented cloud: inward = −(outward normal)."""
        dirs = -jnp.asarray(normals, jnp.float32)
        return self._integrate(points, colors, valid, dirs)

    # ------------------------------------------------------------------

    def extract(self, min_weight: float = 0.0, quantile_trim: float = 0.0,
                cells_floor: int = 4096, tris_floor: int = 8192,
                with_colors: bool = True) -> TriangleMesh:
        return extract_colored(
            self._state, self.params, self.origin, self.voxel_size,
            min_weight=min_weight, quantile_trim=quantile_trim,
            cells_floor=cells_floor, tris_floor=tris_floor,
            with_colors=with_colors)

    def to_dense(self):
        """Dense (tsdf, weight, rgb) host arrays (oracle layout)."""
        return tsdf_ops.state_to_dense(self._state, self.params)

    def stats(self) -> dict:
        return {
            "bricks": int(self.n_bricks),
            "max_bricks": int(self.params.max_bricks),
            "bricks_dropped": int(self.n_dropped),
            "stops_integrated": int(self.stops_integrated),
            "voxel_size": round(self.voxel_size, 6),
            "grid_depth": int(self.params.grid_depth),
        }
