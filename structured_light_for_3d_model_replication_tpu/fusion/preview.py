"""TSDF progressive previews: incremental integration, no re-solve.

The coarse-Poisson previewer (`stream/preview.py`) re-solves the WHOLE
running model from scratch at every stop — correct, but the per-stop
cost is a full screened-Poisson CG no matter how little the model
changed. This mesher is the TSDF alternative the ROADMAP names: each
stop's pose-transformed points are INTEGRATED into a persistent volume
(one donated scatter — `ops/tsdf.integrate`), and the preview is a
direct iso-surface extraction of what the volume already holds. Work
per stop is proportional to the stop, not the model, and the preview
carries per-vertex COLOR the Poisson path discards.

Static-shape discipline: integration is one program per (params,
view_cap) — the stop count never appears — and extraction pins its
compaction capacities to fixed floors (``extract.cells_floor``), so a
growing surface re-uses the same compiled programs. After the first
preview the whole chain is pure execution (the bench [11] bar: zero
steady-state compiles across stops 5–24).

The volume's world mapping is fixed lazily at the FIRST stop (padded
bbox, `volume.fit_bounds`) — later stops of a turntable ring orbit the
same object, so a generous pad covers the full sweep; out-of-volume
points are dropped by the integrate op's bounds mask (logged via the
brick-overflow counter, never an error).
"""

from __future__ import annotations

import numpy as np

from ..io.stl import TriangleMesh
from ..ops.tsdf import TSDFParams
from ..utils.log import get_logger
from .volume import TSDFVolume

log = get_logger(__name__)


class TSDFPreviewMesher:
    """Drop-in for `stream.preview.PreviewMesher` with per-stop
    incremental integration (`IncrementalSession` feeds each fused
    stop through :meth:`integrate_stop`; ``__call__`` keeps the
    Poisson previewer's signature and ignores the model buffer —
    the volume IS the running model)."""

    def __init__(self, voxel_size_hint: float,
                 params: TSDFParams = TSDFParams(max_bricks=4096),
                 min_weight: float = 0.0, quantile_trim: float = 0.0,
                 pad_frac: float = 0.6, cells_floor: int = 16384,
                 tris_floor: int = 65536):
        # voxel_size_hint caps resolution: the volume never resolves
        # finer than the session's merge voxel (there is no data below
        # it) — bounds permitting, fit_bounds may choose coarser.
        self.voxel_size_hint = float(voxel_size_hint)
        self.params = params
        self.min_weight = float(min_weight)
        self.quantile_trim = float(quantile_trim)
        self.pad_frac = float(pad_frac)
        self.cells_floor = int(cells_floor)
        self.tris_floor = int(tris_floor)
        self.volume: TSDFVolume | None = None
        self.last_cg_iters = None    # interface parity with PreviewMesher

    # ------------------------------------------------------------------

    def _ensure_volume(self, moved_np: np.ndarray) -> None:
        if self.volume is not None:
            return
        lo = moved_np.min(axis=0) if moved_np.shape[0] else \
            np.zeros(3, np.float32)
        hi = moved_np.max(axis=0) if moved_np.shape[0] else \
            np.ones(3, np.float32)
        vol = TSDFVolume.from_bounds(self.params, lo, hi,
                                     pad_frac=self.pad_frac)
        if vol.voxel_size < self.voxel_size_hint:
            vol.voxel_size = self.voxel_size_hint
        self.volume = vol
        log.debug("TSDF preview volume: voxel %.4f, %d^3 voxels, "
                  "%d brick slots", vol.voxel_size,
                  self.params.resolution, self.params.max_bricks)

    def integrate_stop(self, moved, colors, valid, cam,
                       moved_np: np.ndarray | None = None) -> int:
        """Fuse one pose-transformed stop (device arrays straight from
        the session's ``_fuse_fn``); ``cam`` is the stop's camera center
        in the model frame. ``moved_np`` (the host copy the session
        already pulled for the covis gate) seeds the lazy bounds."""
        if self.volume is None:
            ref = moved_np if moved_np is not None \
                else np.asarray(moved)[np.asarray(valid)]
            self._ensure_volume(np.asarray(ref, np.float32))
        return self.volume.integrate_from_camera(moved, colors, valid,
                                                 cam)

    # ------------------------------------------------------------------

    def __call__(self, model_pts, model_valid) -> TriangleMesh:
        """Extract the current surface (arguments accepted for
        PreviewMesher signature parity; the volume holds the model)."""
        del model_pts, model_valid
        if self.volume is None:
            return self.empty()
        return self.volume.extract(
            min_weight=self.min_weight, quantile_trim=self.quantile_trim,
            cells_floor=self.cells_floor, tris_floor=self.tris_floor)

    @staticmethod
    def empty() -> TriangleMesh:
        return TriangleMesh(vertices=np.zeros((0, 3), np.float32),
                            faces=np.zeros((0, 3), np.int32))

    def stats(self) -> dict:
        return self.volume.stats() if self.volume is not None else {}
