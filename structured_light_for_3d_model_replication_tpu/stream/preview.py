"""Progressive mesh previews at static shapes (zero steady-state compiles).

The batch mesher (`models/meshing.mesh_from_cloud`) compacts to the
cloud's exact point count on host, so every preview of a growing model
would mint a fresh XLA program — a recompile per stop, exactly what the
streaming acceptance bar forbids. This mesher keeps every device shape
FIXED across the session: the running model is stratified-sampled into
``points`` static slots (invalid slots masked, never compacted), normals
are estimated and radially oriented in one jitted program over those
slots, and the screened-Poisson solve runs at a constant ``depth`` — so
the whole preview chain compiles once at the first preview and is pure
execution for every stop after. Extraction stays the host NumPy
marching-tets oracle (`ops/marching.extract`), whose data-dependent
output size costs no compiles.

Fidelity schedule (docs/STREAMING.md): per-stop previews are COARSE
(default depth 6 — blocky but instant feedback while the turntable is
still moving); the full-depth watertight mesh is built once at
finalize through the ordinary batch mesher.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..io.stl import TriangleMesh
from ..ops import marching, pointcloud, poisson
from ..utils.log import get_logger

log = get_logger(__name__)


@functools.lru_cache(maxsize=None)
def _sample_normals_fn(m: int, k: int):
    """Model buffer → ``m`` preview slots + oriented normals, one launch.

    Stratified selection keeps the sample spatially spread however the
    model grew; normals orient outward from the valid centroid (the
    reference's radial trick — previews have no camera to orient by)."""

    def run(pts, valid):
        idx, v = pointcloud.stratified_indices(valid, m)
        p = jnp.where(v[:, None], pts[idx], 0.0)
        nv = jnp.maximum(jnp.sum(v.astype(jnp.float32)), 1.0)
        center = jnp.sum(p, axis=0) / nv
        normals, n_ok = pointcloud.estimate_normals(p, valid=v, k=k)
        normals = pointcloud.orient_normals(p, normals, center,
                                            outward=True)
        return p, normals, v & n_ok

    return jax.jit(run)


class PreviewMesher:
    """Coarse progressive previews of a running fused model.

    One instance per session; ``__call__`` takes the session's model
    buffer (static ``cap`` slots + valid mask) and returns a host
    :class:`TriangleMesh`. All device work happens at shapes fixed by
    ``(points, depth)`` — stop count never appears in a shape.

    Warm start: the previous preview's χ grid seeds the next solve's CG
    (`poisson.reconstruct(x0=...)`). Between stops the model barely
    moves, so the residual stop fires after a fraction of the cold
    iteration count — ``last_cg_iters`` exposes the measured count (the
    warm-start assertion in tests/test_stream.py). The grid's world
    mapping is recomputed per call, so a shifting bbox only WEAKENS the
    guess (CG converges from any x0), never corrupts it.
    """

    def __init__(self, points: int = 8192, depth: int = 6,
                 quantile_trim: float = 0.05, normals_k: int = 16,
                 cg_iters: int = 60):
        if depth > 8:
            raise ValueError(f"preview depth {depth} > 8: previews ride "
                             "the dense Poisson grid (keep them coarse; "
                             "finalize owns the deep solve)")
        self.points = int(points)
        self.depth = int(depth)
        self.quantile_trim = float(quantile_trim)
        self.normals_k = int(normals_k)
        self.cg_iters = int(cg_iters)
        self.last_cg_iters: int | None = None
        self._last_chi = None
        self._last_grid = None

    def __call__(self, model_pts, model_valid) -> TriangleMesh:
        p, normals, v = _sample_normals_fn(self.points, self.normals_k)(
            model_pts, model_valid)
        grid, iters = poisson.reconstruct(
            p, normals, valid=v, depth=self.depth,
            cg_iters=self.cg_iters, x0=self._last_chi, return_iters=True)
        self.last_cg_iters = iters
        self._last_chi = grid.chi
        self._last_grid = grid
        mesh = marching.extract(grid, quantile_trim=self.quantile_trim)
        log.debug("preview: %d sample slots -> %d faces (depth %d, "
                  "%d CG iters)", self.points, len(mesh.faces),
                  self.depth, iters)
        return mesh

    @property
    def last_chi(self):
        """Latest preview χ grid — finalize warm-starts from it when the
        final solve runs at the SAME dense depth (stream/session.py)."""
        return self._last_chi

    @property
    def last_grid(self):
        """Latest preview grid WITH its world normalization — the
        sparse finalize (final_depth > 8) threads it into
        ``reconstruct_sparse(x0=…)``, which world-aligns it onto its
        internal coarse solve (docs/MESHING.md § warm starts)."""
        return self._last_grid

    @staticmethod
    def empty() -> TriangleMesh:
        return TriangleMesh(vertices=np.zeros((0, 3), np.float32),
                            faces=np.zeros((0, 3), np.int32))


def make_previewer(params):
    """StreamParams → the session's previewer: the incremental TSDF
    mesher (``representation="tsdf"`` — the default — and
    ``"archival"``, whose previews are the same TSDF lane with only the
    FINAL artifact going through Poisson; `fusion/preview.py`), the
    coarse-Poisson re-solver (``"poisson"``, the legacy lane) or the
    splat appearance lane (``"splat"``, `splat/preview.py` — the TSDF
    mesher plus rendered novel views). All share the
    ``__call__(model_pts, model_valid) -> TriangleMesh`` contract."""
    if params.representation in ("tsdf", "splat", "archival"):
        from ..ops.tsdf import TSDFParams

        tparams = TSDFParams(grid_depth=params.tsdf_grid_depth,
                             max_bricks=params.tsdf_max_bricks,
                             carve_steps=params.tsdf_carve_steps)
        hint = params.tsdf_voxel_scale * params.merge.voxel_size
        if params.representation == "splat":
            from ..splat.model import SplatParams
            from ..splat.preview import SplatPreviewMesher

            return SplatPreviewMesher(
                voxel_size_hint=hint, params=tparams,
                splat_params=SplatParams(capacity=params.splat_cap),
                fit_iters=params.splat_fit_iters,
                max_frames=params.splat_max_frames,
                fit_pixels=params.splat_fit_pixels,
                render_sizes=params.splat_render_sizes,
                quantile_trim=params.preview_trim)
        from ..fusion.preview import TSDFPreviewMesher

        return TSDFPreviewMesher(
            voxel_size_hint=hint, params=tparams,
            quantile_trim=params.preview_trim)
    return PreviewMesher(points=params.preview_points,
                         depth=params.preview_depth,
                         quantile_trim=params.preview_trim)
