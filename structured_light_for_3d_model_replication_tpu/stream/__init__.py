"""Streaming incremental reconstruction — the SLAM-shaped pipeline.

A batch 360° scan is final the moment each stop lands, yet the batch
pipeline (`models/scan360`) only merges after stop 24 — perceived latency
is the whole scan. This package is the incremental version the retrieved
SLAM line of work points at (S3-SLAM's incremental sparse-encoding
updates, AGS's codec-assisted covisibility gating, RGBD GS-ICP SLAM —
PAPERS.md): each stop is fused into a running model AS IT ARRIVES, the
pose graph is updated incrementally (new edge against the running anchor
set + a windowed local re-optimize instead of a full batch solve), a
cheap covisibility/novelty gate skips redundant stops before they cost
registration and fusion, and a progressive coarse-Poisson mesh preview
is emitted after every stop — first preview after stop 1, not stop 24.

Zero new steady-state compiles by construction: every device program an
:class:`~.session.IncrementalSession` launches is either one of the
batch pipeline's already-compiled programs reused at per-stop shapes
(`models/pipeline.reconstruct_batch_fn` B=1, `models/merge._preprocess_fn`
/ `_edge_fn`, the shared subsample) or a stream-local program with
static shapes independent of the stop count (the model-fuse scatter, the
fixed-window pose refine, the fixed-size preview mesher). After the
warm-up stops, adding a stop compiles nothing — asserted by compile
telemetry in tests and bench config [8].

Entry points: :class:`~.session.IncrementalSession` (in-process),
`serve/`'s multi-stop session API (``POST /session`` …, docs/SERVING.md),
``cli scan-360 --stream``, and `scanner.auto_scan_360(on_stop=…)` for
live capture. docs/STREAMING.md has the architecture and semantics.
"""

from .preview import PreviewMesher, make_previewer
from .session import IncrementalSession, StopResult, StreamParams
from .warmup import warm_session_programs

__all__ = [
    "IncrementalSession",
    "PreviewMesher",
    "StopResult",
    "StreamParams",
    "make_previewer",
    "warm_session_programs",
]
