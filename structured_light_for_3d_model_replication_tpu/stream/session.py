"""IncrementalSession: fuse a 360° scan one stop at a time.

The batch pipeline's math, re-staged so each stop is consumed the moment
it lands:

* **decode** — the same compiled batch program at B=1
  (`models/scan360.decode_stop`);
* **subsample** — the same shared stratified pass, stop axis of 1
  (`models/scan360.subsample_stop`);
* **register** — the same per-stop preprocess + per-edge programs the
  batch loop strategy runs (`models/merge.preprocess_registration_view`,
  `register_edge`), hint-chained and keyed identically, so a finalized
  incremental session reproduces the batch ring bit-for-bit on a clean
  scan (the parity bar in tests/test_stream.py);
* **pose update** — chain for the new stop, then a WINDOWED local
  re-optimize: the last `window` edges plus turntable-step prior edges
  run through the existing pose-graph LM at a fixed padded shape
  (compiled once, reused every stop) instead of a full batch solve;
* **fuse** — the stop's merge view is pose-transformed and voxel-merged
  into a fixed-capacity running model buffer in ONE donated-in/out
  program (static shapes: stop count never appears);
* **preview** — a coarse static-shape Poisson mesh of the running model
  after every stop (`stream/preview.py`) — first preview after stop 1.

**Covisibility/novelty gate** (AGS-style, PAPERS.md): before a stop pays
for registration and fusion, two cheap host-side voxel-overlap tests run
against what the session already holds — a camera-frame test against the
previous accepted stop (a stuck turntable re-captures the same view;
overlap ≈ 1) and a predicted-pose test against the fused model (a second
lap, or stops commanded denser than the geometry needs). A redundant
stop is SKIPPED: its decision is journaled (``stop_skipped_covisible``),
its pose is predicted from the ring consensus, and the next real stop
bridges across it exactly like the PR-3 degraded-ring path.

Zero steady-state compiles: every program above is either already
compiled by the batch path or compiled once at session warm-up with
shapes independent of the stop count — asserted via compile telemetry in
tests/test_stream.py and bench config [8].
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import health as health_mod
from ..config import DecodeConfig, TriangulationConfig
from ..io import ply as ply_io
from ..ops import pointcloud, posegraph, registration
from ..utils import events, trace
from ..utils.log import get_logger
from ..models import merge as merge_mod
from ..models import scan360 as scan360_mod
from .preview import make_previewer

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class StreamParams:
    """Streaming knobs on top of the batch merge parameters.

    Frozen/hashable (it keys compiled-program caches the same way
    `Scan360Params` does)."""

    merge: merge_mod.MergeParams = merge_mod.MergeParams()
    method: str = "posegraph"           # finalize pose solve
    view_cap: int = 131_072             # per-stop merge-view slots
    # PR-3 quality gates: per-stop decode coverage (skip-and-bridge) and
    # per-edge fitness/RMSE (consensus repair / down-weight) at finalize.
    # None = gates off (batch ungated semantics).
    gates: health_mod.QualityGates | None = None
    # Running fused model: static slot capacity of the voxel-merged
    # buffer previews sample from. Overflow degrades to a stratified
    # subset (logged), never a recompile.
    model_cap: int = 262_144
    # -- covisibility / novelty gate (AGS-style) -------------------------
    covis: bool = True
    # Predicted-pose overlap with the fused model above which a stop is
    # redundant (second lap / oversampled ring). Ring neighbors genuinely
    # share most of their view, so the default only fires on near-total
    # redundancy.
    covis_model_overlap: float = 0.995
    # Camera-frame overlap with the PREVIOUS accepted stop above which
    # the turntable did not advance (stuck table, duplicate upload).
    covis_duplicate_overlap: float = 0.98
    covis_voxel_scale: float = 2.0      # gate voxel = scale × merge voxel
    covis_min_points: int = 256         # below this the gate abstains
    # -- windowed local re-optimize --------------------------------------
    window: int = 6                     # edges in the local LM window
    window_iterations: int = 10
    # Prior-edge information scale relative to the window's measured
    # edges: the turntable-step consensus votes gently, smoothing a bad
    # live edge without overriding good ICP.
    window_prior_scale: float = 0.05
    # -- progressive previews --------------------------------------------
    preview_every: int = 1              # 0 disables previews
    preview_points: int = 8192
    preview_depth: int = 6
    preview_trim: float = 0.05
    # Scene representation for previews AND the final mesh dispatch
    # (docs/MESHING.md, docs/STREAMING.md): "tsdf" (DEFAULT) = the
    # integrate-don't-re-solve lane — incremental fused-volume previews
    # (fusion/, per-stop integration instead of a re-solve) and a
    # vertex-COLORED final mesh re-fused from the pose-graph-final
    # cloud, no Poisson solve anywhere; "archival" = the TSDF preview
    # lane but the FINAL artifact is the full-depth watertight Poisson
    # solve (the print/archive format, opt-in because it costs seconds
    # where the default costs a fraction of one); "poisson" = the
    # legacy lane — coarse re-solve previews (whose grids warm-start
    # the final solve) + the watertight print path; "splat" = the TSDF
    # lane PLUS the Gaussian appearance tier (splat/,
    # docs/RENDERING.md) — rendered novel-view previews next to the
    # mesh ones, fitted from the per-stop RGB the session already
    # decodes.
    representation: str = "tsdf"
    tsdf_voxel_scale: float = 2.0       # TSDF voxel = scale × merge voxel
    tsdf_grid_depth: int = 8
    tsdf_max_bricks: int = 4096
    # Free-space carving (ops/tsdf.py TSDFParams.carve_steps): 0 = the
    # historical bit-identical integrate; > 0 marches observed-empty
    # samples toward the camera so moving-sensor captures erase stale
    # surface (docs/MESHING.md).
    tsdf_carve_steps: int = 0
    # -- splat appearance tier (representation="splat") -------------------
    splat_cap: int = 8192               # splat slots on the TSDF shell
    splat_fit_iters: int = 40           # Adam steps per lazy scene build
    splat_max_frames: int = 8           # RGB frames kept for the fit
    splat_fit_pixels: int = 12288       # fit-resolution pixel budget
    # Allowed render resolutions (W, H): first is the default; the serve
    # render endpoint 400s anything else (each size is one compiled
    # program — an open set would mint compiles on demand).
    splat_render_sizes: tuple = ((384, 288),)
    # -- finalize ---------------------------------------------------------
    final_depth: int = 8
    final_trim: float = 0.0
    # Stop-count hint: with it the per-edge PRNG key schedule matches the
    # batch path's `split(key, n)` exactly (bit-parity on clean scans);
    # without it a generous schedule is pre-split and parity is
    # tolerance-level only.
    expected_stops: int | None = None
    max_stops: int = 256


@dataclasses.dataclass
class StopResult:
    """What happened to one submitted stop."""

    stop: int
    fused: bool
    reason: str                  # fused | skipped_coverage |
    #                              skipped_duplicate | skipped_covisible
    coverage: float
    overlap: float | None = None
    fitness: float | None = None
    rmse: float | None = None
    gap: int = 1
    preview: bool = False
    model_points: int = 0
    seconds: float = 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        for k in ("coverage", "overlap", "fitness", "rmse", "seconds"):
            if d[k] is not None:
                d[k] = round(float(d[k]), 4)
        return d


@dataclasses.dataclass
class FinalizeResult:
    cloud: ply_io.PointCloud
    poses: np.ndarray            # (max_label+1, 4, 4); skipped stops carry
    #                              their predicted pose, unseen stops I
    mesh: "object | None"        # TriangleMesh at final_depth, if built
    health: health_mod.ScanHealthReport
    stats: dict


# ---------------------------------------------------------------------------
# Stream-local compiled programs (static shapes, stop count never appears)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _fuse_fn(voxel: float, model_cap: int, view_cap: int):
    """Model ∪ one pose-transformed stop view → model, ONE launch.

    The model buffers are donated: in and out are the same (cap,) shapes,
    so XLA aliases them — the running model updates in place, the classic
    streaming donation win (sharding-readiness, docs/JAXLINT.md)."""

    def run(m_pts, m_col, m_val, pose, s_pts, s_col, s_val):
        moved = registration.transform_points(pose, s_pts)
        moved = jnp.where(s_val[:, None], moved, 0.0)
        allp = jnp.concatenate([m_pts, moved], axis=0)
        allc = jnp.concatenate([m_col, s_col], axis=0)
        allv = jnp.concatenate([m_val, s_val], axis=0)
        dp, dc, dv, _ = pointcloud.voxel_downsample(
            allp, voxel, valid=allv, attrs=allc, with_attrs=True)
        idx, v2 = pointcloud.stratified_indices(dv, model_cap)
        out_pts = jnp.where(v2[:, None], dp[idx], 0.0)
        out_col = jnp.where(v2[:, None], dc[idx], 0.0)
        return out_pts, out_col, v2, jnp.sum(dv.astype(jnp.int32)), moved

    return jax.jit(run, donate_argnums=(0, 1, 2),
                   in_shardings=None, out_shardings=None)


@functools.lru_cache(maxsize=None)
def _window_refine_fn(window: int, iterations: int):
    """Fixed-window pose-graph LM: the last ``window`` chain edges plus
    turntable-step prior edges, padded to a STATIC shape (zero-information
    padding edges constrain nothing), compiled once per (window,
    iterations) and reused every stop. Node 0 (the window anchor) is held
    fixed, so outputs are poses relative to the window start."""
    src = tuple(range(1, window + 1))
    dst = tuple(range(window))

    def run(edge_T, edge_info, prior_T, prior_info):
        poses0 = posegraph.chain_poses(edge_T)
        graph = posegraph.PoseGraph(
            poses0,
            jnp.asarray(src + src, jnp.int32),
            jnp.asarray(dst + dst, jnp.int32),
            jnp.concatenate([edge_T, prior_T], axis=0),
            jnp.concatenate([edge_info, prior_info], axis=0))
        return posegraph.optimize(graph, iterations=iterations)

    return jax.jit(run)


# ---------------------------------------------------------------------------
# Covisibility gate helpers (host-side: a few thousand points per stop)
# ---------------------------------------------------------------------------

_VOX_BITS = 21
_VOX_OFF = 1 << (_VOX_BITS - 1)


def _voxel_keys(pts: np.ndarray, voxel: float) -> np.ndarray:
    """Exact packed int64 voxel keys (21 signed bits per axis — ±1M
    voxels; beyond that the gate would abstain long before overflow)."""
    q = np.floor(pts / float(voxel)).astype(np.int64) + _VOX_OFF
    q = np.clip(q, 0, (1 << _VOX_BITS) - 1)
    return np.unique((q[:, 0] << (2 * _VOX_BITS))
                     | (q[:, 1] << _VOX_BITS) | q[:, 2])


def voxel_overlap(pts: np.ndarray, occupied: np.ndarray,
                  voxel: float) -> float:
    """Fraction of ``pts``'s occupied voxels already present in the
    sorted key array ``occupied`` — the covisibility measure."""
    if pts.shape[0] == 0 or occupied.size == 0:
        return 0.0
    keys = _voxel_keys(pts, voxel)
    return float(np.isin(keys, occupied, assume_unique=True).mean())


class _EdgeRec:
    """One incremental ring edge (device transform + host scalars)."""

    __slots__ = ("src", "dst", "gap", "T_dev", "T_np", "T_live", "fit",
                 "rmse", "info")

    def __init__(self, src, dst, gap, T_dev, fit, rmse, info):
        self.src = src
        self.dst = dst
        self.gap = gap
        self.T_dev = T_dev                     # raw measured (finalize)
        self.T_np = np.asarray(T_dev, np.float64)
        self.T_live = self.T_np                # possibly live-repaired
        self.fit = float(fit)
        self.rmse = float(rmse)
        self.info = np.asarray(info, np.float64)


class IncrementalSession:
    """Consume one decoded stop at a time; keep a fused model, live
    poses, and a progressive preview current throughout.

    Not thread-safe by itself — concurrent callers (serve sessions) hold
    a per-session lock. One session is one scan: ``finalize`` closes it.
    """

    def __init__(self, calib, col_bits: int, row_bits: int,
                 params: StreamParams = StreamParams(),
                 decode_cfg: DecodeConfig = DecodeConfig(),
                 tri_cfg: TriangulationConfig = TriangulationConfig(),
                 key=None, scan_id: str | None = None,
                 health: health_mod.ScanHealthReport | None = None):
        if params.method not in ("sequential", "posegraph"):
            raise ValueError(f"method must be 'sequential' or 'posegraph',"
                             f" got {params.method!r}")
        if params.representation not in ("poisson", "tsdf", "splat",
                                         "archival"):
            raise ValueError(f"representation must be 'poisson', 'tsdf', "
                             f"'splat' or 'archival', got "
                             f"{params.representation!r}")
        self.calib = calib
        self.col_bits = col_bits
        self.row_bits = row_bits
        self.params = params
        self.decode_cfg = decode_cfg
        self.tri_cfg = tri_cfg
        self._key = key if key is not None else jax.random.PRNGKey(0)
        self.scan_id = scan_id or f"stream-{id(self):x}"
        self.health = health if health is not None \
            else health_mod.ScanHealthReport()
        if self.health.scan_id is None:
            self.health.scan_id = self.scan_id
        self._keys = None            # per-edge PRNG schedule (first stop)
        self._n_pixels: int | None = None
        self._view_cap = self._m_reg = 0
        # Per-FUSED-stop state (parallel lists, index = fused order).
        self._labels: list[int] = []
        self._preps: list[tuple] = []
        self._subs: list[tuple] = []
        self._poses: list[np.ndarray] = []
        self._edges: list[_EdgeRec] = []
        self._hint = None
        self._consensus: np.ndarray | None = None
        # Skipped stops: label -> (reason, predicted pose).
        self._skipped: dict[int, tuple[str, np.ndarray]] = {}
        self._next_label = 0
        # Running fused model + host voxel occupancy for the covis gate.
        self._model: tuple | None = None      # (pts, col, val) device
        self._model_points = 0
        self._model_voxels = np.empty(0, np.int64)
        self._prev_cam_voxels = np.empty(0, np.int64)
        self._mesher = make_previewer(params)
        self._last_integrate_s = 0.0   # tsdf: this stop's fuse seconds
        self.preview = None
        self.preview_meta: dict = {}
        # Overload hook (serve/governor.py): while True, progressive
        # previews are skipped — the cheapest work to shed under load
        # (fusion and the final artifact are untouched; the last emitted
        # preview keeps serving). Flipped per stop by the serve layer.
        self.suppress_previews = False
        self._finalized = False
        self._t0 = time.monotonic()

    # -- properties --------------------------------------------------------

    @property
    def stops_fused(self) -> int:
        return len(self._labels)

    @property
    def stops_skipped(self) -> int:
        return len(self._skipped)

    @property
    def finalized(self) -> bool:
        return self._finalized

    def live_poses(self) -> np.ndarray:
        """Current (fused-stop) global poses — refined incrementally; the
        authoritative poses come from :meth:`finalize`."""
        return np.stack(self._poses) if self._poses else \
            np.zeros((0, 4, 4))

    def status_dict(self) -> dict:
        return {
            "scan_id": self.scan_id,
            "representation": self.params.representation,
            "stops_fused": self.stops_fused,
            "stops_skipped": self.stops_skipped,
            "skipped": {str(k): v[0] for k, v in self._skipped.items()},
            "model_points": int(self._model_points),
            "preview": dict(self.preview_meta) if self.preview_meta
            else None,
            "finalized": self._finalized,
        }

    # -- per-stop ingestion ------------------------------------------------

    def add_stop(self, stack, stop: int | None = None) -> StopResult:
        """Decode one (F, H, W) uint8 capture stack and fuse it (the
        in-process path; serve sessions decode through the batcher and
        call :meth:`add_decoded`)."""
        pts, cols, vals = scan360_mod.decode_stop(
            stack, self.calib, self.col_bits, self.row_bits,
            decode_cfg=self.decode_cfg, tri_cfg=self.tri_cfg)
        return self.add_decoded(pts, cols, vals, stop=stop,
                                frame_shape=stack.shape[1:3])

    def add_decoded(self, points, colors, valid,
                    stop: int | None = None,
                    coverage: float | None = None,
                    frame_shape: tuple | None = None) -> StopResult:
        """Fuse one stop's decoded dense arrays (device or host):
        ``points`` (P, 3) f32, ``colors`` (P, 3), ``valid`` (P,) bool.
        ``stop`` is the PHYSICAL stop label (strictly increasing;
        defaults to the next commanded index) — capture-failed stops the
        caller never submits show up as label gaps and bridge exactly
        like the batch degraded-ring path. ``coverage`` overrides the
        plain ``mean(valid)`` statistic — serve workers pass the
        pre-padding region's coverage so bucket padding never dilutes
        the gate. ``frame_shape`` is the dense arrays' (H, W) pixel
        layout — the splat appearance tier needs it to treat the stop
        as an RGB supervision frame (``add_stop`` and the serve worker
        pass it; without it the splat lane renders from fused DC colors
        only)."""
        if self._finalized:
            raise health_mod.StopQualityError(
                f"session {self.scan_id} is finalized")
        label = self._next_label if stop is None else int(stop)
        if label < self._next_label:
            raise ValueError(
                f"stop labels must be strictly increasing: got {label} "
                f"after {self._next_label - 1}")
        self._next_label = label + 1
        t0 = time.monotonic()
        with events.context(scan_id=self.scan_id, stop=label):
            res = self._ingest(label, points, colors, valid, coverage,
                               frame_shape)
        res.seconds = time.monotonic() - t0
        return res

    def _ingest(self, label: int, points, colors, valid,
                coverage: float | None = None,
                frame_shape: tuple | None = None) -> StopResult:
        p = self.params
        mp = p.merge
        points = jnp.asarray(points)
        if self._n_pixels is None:
            self._n_pixels = int(points.shape[0])
            self._view_cap, self._m_reg = scan360_mod.stop_view_sizes(
                scan360_mod.Scan360Params(merge=mp, view_cap=p.view_cap),
                self._n_pixels)
            n_keys = p.expected_stops if p.expected_stops else p.max_stops
            self._keys = jax.random.split(self._key, n_keys)
        elif int(points.shape[0]) != self._n_pixels:
            raise ValueError(
                f"stop {label} has {int(points.shape[0])} pixels; this "
                f"session is locked to {self._n_pixels}")

        if coverage is None:
            coverage = float(jnp.mean(
                jnp.asarray(valid).astype(jnp.float32)))
        rec = self.health.stop(label)
        rec.coverage = coverage

        # -- decode-coverage gate (PR-3 semantics: skip and bridge) -------
        if p.gates is not None and not p.gates.coverage_ok(coverage):
            rec.status = "dropped"
            events.record("stop_dropped", severity="warning",
                          message="decode coverage below gate",
                          coverage=round(coverage, 4),
                          min_coverage=p.gates.min_coverage)
            self._skipped[label] = ("skipped_coverage",
                                    self._predict_pose(label))
            return StopResult(stop=label, fused=False,
                              reason="skipped_coverage", coverage=coverage,
                              gap=self._gap_for(label))

        sub = scan360_mod.subsample_stop(
            points, jnp.asarray(colors), jnp.asarray(valid),
            self._view_cap, self._m_reg)
        sub_pts, sub_col, sub_val, reg_pts, reg_val = sub
        reg_np = np.asarray(reg_pts)[np.asarray(reg_val)]

        # -- covisibility / novelty gate ----------------------------------
        overlap = self._covis_overlap(label, reg_np)
        if overlap is not None:
            kind, value = overlap
            rec.status = kind
            events.record(
                "stop_skipped_covisible", severity="info",
                message=f"redundant stop ({kind})",
                overlap=round(value, 4), coverage=round(coverage, 4),
                threshold=(p.covis_duplicate_overlap
                           if kind == "skipped_duplicate"
                           else p.covis_model_overlap))
            self._skipped[label] = (kind, self._predict_pose(label))
            return StopResult(stop=label, fused=False, reason=kind,
                              coverage=coverage, overlap=value,
                              gap=self._gap_for(label),
                              model_points=self._model_points)

        # -- register against the running anchor --------------------------
        prep = merge_mod.preprocess_registration_view(reg_pts, reg_val, mp)
        fit = rmse = None
        gap = self._gap_for(label)
        if self._labels:
            edge = self._register_edge(label, prep, gap)
            fit, rmse = edge.fit, edge.rmse
            pose = self._poses[-1] @ edge.T_live
            self._edges.append(edge)
            self._update_consensus()
        else:
            pose = np.eye(4)
        self._labels.append(label)
        self._preps.append(prep)
        self._subs.append((sub_pts, sub_col, sub_val))
        self._poses.append(pose)
        if len(self._edges) >= 2:
            self._refine_window()

        # -- fuse into the running model ----------------------------------
        moved = self._fuse(sub_pts, sub_col, sub_val)
        if p.representation == "splat" and frame_shape is not None:
            # Appearance supervision (splat/preview.py): the stop's
            # DENSE RGB + valid mask and its registered pose join the
            # fit buffer — one strided host subsample, no device work
            # on the ingest path (the fit itself is lazy, at render
            # time). The stored pose is the stop's at-ingest estimate;
            # later window refinements shift it by less than the fit's
            # pixel tolerance.
            self._mesher.observe_frame(points, colors, valid,
                                       self._poses[-1], frame_shape)
        if p.covis:
            cam_keys = _voxel_keys(reg_np, self._covis_voxel())
            self._prev_cam_voxels = cam_keys
            mv = moved[np.asarray(sub_val)]
            self._model_voxels = np.union1d(
                self._model_voxels, _voxel_keys(mv, self._covis_voxel()))

        # -- progressive preview ------------------------------------------
        did_preview = self._maybe_preview(label)
        events.record("stop_fused", coverage=round(coverage, 4),
                      fitness=None if fit is None else round(fit, 4),
                      rmse=None if rmse is None else round(rmse, 4),
                      gap=gap, model_points=self._model_points)
        return StopResult(stop=label, fused=True, reason="fused",
                          coverage=coverage, fitness=fit, rmse=rmse,
                          gap=gap, preview=did_preview,
                          model_points=self._model_points)

    # -- gate internals ----------------------------------------------------

    def _covis_voxel(self) -> float:
        return self.params.covis_voxel_scale * self.params.merge.voxel_size

    def _gap_for(self, label: int) -> int:
        return label - self._labels[-1] if self._labels else 1

    def _predict_pose(self, label: int) -> np.ndarray:
        """Consensus-extrapolated global pose for a stop that was never
        registered (skipped) — reporting only, never fused."""
        if not self._poses:
            return np.eye(4)
        pose = self._poses[-1].copy()
        if self._consensus is not None:
            pose = pose @ health_mod._matrix_power_T(
                self._consensus, self._gap_for(label))
        return pose

    def _covis_overlap(self, label: int, reg_np: np.ndarray):
        """(reason, overlap) when the stop should be skipped, else None."""
        p = self.params
        if not p.covis or reg_np.shape[0] < p.covis_min_points \
                or not self._labels:
            return None
        voxel = self._covis_voxel()
        # Camera-frame duplicate: the turntable did not advance.
        dup = voxel_overlap(reg_np, self._prev_cam_voxels, voxel)
        if dup >= p.covis_duplicate_overlap:
            return ("skipped_duplicate", dup)
        # Predicted-pose redundancy against the fused model.
        if self._consensus is not None and self._model_voxels.size:
            predicted = self._predict_pose(label)
            moved = reg_np @ predicted[:3, :3].T + predicted[:3, 3]
            cov = voxel_overlap(moved, self._model_voxels, voxel)
            if cov >= p.covis_model_overlap:
                return ("skipped_covisible", cov)
        return None

    # -- registration internals -------------------------------------------

    def _edge_key(self, idx: int):
        if idx < self._keys.shape[0]:
            return self._keys[idx]
        # Off-schedule (more stops than expected): deterministic but no
        # longer bit-parity with the batch split — documented in
        # StreamParams.expected_stops.
        return jax.random.fold_in(self._key, idx)

    def _register_edge(self, label: int, prep, gap: int) -> _EdgeRec:
        p = self.params
        key = self._edge_key(len(self._edges))
        hint = self._hint if self._hint is not None \
            else jnp.eye(4, dtype=jnp.float32)
        T, fit, rmse, info = merge_mod.register_edge(
            prep, self._preps[-1], p.merge, key=key, hint=hint)
        self._hint = T
        edge = _EdgeRec(src=label, dst=self._labels[-1], gap=gap,
                        T_dev=T, fit=np.asarray(fit),
                        rmse=np.asarray(rmse), info=info)
        # Live repair: a failing edge must not corrupt the LIVE pose chain
        # (finalize re-gates the raw measurements exactly like the batch
        # path, so this only shapes previews and the covis prediction).
        if p.gates is not None and not p.gates.edge_ok(edge.fit, edge.rmse):
            if self._consensus is not None:
                edge.T_live = health_mod._matrix_power_T(
                    self._consensus, gap)
                events.record("edge_rejected", severity="warning",
                              message=f"live edge {label}->{edge.dst} "
                                      "replaced by ring consensus",
                              fitness=round(edge.fit, 4),
                              rmse=round(edge.rmse, 4), gap=gap)
        return edge

    def _update_consensus(self) -> None:
        Ts = np.stack([e.T_np for e in self._edges if e.gap == 1]) \
            if any(e.gap == 1 for e in self._edges) else None
        if Ts is not None:
            self._consensus = health_mod.consensus_step_np(
                Ts, self.params.merge.step_deg)

    def _refine_window(self) -> None:
        """Local pose-graph re-optimize over the trailing window (see
        `_window_refine_fn`) — runs only when a step consensus exists
        (a pure chain is already the exact solution)."""
        p = self.params
        if self._consensus is None or p.window < 2:
            return
        w = min(p.window, len(self._edges))
        if w < 2:
            return
        W = p.window
        eT = np.tile(np.eye(4, dtype=np.float32), (W, 1, 1))
        eI = np.zeros((W, 6, 6), np.float32)
        pT = np.tile(np.eye(4, dtype=np.float32), (W, 1, 1))
        pI = np.zeros((W, 6, 6), np.float32)
        sel = self._edges[-w:]
        scale = p.window_prior_scale * float(np.median(
            [np.trace(e.info) / 6.0 for e in sel]))
        eye6 = np.eye(6, dtype=np.float32)
        for j, e in enumerate(sel):
            eT[j] = e.T_live.astype(np.float32)
            eI[j] = e.info.astype(np.float32)
            pT[j] = health_mod._matrix_power_T(
                self._consensus, e.gap).astype(np.float32)
            pI[j] = scale * eye6
        opt = np.asarray(_window_refine_fn(W, p.window_iterations)(
            eT, eI, pT, pI), np.float64)
        anchor = self._poses[-(w + 1)]
        for j in range(1, w + 1):
            self._poses[-(w + 1) + j] = anchor @ opt[j]

    # -- fusion + preview --------------------------------------------------

    def _fuse(self, sub_pts, sub_col, sub_val) -> np.ndarray:
        p = self.params
        if self._model is None:
            cap = p.model_cap
            self._model = (jnp.zeros((cap, 3), jnp.float32),
                           jnp.zeros((cap, 3), jnp.float32),
                           jnp.zeros((cap,), bool))
        pose_dev = jnp.asarray(self._poses[-1], jnp.float32)
        m_pts, m_col, m_val, n_model, moved = _fuse_fn(
            p.merge.voxel_size, p.model_cap, self._view_cap)(
            *self._model, pose_dev, sub_pts, sub_col, sub_val)
        self._model = (m_pts, m_col, m_val)
        n_model = int(n_model)
        if n_model > p.model_cap:
            log.warning("running model overflowed model_cap=%d "
                        "(%d voxels) — previews sample a stratified "
                        "subset", p.model_cap, n_model)
        self._model_points = min(n_model, p.model_cap)
        moved_np = np.asarray(moved)
        if p.representation in ("tsdf", "splat", "archival"):
            # Incremental TSDF integration (fusion/preview.py): the
            # stop's pose-transformed view fuses into the persistent
            # volume here, so the preview is a pure extraction — no
            # per-stop re-solve. The camera center in the model frame
            # is the stop pose's translation (decode triangulates in
            # the camera frame, camera at the origin). The valid-masked
            # host copy only seeds the volume's lazy bounds — skip the
            # per-stop fancy-index once the volume exists. Timed (the
            # returned brick count blocks on the program) so preview
            # latency can be reported as integrate + extract.
            t_int = time.monotonic()
            self._mesher.integrate_stop(
                moved, sub_col, sub_val, self._poses[-1][:3, 3],
                moved_np=moved_np[np.asarray(sub_val)]
                if self._mesher.volume is None else None)
            self._last_integrate_s = time.monotonic() - t_int
        return moved_np

    def _maybe_preview(self, label: int) -> bool:
        p = self.params
        if not p.preview_every:
            return False
        n = len(self._labels)
        if n != 1 and n % p.preview_every != 0:
            return False
        if self.suppress_previews:
            events.record("preview_shed", severity="info",
                          message="progressive preview skipped under "
                                  "overload shedding", stops_fused=n)
            return False
        t0 = time.monotonic()
        with trace.span("stream.preview", stop=label):
            mesh = self._mesher(self._model[0], self._model[2])
        dt = time.monotonic() - t0
        self.preview = mesh
        self.preview_meta = {
            "stop": label, "stops_fused": n,
            "faces": int(len(mesh.faces)),
            "vertices": int(len(mesh.vertices)),
            "depth": p.preview_depth,
            "representation": p.representation,
            "model_points": self._model_points,
            "preview_s": round(dt, 3),
            # TSDF: the per-stop volume fuse this preview extracts from
            # (0.0 under poisson, whose __call__ re-solves inside
            # preview_s) — preview_s + integrate_s is the representation-
            # fair per-stop latency bench [11] compares.
            "integrate_s": round(self._last_integrate_s, 3),
        }
        events.record("preview_emitted", faces=int(len(mesh.faces)),
                      depth=p.preview_depth, stops_fused=n,
                      preview_s=round(dt, 3),
                      model_points=self._model_points)
        return True

    # -- finalize ----------------------------------------------------------

    def finalize(self, mesh: bool = True,
                 overlap: bool = True) -> FinalizeResult:
        """Close the ring: optional loop-closure edge, axis-prior re-pass
        (clean rings) or edge gates (degraded rings), full pose solve,
        full-resolution merge of every retained stop view, and the
        final mesh — the SAME math `scan_stacks_to_cloud` runs, staged
        from the per-stop state this session retained (the parity
        contract of tests/test_stream.py).

        The final mesh follows ``params.representation``: the default
        ``"tsdf"`` re-fuses the pose-graph-final cloud into a TSDF and
        extracts — integrate-don't-re-solve, a fraction of a second;
        ``"archival"`` (and the legacy ``"poisson"``) runs the
        full-depth watertight Poisson solve, the print/archive format.

        ``overlap=True`` (default) launches that mesh solve on a
        pipelined worker (`utils/overlap.py`) the moment the merged
        cloud is final, so it runs concurrently with the remaining
        finalize tail (pose-table assembly, health, stats) and joins
        deterministically before the result is returned — same mesh
        bit-for-bit as ``overlap=False`` (tests/test_overlap.py), with
        the realized concurrency window reported in
        ``FinalizeResult.stats["overlap"]``."""
        if self._finalized:
            raise health_mod.StopQualityError(
                f"session {self.scan_id} already finalized")
        if len(self._labels) < 2:
            raise health_mod.StopQualityError(
                f"need at least 2 fused stops to finalize, have "
                f"{len(self._labels)}")
        t0 = time.monotonic()
        p = self.params
        mp = p.merge
        n = len(self._labels)
        loop = p.method == "posegraph" and mp.loop_closure
        with events.context(scan_id=self.scan_id), \
                trace.span("stream.finalize", stops=n):
            result = self._finalize_inner(n, loop, mp, mesh, overlap)
        self._finalized = True
        events.record("session_finalized", stops_fused=n,
                      stops_skipped=len(self._skipped),
                      cloud_points=len(result.cloud),
                      mesh_faces=None if result.mesh is None
                      else int(len(result.mesh.faces)),
                      elapsed_s=round(time.monotonic() - t0, 3))
        return result

    def _finalize_inner(self, n: int, loop: bool, mp, want_mesh: bool,
                        overlap: bool = True):
        p = self.params
        outs_T = [e.T_dev for e in self._edges]
        fit = [e.fit for e in self._edges]
        rmse = [e.rmse for e in self._edges]
        infos = [np.asarray(e.info, np.float32) for e in self._edges]
        if loop:
            key = self._edge_key(len(self._edges))
            hint = self._hint if self._hint is not None \
                else jnp.eye(4, dtype=jnp.float32)
            T, f, r, info = merge_mod.register_edge(
                self._preps[0], self._preps[-1], mp, key=key, hint=hint)
            outs_T.append(T)
            fit.append(float(np.asarray(f)))
            rmse.append(float(np.asarray(r)))
            infos.append(np.asarray(info, np.float32))
        Ts = jnp.stack(outs_T)
        fit = np.asarray(fit)
        rmse = np.asarray(rmse)
        infos_dev = jnp.stack([jnp.asarray(i) for i in infos])

        bridged = any(e.gap != 1 for e in self._edges)
        n_edges = Ts.shape[0]
        if not bridged and mp.axis_prior and n_edges >= 3:
            # Clean ring: the batch loop strategy's consensus re-pass,
            # fed from the retained per-stop preprocesses. Keys are
            # re-derived per edge (NOT self._keys[:E]) so a session that
            # outgrew its expected_stops schedule — edges past the split
            # fall back to fold_in — still hands _edge_xs exactly the E
            # keys the edges actually used.
            pre_stacked = tuple(
                jnp.stack([self._preps[i][j] for i in range(n)])
                for j in range(4))
            keys_used = jnp.stack([self._edge_key(i)
                                   for i in range(n_edges)])
            xs = merge_mod._edge_xs(pre_stacked, n, loop, keys_used)
            Ts, fit_j, rmse_j, infos_dev = merge_mod._axis_pass_fn(mp)(
                xs, (Ts, jnp.asarray(fit, jnp.float32),
                     jnp.asarray(rmse, jnp.float32), infos_dev))
            fit = np.asarray(fit_j)
            rmse = np.asarray(rmse_j)

        if p.gates is not None:
            edges_meta = health_mod.ring_edges(
                self._labels, loop,
                span=scan360_mod._ring_span(self._labels, mp.step_deg))
            Ts2, infos2, _ = health_mod.gate_edges(
                edges_meta, np.asarray(Ts), fit, rmse,
                np.asarray(infos_dev), p.gates, step_deg=mp.step_deg,
                report=self.health)
            seq_T = jnp.asarray(Ts2[: n - 1], jnp.float32)
            seq_info = jnp.asarray(infos2[: n - 1], jnp.float32)
            loop_T = jnp.asarray(Ts2[n - 1], jnp.float32) if loop else None
            loop_info = jnp.asarray(infos2[n - 1], jnp.float32) \
                if loop else None
        else:
            seq_T, seq_info = Ts[: n - 1], infos_dev[: n - 1]
            loop_T = Ts[n - 1] if loop else None
            loop_info = infos_dev[n - 1] if loop else None

        if p.method == "posegraph":
            graph = posegraph.build_360_graph(seq_T, seq_info, loop_T,
                                              loop_info)
            poses = posegraph.optimize(
                graph, iterations=mp.posegraph_iterations)
        else:
            poses = posegraph.chain_poses(seq_T)
        poses_f = jnp.asarray(poses, jnp.float32)

        sub_pts = jnp.stack([s[0] for s in self._subs])
        sub_col = jnp.stack([s[1] for s in self._subs])
        sub_val = jnp.stack([s[2] for s in self._subs])
        moved = scan360_mod._transform_views_fn()(poses_f, sub_pts)
        merged = merge_mod._finalize(
            moved.reshape(-1, 3), sub_col.reshape(-1, 3),
            sub_val.reshape(-1), mp, has_colors=True)

        # The merged cloud is final here — its geometry is everything the
        # mesh solve needs. Launch the solve NOW on the pipelined worker
        # (overlap=True) so the device chews on it while the host runs
        # the remaining finalize tail below (pose-table assembly, health,
        # stats); the deterministic join before FinalizeResult means the
        # mesh is bit-for-bit the sequential path's.
        final_mesh = None
        mesh_task = None
        solve_stats: dict = {}
        if want_mesh:
            from ..models import meshing

            # Poisson warm starts from the previews (docs/MESHING.md):
            # at the SAME dense depth the last preview χ seeds the CG
            # directly; at a SPARSE final depth (> 8) the full preview
            # GRID rides along and warm-starts the sparse solver's
            # internal coarse solve (world-aligned — the ROADMAP's
            # "previews → final solve" item). Only the legacy poisson
            # lane has Poisson previews to warm from; archival previews
            # are the TSDF volume.
            x0 = None
            if p.representation == "poisson":
                if p.final_depth == p.preview_depth:
                    x0 = getattr(self._mesher, "last_chi", None)
                elif p.final_depth > 8:
                    x0 = getattr(self._mesher, "last_grid", None)
            # The splat lane's GEOMETRY is the TSDF volume — its final
            # mesh is the colored TSDF extraction (the rendered artifact
            # rides result_format="render_png", not the mesh path).
            # Archival = TSDF previews, Poisson final artifact.
            mesh_rep = {"splat": "tsdf", "archival": "poisson"}.get(
                p.representation, p.representation)
            mesh_kw = dict(
                mode="watertight", depth=p.final_depth,
                quantile_trim=p.final_trim, representation=mesh_rep,
                tsdf_max_bricks=p.tsdf_max_bricks, cg_x0=x0,
                solve_stats=solve_stats)
            if overlap:
                mesh_task = meshing.mesh_from_cloud_async(
                    merged, task_name=f"finalize-{self.scan_id}",
                    **mesh_kw)
            else:
                final_mesh = meshing.mesh_from_cloud(merged, **mesh_kw)

        poses_np = np.asarray(poses)
        all_poses = np.tile(np.eye(4, dtype=np.float32),
                            (self._next_label, 1, 1))
        for j, lab in enumerate(self._labels):
            all_poses[lab] = poses_np[j].astype(np.float32)
        for lab, (_, predicted) in self._skipped.items():
            all_poses[lab] = predicted.astype(np.float32)

        stats = {
            "stops_fused": n,
            "stops_skipped": len(self._skipped),
            "edges": [
                {"src": e.src, "dst": e.dst, "gap": e.gap,
                 "fitness": round(e.fit, 4), "rmse": round(e.rmse, 4)}
                for e in self._edges],
            "min_fitness": round(float(fit.min()), 4) if len(fit) else None,
            "cloud_points": len(merged),
        }
        if mesh_task is not None:
            # Join: the tail above ran while the solve did. tail_done_s <
            # solve ended_s = the finalize tail was fully hidden inside
            # the solve window; bench [6b] asserts the converse too (the
            # solve was already running when the tail finished).
            tail_done_s = time.monotonic() - mesh_task.t_submit
            final_mesh = mesh_task.result()
            timings = mesh_task.timings()
            stats["overlap"] = {
                "solve": timings,
                "tail_done_s": round(tail_done_s, 6),
                "overlapped": timings["started_s"] is not None
                and timings["started_s"] < tail_done_s,
            }
        if solve_stats:
            # Sparse-finalize solve telemetry (warm_start_blocks > 0 =
            # the previews seeded the final solve; tests assert it).
            stats["final_solve"] = solve_stats
        log.info("stream finalize[%s]: %d fused / %d skipped stops -> "
                 "%d points%s", self.scan_id, n, len(self._skipped),
                 len(merged),
                 "" if final_mesh is None
                 else f", {len(final_mesh.faces)} mesh faces")
        return FinalizeResult(cloud=merged, poses=all_poses,
                              mesh=final_mesh, health=self.health,
                              stats=stats)
