"""Session-lane program warmup: compile the streaming chain at start.

The serve batch lanes precompile at startup (`serve/cache.warmup`), but
a session's device programs — per-stop registration, the windowed pose
refine, the model fuse, the preview chain — historically compiled inside
the FIRST session that exercised them. On a fleet that is exactly the
failover window: a survivor adopting a dead replica's session paid
~30–40 s of session-lane jit compiles before the first re-pinned stop
fused (ROADMAP). This module runs a tiny deterministic 3-stop synthetic
ring through a throwaway :class:`~.session.IncrementalSession` at the
REAL bucket pixel count and the REAL session params, so every program a
recovered/adopted session will launch is already in the jit cache:

* stop 1 — subsample + first fuse + first preview;
* stop 2 — registration preprocess + edge ICP + consensus;
* stop 3 — the fixed-window pose-graph refine (needs ≥ 2 edges).

Not warmed (shapes depend on the final stop count, finalize-only):
the full-ring pose solve, the axis-prior re-pass and the finalize
merge. Those run once per session at finalize, outside the failover
window the fleet chaos gate measures.

The synthetic stops are a rotated sphere cap — enough structure for
RANSAC/ICP to run its full program graph; the result is discarded.
Covisibility gating is host-side (no programs) but the stops rotate by
the ring step anyway so none is skipped as a duplicate.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from ..utils.log import get_logger
from .session import IncrementalSession, StreamParams

log = get_logger(__name__)


def _synthetic_stop(n_pixels: int, m_valid: int, step_deg: float,
                    k: int) -> tuple:
    """One deterministic fake decoded stop: ``m_valid`` points on a
    bumpy sphere (radius 80 @ z=500, the synthetic-rig scale), rotated
    ``k`` turntable steps; the remaining slots are invalid zeros."""
    m = min(m_valid, n_pixels)
    i = np.arange(m, dtype=np.float64)
    phi = np.pi * (3.0 - np.sqrt(5.0))
    y = 1.0 - 2.0 * (i + 0.5) / m
    r = np.sqrt(np.maximum(1.0 - y * y, 0.0))
    pts = np.stack([np.cos(phi * i) * r, y, np.sin(phi * i) * r], axis=1)
    # Low-frequency bumps give ICP/FPFH non-degenerate structure.
    pts *= (1.0 + 0.1 * np.sin(3.0 * pts[:, :1]) * np.cos(2.0 * pts[:, 2:]))
    a = np.deg2rad(step_deg) * k
    rot = np.array([[np.cos(a), 0.0, np.sin(a)],
                    [0.0, 1.0, 0.0],
                    [-np.sin(a), 0.0, np.cos(a)]])
    pts = pts @ rot.T * 80.0 + np.array([0.0, 0.0, 500.0])
    points = np.zeros((n_pixels, 3), np.float32)
    points[:m] = pts.astype(np.float32)
    # uint8, NOT float: decode hands sessions uint8 colors, and the
    # subsample/fuse programs are keyed on that dtype — a float warmup
    # would compile a lane no real stop ever uses.
    colors = np.zeros((n_pixels, 3), np.uint8)
    colors[:m] = 128
    valid = np.zeros(n_pixels, bool)
    valid[:m] = True
    return points, colors, valid


def _warm_splat_lane(mesher, frame_shape) -> bool:
    """Drive the splat previewer's observe → seed → fit → render chain
    once with a pinhole-consistent synthetic frame (a fronto-parallel
    textured plane — the sphere stops above already populated the
    volume; this frame exists so the pinhole fit succeeds and the fit
    step compiles). The result is discarded; the programs stay."""
    h, w = int(frame_shape[0]), int(frame_shape[1])
    f = 0.8 * w
    cx, cy = (w - 1) * 0.5, (h - 1) * 0.5
    z = 500.0
    jj, ii = np.meshgrid(np.arange(w, dtype=np.float32),
                         np.arange(h, dtype=np.float32))
    points = np.stack([(jj - cx) * z / f, (ii - cy) * z / f,
                       np.full((h, w), z, np.float32)],
                      axis=-1).reshape(-1, 3)
    colors = np.zeros((h * w, 3), np.uint8)
    colors[:, 0] = (np.arange(h * w) % 255).astype(np.uint8)
    valid = np.ones(h * w, bool)
    if not mesher.observe_frame(points, colors, valid, np.eye(4),
                                (h, w)):
        return False
    return mesher.render_png(30.0, 20.0) is not None


def warm_session_programs(params: StreamParams, n_pixels: int,
                          col_bits: int = 8, row_bits: int = 8,
                          stops: int = 3,
                          frame_shape: tuple | None = None) -> dict:
    """Compile the session-lane programs for ``(params, n_pixels)``.

    Returns a small report dict (seconds, stops, representation). Safe
    to call more than once — warm programs make reruns near-free (the
    jit cache is process-global, exactly why this works).
    ``frame_shape`` (H, W) warms the splat appearance lane too
    (``representation="splat"``): seed, fit step and the default-size
    render compile at replica start instead of inside the first
    render request."""
    t0 = time.monotonic()
    # Gates and covisibility are host-side (they key no programs);
    # disabling them guarantees every synthetic stop actually FUSES —
    # a skipped stop would leave its programs cold.
    wp = dataclasses.replace(params, gates=None, covis=False,
                             preview_every=1)
    sess = IncrementalSession(
        calib=None, col_bits=col_bits, row_bits=row_bits, params=wp,
        scan_id="warmup-session")
    m_valid = min(n_pixels, 8192)
    # step_deg may be None (ring step unknown until a real session);
    # the synthetic rotation only shapes geometry, never a program.
    step = wp.merge.step_deg if wp.merge.step_deg else 15.0
    for k in range(max(3, int(stops))):
        points, colors, valid = _synthetic_stop(
            n_pixels, m_valid, step, k)
        sess.add_decoded(points, colors, valid)
    rendered = False
    if wp.representation == "splat" and frame_shape is not None:
        rendered = _warm_splat_lane(sess._mesher, frame_shape)
    report = {
        "seconds": round(time.monotonic() - t0, 3),
        "stops": sess.stops_fused,
        "pixels": int(n_pixels),
        "representation": wp.representation,
        "render_warmed": rendered,
    }
    log.info("session-lane warmup: %d synthetic stops @ %d px "
             "(%s previews) in %.1fs", report["stops"], n_pixels,
             wp.representation, report["seconds"])
    return report
