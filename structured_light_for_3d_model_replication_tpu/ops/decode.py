"""Per-pixel Gray-code stack decode.

TPU-first redesign of the reference decode loops (`server/sl_system.py:508-580`
and the fixed-threshold twin `multi_point_cloud_process.py:23-71`):

* the reference does 22 full-frame NumPy passes (one imread+compare per bit,
  `sl_system.py:549-564`) then an XOR loop (`:567-570`). Here the whole
  (n_frames, H, W) stack is decoded in ONE jitted kernel: a batched compare of
  the pattern/inverse frame planes, an exact integer bit-pack reduction on the
  VPU (deliberately NOT a tensordot/einsum — on TPU that would route int32
  through the MXU's reduced-precision path), and a doubling XOR scan for
  Gray→binary.
* validity masks are computed densely (no data-dependent `np.where` gathers —
  everything downstream is masked, static-shape).

Stack layout is the protocol order of `patterns.pattern_stack`:
[white, black, colbit_0, ~colbit_0, ..., rowbit_0, ~rowbit_0, ...], MSB first.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import _backend
from .patterns import gray_to_binary
from ..config import DecodeConfig


def _check_frames(stack: jnp.ndarray, col_bits: int, row_bits: int) -> int:
    n = 2 + 2 * col_bits + 2 * row_bits
    if stack.shape[0] != n:
        raise ValueError(f"stack has {stack.shape[0]} frames, expected {n}")
    return n


def split_stack(stack: jnp.ndarray, col_bits: int, row_bits: int):
    """Split a protocol-ordered stack into (white, black, col_pairs, row_pairs).

    col_pairs/row_pairs have shape (n_bits, 2, H, W) with [:,0]=pattern,
    [:,1]=inverse.
    """
    _check_frames(stack, col_bits, row_bits)
    white = stack[0]
    black = stack[1]
    col = stack[2 : 2 + 2 * col_bits].reshape(col_bits, 2, *stack.shape[1:])
    row = stack[2 + 2 * col_bits :].reshape(row_bits, 2, *stack.shape[1:])
    return white, black, col, row


def decode_bits(pairs: jnp.ndarray) -> jnp.ndarray:
    """(n_bits, 2, H, W) pattern/inverse pairs -> (H, W) int32 binary code.

    bit_b = pattern_b > inverse_b  (reference `server/sl_system.py:557`),
    packed MSB-first then Gray→binary.
    """
    n_bits = pairs.shape[0]
    bits = (pairs[:, 0] > pairs[:, 1]).astype(jnp.int32)  # (n_bits, H, W)
    # Exact integer bit-pack on the VPU (a tensordot would route int32 through
    # the MXU's reduced-precision path on TPU).
    weights = (1 << jnp.arange(n_bits - 1, -1, -1, dtype=jnp.int32))
    gray = jnp.sum(weights[:, None, None] * bits, axis=0)  # (H, W)
    return gray_to_binary(gray, n_bits)


def _percentile_u8(x: jnp.ndarray, q: float) -> jnp.ndarray:
    """Exact ``np.percentile`` (linear interpolation) for uint8 data via a
    256-bin histogram. ``jnp.percentile`` lowers to a full sort of the
    frame — XProf measured 256 ms of the fused 360° pipeline spent
    sorting 2M pixels per stop for ONE order statistic; both order
    statistics of an integer image fall out of cumulative counts."""
    flat = x.reshape(-1).astype(jnp.int32)
    n = flat.shape[0]
    vals = jnp.arange(256, dtype=jnp.int32)
    # count(x ≤ v) per v as a broadcast-reduce (fuses on TPU; no scatter).
    cum = jnp.sum((flat[None, :] <= vals[:, None]).astype(jnp.int32),
                  axis=1)                                   # (256,)
    pos = (n - 1) * (q / 100.0)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.ceil(pos).astype(jnp.int32)
    frac = jnp.float32(pos - jnp.floor(pos))
    v_lo = jnp.argmax(cum > lo).astype(jnp.float32)  # first cum ≥ lo+1
    v_hi = jnp.argmax(cum > hi).astype(jnp.float32)
    return v_lo + (v_hi - v_lo) * frac


def adaptive_mask(
    white: jnp.ndarray,
    black: jnp.ndarray,
    white_factor: float = 1.5,
    black_percentile: float = 95.0,
    contrast_frac: float = 0.05,
) -> jnp.ndarray:
    """Reference adaptive validity mask (`server/sl_system.py:526-535`).

    valid = white > factor * P95(black)  AND  (white-black) > frac * max_contrast.
    """
    w = white.astype(jnp.float32)
    b = black.astype(jnp.float32)
    # Histogram path strictly for uint8 (its 256 bins are wrong for wider
    # integer types, e.g. 10/12-bit camera frames).
    if jnp.asarray(black).dtype == jnp.uint8:
        p = _percentile_u8(black, black_percentile)
    else:
        p = jnp.percentile(b, black_percentile)
    thresh_w = white_factor * p
    contrast = w - b
    thresh_c = contrast_frac * jnp.max(contrast)
    return (w > thresh_w) & (contrast > thresh_c)


def fixed_mask(
    white: jnp.ndarray,
    black: jnp.ndarray,
    white_thresh: float = 40.0,
    contrast_thresh: float = 10.0,
) -> jnp.ndarray:
    """Fixed-threshold mask (`multi_point_cloud_process.py:36-38`)."""
    w = white.astype(jnp.float32)
    b = black.astype(jnp.float32)
    return (w > white_thresh) & ((w - b) > contrast_thresh)


@functools.partial(
    jax.jit, static_argnums=(1, 2),
    static_argnames=("cfg", "downsample", "backend")
)
def decode_stack(
    stack: jnp.ndarray,
    col_bits: int,
    row_bits: int,
    cfg: DecodeConfig = DecodeConfig(),
    downsample: int = 1,
    backend: str = "auto",
):
    """Full decode: (n_frames, H, W) stack -> (col_map, row_map, mask).

    col_map/row_map are int32 projector PIXEL coordinates per camera pixel
    (coarse codes are rescaled to stripe centers when downsample > 1); mask is
    the per-pixel validity. Dense over all pixels (masking instead of gather).

    ``backend``: "xla" (fused jnp ops), "pallas" (one VMEM-resident TPU
    kernel, ops/decode_pallas.py), or "auto" (pallas on TPU backends).
    """
    if backend == "auto":
        # Mosaic kernels are TPU-only (the shared _backend gate knows the
        # tunneled-TPU platform names). Anything else (cpu, gpu, ...)
        # takes the portable XLA path.
        backend = "pallas" if _backend.tpu_backend() else "xla"
    _check_frames(stack, col_bits, row_bits)
    white, black = stack[0], stack[1]
    if backend == "pallas":
        from .decode_pallas import decode_maps_pallas

        col_map, row_map = decode_maps_pallas(stack, col_bits, row_bits,
                                              downsample=downsample)
    elif backend == "xla":
        _, _, col_pairs, row_pairs = split_stack(stack, col_bits, row_bits)
        col_map = decode_bits(col_pairs) * downsample + (downsample - 1) // 2
        row_map = decode_bits(row_pairs) * downsample + (downsample - 1) // 2
    else:
        raise ValueError(f"unknown decode backend {backend!r}")
    if cfg.mode == "adaptive":
        mask = adaptive_mask(
            white, black, cfg.white_factor, cfg.black_percentile, cfg.contrast_frac
        )
    elif cfg.mode == "fixed":
        mask = fixed_mask(white, black, cfg.white_thresh, cfg.contrast_thresh)
    else:
        raise ValueError(f"unknown mask mode {cfg.mode!r}")
    return col_map, row_map, mask
