"""Pose-graph optimization (the loop-closure 360° merge upgrade).

Replaces Open3D's ``PoseGraph`` + ``global_optimization`` (Levenberg-
Marquardt) as driven by the reference's legacy merge
(`Old/360Merge.py:43-84`, `Old/new360Merge.py:96-137`): a chain of
sequential ICP edges plus a first↔last loop-closure edge, each carrying a
6×6 information matrix, jointly optimized so drift is distributed around the
loop instead of accumulating (strictly better than the shipped sequential
merge `server/processing.py:140-167`).

TPU-first formulation: the problem is tiny (N≈24 nodes → 6(N−1) variables),
so the whole LM iteration is DENSE — residuals for all edges at once,
the Jacobian by forward-mode autodiff in one ``jax.jacfwd`` call, one
(6(N−1))² solve per iteration, all inside ``lax.scan``. No sparse graph
machinery, no host loops.

Conventions (matching the reference's Open3D usage): node pose X_i maps
frame-i points into the global (node 0) frame; an edge (i, j, T_ij) measures
``X_i ≈ X_j · T_ij`` (T_ij carries source-i points into frame j, exactly
what ICP between scan i and scan j returns). Edge residual
``r = [log_SO3, trans](T_ij⁻¹ · X_j⁻¹ · X_i) ∈ ℝ⁶`` weighted by the edge
information matrix.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .registration import exp_se3

# 4×4 pose chains/products are numerically load-bearing; TPU default matmul
# precision (bf16 inputs) visibly corrupts accumulated rotations. The
# default_matmul_precision context also covers linalg.inv/solve, whose
# LU/triangular kernels are matmul-backed on TPU.
_hi_precision = functools.partial(jax.default_matmul_precision, "highest")


class PoseGraph(NamedTuple):
    poses: jnp.ndarray       # (N, 4, 4) initial node poses (frame i → global)
    edge_src: jnp.ndarray    # (E,) int32
    edge_dst: jnp.ndarray    # (E,) int32
    edge_T: jnp.ndarray      # (E, 4, 4) measured X_dst⁻¹ X_src
    edge_info: jnp.ndarray   # (E, 6, 6) information matrices


def log_so3(R: jnp.ndarray) -> jnp.ndarray:
    """Rotation vector of (..., 3, 3); safe near identity."""
    tr = jnp.trace(R, axis1=-2, axis2=-1)
    cos = jnp.clip((tr - 1.0) / 2.0, -1.0, 1.0)
    th = jnp.arccos(cos)
    v = jnp.stack([
        R[..., 2, 1] - R[..., 1, 2],
        R[..., 0, 2] - R[..., 2, 0],
        R[..., 1, 0] - R[..., 0, 1],
    ], axis=-1)
    s = jnp.sin(th)
    # th/(2 sin th) → 1/2 as th → 0.
    scale = jnp.where(th[..., None] > 1e-6,
                      th[..., None] / (2.0 * jnp.where(jnp.abs(s) > 1e-12, s, 1.0)[..., None]),
                      0.5)
    return v * scale


@jax.jit
def chain_poses(edge_T_seq: jnp.ndarray) -> jnp.ndarray:
    """Initial odometry poses from sequential edge measurements.

    edge_T_seq[i] = T_{i+1, i}? No — pass T such that X_{i+1} = X_i · T_i
    (i.e. T_i maps frame-(i+1) points into frame i, the ICP result of
    aligning scan i+1 onto scan i, as the reference accumulates at
    `server/processing.py:162`). Returns (N, 4, 4) with X_0 = I.

    Jitted at module level: the eager ``lax.scan`` used to rebuild its
    ``step`` closure per call, so EVERY finalize recompiled the scan —
    caught by the no_compile_region around the overlapped finalize
    (tests/test_overlap.py); under jit the program is traced once per
    edge-count.
    """
    def step(X, T):
        Xn = X @ T
        return Xn, Xn

    with _hi_precision():
        _, rest = jax.lax.scan(step, jnp.eye(4, dtype=edge_T_seq.dtype),
                               edge_T_seq)
    return jnp.concatenate([jnp.eye(4, dtype=edge_T_seq.dtype)[None], rest],
                           axis=0)


@functools.partial(jax.jit, static_argnames=("iterations",))
def optimize(
    graph: PoseGraph,
    iterations: int = 30,
    damping: float = 1e-6,
) -> jnp.ndarray:
    """Levenberg-Marquardt over node poses (node 0 held fixed).

    Returns optimized (N, 4, 4) poses. Damping is adapted multiplicatively:
    a step that reduces the weighted cost is accepted and λ shrinks ×0.5,
    otherwise the step is rejected and λ grows ×4 (classic LM schedule,
    branch-free via jnp.where).
    """
    n = graph.poses.shape[0]
    nv = 6 * (n - 1)
    with _hi_precision():
        poses0 = graph.poses.astype(jnp.float32)
        Tinv = jnp.linalg.inv(graph.edge_T.astype(jnp.float32))
        info = graph.edge_info.astype(jnp.float32)

        def apply_delta(poses, xi):
            """Right-perturb every pose except node 0."""
            xi_full = jnp.concatenate([jnp.zeros((1, 6), xi.dtype),
                                       xi.reshape(n - 1, 6)], axis=0)
            deltas = jax.vmap(lambda v: exp_se3(v[:3], v[3:]))(xi_full)
            return jnp.einsum("nij,njk->nik", poses, deltas,
                              precision=jax.lax.Precision.HIGHEST)

        def residuals(xi, poses):
            P = apply_delta(poses, xi)
            Xi = P[graph.edge_src]
            Xj_inv = jnp.linalg.inv(P[graph.edge_dst])
            E = jnp.einsum("eij,ejk,ekl->eil", Tinv, Xj_inv, Xi,
                            precision=jax.lax.Precision.HIGHEST)
            r_rot = log_so3(E[:, :3, :3])
            r_t = E[:, :3, 3]
            return jnp.concatenate([r_rot, r_t], axis=-1)  # (E, 6)

        def cost_of(r):
            return jnp.sum(jnp.einsum("ei,eij,ej->e", r, info, r,
                                     precision=jax.lax.Precision.HIGHEST))

        def step(carry, _):
            poses, lam = carry
            zero = jnp.zeros(nv, jnp.float32)
            r = residuals(zero, poses)                       # (E, 6)
            J = jax.jacfwd(lambda x: residuals(x, poses))(zero)  # (E, 6, nv)
            # H = Σ_e J_eᵀ Λ_e J_e ; g = Σ_e J_eᵀ Λ_e r_e
            JL = jnp.einsum("eij,eik->ejk", info, J,
                            precision=jax.lax.Precision.HIGHEST)         # Λᵀ=Λ
            H = jnp.einsum("eiv,eiw->vw", J, JL,
                            precision=jax.lax.Precision.HIGHEST)
            g = jnp.einsum("eiv,eij,ej->v", J, info, r,
                            precision=jax.lax.Precision.HIGHEST)
            delta = -jnp.linalg.solve(
                H + lam * jnp.eye(nv, dtype=H.dtype), g
            )
            new_poses = apply_delta(poses, delta)
            c0 = cost_of(r)
            c1 = cost_of(residuals(zero, new_poses))
            better = c1 < c0
            poses = jnp.where(better, new_poses, poses)
            lam = jnp.where(better, lam * 0.5, lam * 4.0)
            return (poses, lam), c0

        (poses, _), _ = jax.lax.scan(step, (poses0, jnp.float32(damping)),
                                     None, length=iterations)
        return poses


def build_360_graph(
    seq_T: jnp.ndarray,
    seq_info: jnp.ndarray,
    loop_T: jnp.ndarray | None = None,
    loop_info: jnp.ndarray | None = None,
) -> PoseGraph:
    """Graph for an N-stop turntable ring: sequential edges i+1→i (ICP of
    scan i+1 onto scan i) plus the optional loop-closure edge 0→N-1
    (`Old/360Merge.py:53-56`: "sequential scans ... AND the loop closure").

    seq_T[i] maps frame-(i+1) points into frame i; loop_T maps frame-0
    points into frame N-1 (ICP of scan 0 onto the last scan).
    """
    n = seq_T.shape[0] + 1
    poses = chain_poses(seq_T)
    src = jnp.arange(1, n, dtype=jnp.int32)
    dst = jnp.arange(0, n - 1, dtype=jnp.int32)
    edge_T = seq_T
    info = seq_info
    if loop_T is not None:
        src = jnp.concatenate([src, jnp.array([0], jnp.int32)])
        dst = jnp.concatenate([dst, jnp.array([n - 1], jnp.int32)])
        edge_T = jnp.concatenate([edge_T, loop_T[None]], axis=0)
        info = jnp.concatenate([info, loop_info[None]], axis=0)
    return PoseGraph(poses, src, dst, edge_T, info)
