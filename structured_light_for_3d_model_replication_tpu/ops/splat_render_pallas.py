"""Pallas TPU kernel for the per-tile splat composite.

The XLA composite (`splat_render._composite_xla`) materializes the
(NT, T², K) Gaussian-weight tensor and its cumulative-transmittance
sibling in HBM — ~57 MB per intermediate for a 384×288 frame at the
default tile=8/K=128 (1728 tiles × 64 px × 128 records, float32), and
several such intermediates live through the composite. This kernel
runs the classic front-to-back loop instead: one grid step per image
tile, the tile's K gathered splat records resident in VMEM, a
``fori_loop`` over the (already depth-sorted) splats accumulating a
``(1, T²)`` transmittance row and three color rows — every
intermediate stays on chip and each record is read exactly once.

Record layout mirrors `ops/tsdf_pallas.py`'s flat-plane rule: every
operand is a (NT, K) float32 plane (colors as three planes, the
membership mask pre-cast to float), so all inputs share one tile
shape; the pixel axis (T² — 64 lanes at the default 8-px tile, padded
to the 128-lane minimum by Mosaic; 16-px tiles fill the lanes but see
`RenderConfig`'s depth-capacity caveat) is the minor dimension of
every in-kernel tensor. The tile's pixel origin rides a (NT, 1)
operand rather than a program_id reconstruction, keeping the kernel
shape-agnostic in the tile grid.

Numerical contract pinned against the XLA form (interpret mode on CPU,
compiled on TPU) in tests/test_splat.py. Gradients are NOT defined for
this path — the fit loop always differentiates the XLA form
(`splat/fit.py`); this kernel only serves reads (novel-view renders).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import _backend


def available() -> bool:
    return _backend.tpu_backend()


def _kernel(u_ref, v_ref, ca_ref, cb_ref, cc_ref, cr_ref, cg_ref,
            cb2_ref, opa_ref, ok_ref, x0_ref, y0_ref,
            r_out, g_out, b_out, a_out, *, tile: int, k: int):
    t2 = tile * tile
    px = jax.lax.broadcasted_iota(jnp.float32, (1, t2), 1)
    gx = x0_ref[0, 0] + px % float(tile)
    gy = y0_ref[0, 0] + px // float(tile)

    def body(i, carry):
        trans, r, g, b = carry
        dx = gx - u_ref[0, i]
        dy = gy - v_ref[0, i]
        power = (-0.5 * (ca_ref[0, i] * dx * dx + cc_ref[0, i] * dy * dy)
                 - cb_ref[0, i] * dx * dy)
        gauss = jnp.exp(jnp.minimum(power, 0.0))
        alpha = jnp.clip(opa_ref[0, i] * gauss, 0.0, 0.995) * ok_ref[0, i]
        w = trans * alpha
        return (trans * (1.0 - alpha), r + w * cr_ref[0, i],
                g + w * cg_ref[0, i], b + w * cb2_ref[0, i])

    ones = jnp.ones((1, t2), jnp.float32)
    zero = jnp.zeros((1, t2), jnp.float32)
    trans, r, g, b = jax.lax.fori_loop(0, k, body,
                                       (ones, zero, zero, zero))
    r_out[...] = r
    g_out[...] = g
    b_out[...] = b
    a_out[...] = 1.0 - trans


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def composite_pallas(u, v, ca, cb, cc, cr, cg, cbl, opa, ok, x0, y0,
                     cfg, interpret: bool = False):
    """Same contract as ``splat_render._composite_xla``: (NT, K) record
    planes + (NT,) tile origins → ((NT, T², 3) premultiplied color,
    (NT, T²) alpha). ``px % tile`` in-kernel recovers pixel coords, so
    the grid is one step per tile with no host-side pixel tables."""
    nt, k = u.shape
    t2 = cfg.tile * cfg.tile
    okf = ok.astype(jnp.float32)
    x0c = x0.reshape(nt, 1)
    y0c = y0.reshape(nt, 1)
    rec = pl.BlockSpec((1, k), lambda c: (c, 0))
    org = pl.BlockSpec((1, 1), lambda c: (c, 0))
    out = pl.BlockSpec((1, t2), lambda c: (c, 0))
    r, g, b, a = pl.pallas_call(
        functools.partial(_kernel, tile=cfg.tile, k=k),
        grid=(nt,),
        in_specs=[rec] * 10 + [org, org],
        out_specs=[out] * 4,
        out_shape=[jax.ShapeDtypeStruct((nt, t2), jnp.float32)] * 4,
        interpret=interpret,
    )(u, v, ca, cb, cc, cr, cg, cbl, opa, okf, x0c, y0c)
    return jnp.stack([r, g, b], axis=-1), a
