"""Fused statistical-outlier-removal + normal estimation — ONE program,
ONE Morton sort, ZERO large random gathers.

The reference runs these as two separate Open3D passes over the final
merged cloud (`server/processing.py:174-178`: ``remove_statistical_outlier``
then ``estimate_normals`` on the survivors), and round 1 mirrored that
structure on TPU: two independent Morton-KNN launches (two sorts, two
candidate sweeps) plus an (N, k, 3) random gather for the covariance — the
one memory pattern a TPU does poorly. Measured at 1M points: ~1.5 s.

This module fuses the whole chain into a single jitted program in Morton-
sorted space:

1. sort points ONCE by 30-bit Morton code (`ops/mortonknn.py` scheme);
2. per block of B sorted points, the candidate window is blocks
   b−1, b, b+1 — three contiguous slices, no gather;
3. **phase 1 (SOR)**: one (B × 3B) distance matmul per block →
   ``approx_min_k`` over the window (self excluded) → per-point mean
   neighbor distance → global μ/σ → keep mask. Exactly
   :func:`..ops.pointcloud.statistical_outlier_removal` semantics on the
   Morton-approximate neighborhood;
4. **phase 2 (normals)**: the SAME sorted layout (no second sort), with
   dropped outliers masked out of the candidate window — matching the
   reference's "estimate on the survivors" ordering — top-k *local window*
   indices, a tiny per-chunk window gather (3B rows, contiguous), masked
   covariance, analytic smallest-eigenvector solve;
5. un-sort all outputs with one scatter.

The distance matrix is recomputed in phase 2 rather than cached: caching
(nb_chunks × B × 3B) floats would spill to HBM and the matmul is cheaper
than the round trip. Everything happens in one launch: on a 1M-point cloud
this replaces two sorts + two sweeps + a 120 MB random gather with one
sort + two sweeps sharing one layout.

Approximation contract matches the Morton engine: recall ≈ 0.93 at k=20 /
B=256, missed neighbors replaced by near-equidistant ones, so SOR
statistics and PCA normals track the exact engine to >99 % — pinned
directly against the exact dense chain by
`tests/test_spatial_knn.py::test_fused_sor_normals_tracks_exact_dense`.

Why Morton and not the ≥0.99-recall brick engine (`ops/brickknn.py`):
this op consumes *statistics* of the neighborhood (mean distance, PCA
covariance), not its exact membership, and Morton's misses are replaced
by near-equidistant points — while the brick sweep ALONE measures ~2.7×
the wall-clock of this entire fused pass at 1M/k=20 (r4 TPU bench:
rescue 1108 ms vs 407 ms for fused SOR+normals). Exact-membership
consumers route through ``pointcloud._self_knn``'s ``rescue`` default
instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .mortonknn import _GRID_MAX, morton_code
from .pointcloud import smallest_eigenvector_sym3


@functools.partial(jax.jit,
                   static_argnums=(3, 4, 5, 6))
def _sor_normals_impl(points, valid, std_ratio, nb_neighbors: int,
                      k_normals: int, block: int, chunk_blocks: int):
    n = points.shape[0]

    # --- one Morton sort (ops/mortonknn.py scheme) ---------------------
    mins = jnp.min(jnp.where(valid[:, None], points, jnp.inf), axis=0)
    maxs = jnp.max(jnp.where(valid[:, None], points, -jnp.inf), axis=0)
    h = jnp.maximum(jnp.max(maxs - mins) / _GRID_MAX, 1e-12)
    cell = jnp.clip(((points - mins) / h).astype(jnp.int32), 0, _GRID_MAX)
    code = morton_code(cell)
    sort_key = jnp.where(valid, code, jnp.int32(2**31 - 1))
    order = jnp.argsort(sort_key)
    pts_s = points[order]
    val_s = valid[order]
    orig_s = order.astype(jnp.int32)

    pad = (-n) % block
    if pad:
        pts_s = jnp.concatenate([pts_s, jnp.zeros((pad, 3), pts_s.dtype)])
        val_s = jnp.concatenate([val_s, jnp.zeros(pad, bool)])
        orig_s = jnp.concatenate([orig_s, jnp.zeros(pad, jnp.int32)])
    nb = pts_s.shape[0] // block
    bp = pts_s.reshape(nb, block, 3)
    bv = val_s.reshape(nb, block)
    bi = orig_s.reshape(nb, block)
    brow = jnp.arange(nb * block, dtype=jnp.int32).reshape(nb, block)

    def with_neighbors(x):
        return jnp.concatenate(
            [jnp.roll(x, 1, axis=0), x, jnp.roll(x, -1, axis=0)], axis=1)

    cp = with_neighbors(bp)    # (nb, 3B, 3)
    cv = with_neighbors(bv)    # (nb, 3B)
    crow = with_neighbors(brow)  # (nb, 3B) sorted-row id of candidates

    cb = chunk_blocks
    nb_pad = (-nb) % cb
    if nb_pad:
        def padb(x):
            return jnp.concatenate(
                [x, jnp.zeros((nb_pad,) + x.shape[1:], x.dtype)])
        bp, bv, brow, cp, cv, crow = map(
            padb, (bp, bv, brow, cp, cv, crow))
    groups = bp.shape[0] // cb

    def g(x):
        return x.reshape((groups, cb) + x.shape[1:])

    hi = jax.lax.Precision.HIGHEST

    def dists(q, kp, mask_bad):
        q2 = jnp.sum(q * q, axis=-1)                      # (C, B)
        p2 = jnp.sum(kp * kp, axis=-1)                    # (C, 3B)
        cross = jnp.einsum("cbd,cnd->cbn", q, kp, precision=hi)
        d2 = q2[..., :, None] + p2[..., None, :] - 2.0 * cross
        return jnp.where(mask_bad, jnp.inf, d2)

    # --- phase 1: SOR mean neighbor distance ---------------------------
    def phase1(args):
        q, qr, kp, kv, kr = args
        bad = ~kv[..., None, :] | (qr[..., :, None] == kr[..., None, :])
        d2 = dists(q, kp, bad)
        flat = d2.reshape(-1, d2.shape[-1])
        cd, _ = jax.lax.approx_min_k(flat, nb_neighbors, recall_target=0.99)
        ok = jnp.isfinite(cd)
        dd = jnp.sqrt(jnp.maximum(jnp.where(ok, cd, 0.0), 0.0))
        cnt = jnp.sum(ok, axis=1)
        mean_d = jnp.sum(dd, axis=1) / jnp.maximum(cnt, 1)
        return mean_d, cnt > 0                            # (C*B,) ×2

    mean_d, has_nb = jax.lax.map(phase1, (g(bp), g(brow), g(cp), g(cv),
                                          g(crow)))
    mean_d = mean_d.reshape(-1)
    has_nb = has_nb.reshape(-1)
    vflat = bv.reshape(-1)
    # Zero-neighbor points are undecidable: excluded from μ/σ and removed
    # (same conservative contract as ops/pointcloud.py SOR — mean_d = 0
    # would make them unconditionally survive).
    vf = (vflat & has_nb).astype(jnp.float32)
    nv = jnp.maximum(jnp.sum(vf), 1.0)
    mu = jnp.sum(mean_d * vf) / nv
    var = jnp.sum((mean_d - mu) ** 2 * vf) / nv
    thresh = mu + std_ratio * jnp.sqrt(var)
    keep_flat = vflat & has_nb & (mean_d <= thresh)       # sorted domain

    # --- phase 2: normals among the survivors --------------------------
    # Keep-mask windows are rebuilt on the PADDED block axis so shapes line
    # up with cp/cv (keep_flat already carries the chunk padding).
    bk = keep_flat.reshape(bp.shape[0], block)
    ck = jnp.concatenate([jnp.roll(bk, 1, axis=0), bk,
                          jnp.roll(bk, -1, axis=0)], axis=1)

    def phase2(args):
        # Covariance WITHOUT a neighbor gather (the gather dominated the
        # whole op: ~350 ms of the round-1 1.5 s at 1M). approx_min_k only
        # supplies the k-th neighbor distance; membership becomes the
        # elementwise window mask d2 ≤ kth, and the PCA moments reduce
        # through the window with MXU matmuls:
        #   cnt = W·1,  s1 = W·p,  s2 = W·(p⊗p)  →  Σ = s2/cnt − μμᵀ.
        # Ties at the k-th distance admit a few extra equidistant
        # neighbors — immaterial to a covariance.
        q, kp, kk = args
        bad = ~kk[..., None, :]  # self included iff it survived SOR
        d2 = dists(q, kp, bad)
        cd, _ = jax.lax.approx_min_k(d2.reshape(-1, d2.shape[-1]),
                                     k_normals, recall_target=0.99)
        kth = jnp.max(jnp.where(jnp.isfinite(cd), cd, 0.0), axis=1)
        W = (d2 <= kth.reshape(q.shape[0], block)[..., None]).astype(
            jnp.float32) * (~bad).astype(jnp.float32)     # (C, B, 3B)
        cnt = jnp.maximum(jnp.sum(W, axis=2), 1.0)        # (C, B)
        s1 = jnp.einsum("cbn,cni->cbi", W, kp, precision=hi)
        # Six unique second moments of the window points.
        ii = jnp.asarray([0, 0, 0, 1, 1, 2], jnp.int32)
        jj = jnp.asarray([0, 1, 2, 1, 2, 2], jnp.int32)
        op = kp[..., ii] * kp[..., jj]                    # (C, 3B, 6)
        s2 = jnp.einsum("cbn,cnu->cbu", W, op, precision=hi)
        mu_n = s1 / cnt[..., None]
        cov6 = s2 / cnt[..., None] - mu_n[..., ii] * mu_n[..., jj]
        C = jnp.stack([
            jnp.stack([cov6[..., 0], cov6[..., 1], cov6[..., 2]], -1),
            jnp.stack([cov6[..., 1], cov6[..., 3], cov6[..., 4]], -1),
            jnp.stack([cov6[..., 2], cov6[..., 4], cov6[..., 5]], -1),
        ], -2)                                            # (C, B, 3, 3)
        nrm = smallest_eigenvector_sym3(C.reshape(-1, 3, 3))
        return nrm, jnp.sum(W, axis=2).astype(jnp.int32).reshape(-1)

    nrm_s, cnt_s = jax.lax.map(phase2, (g(bp), g(cp), g(ck)))
    nrm_s = nrm_s.reshape(-1, 3)[: nb * block]
    cnt_s = cnt_s.reshape(-1)[: nb * block]
    keep_s = keep_flat[: nb * block]

    # --- un-sort: ONE packed scatter (padding rows → dump slot) ---------
    packed = jnp.concatenate([
        nrm_s,
        keep_s[:, None].astype(jnp.float32),
        cnt_s[:, None].astype(jnp.float32),
    ], axis=1)                                            # (rows, 5)
    pos = jnp.where(jnp.arange(nb * block) < n, orig_s[: nb * block], n)
    out = jnp.zeros((n + 1, 5), jnp.float32).at[pos].set(packed)[:n]
    keep = out[:, 3] > 0.5
    normals = out[:, :3]
    nvalid = keep & (out[:, 4] >= 3)
    return keep, normals, nvalid


def sor_normals(
    points: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    nb_neighbors: int = 20,
    std_ratio: float = 2.0,
    k_normals: int = 30,
    block: int = 256,
    chunk_blocks: int = 64,
):
    """Fused SOR → normals-on-survivors (module docstring).

    Returns ``(keep (N,) bool, normals (N,3), normal_valid (N,))`` —
    byte-compatible with calling ``statistical_outlier_removal`` followed
    by ``estimate_normals(valid=keep)``, at roughly half the wall clock
    (one sort, shared layout, no (N,k,3) gather).
    """
    points = jnp.asarray(points, jnp.float32)
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    if 3 * block < max(nb_neighbors + 1, k_normals):
        raise ValueError(f"block {block} too small for nb={nb_neighbors}/"
                         f"k={k_normals}")
    return _sor_normals_impl(points, valid, jnp.float32(std_ratio),
                             nb_neighbors, k_normals, block, chunk_blocks)
