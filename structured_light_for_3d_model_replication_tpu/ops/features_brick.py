"""Brick-layout FPFH — the contiguous-memory engine for the ring preprocess.

The gather-form FPFH (`ops/features.py`) is memory-bound on this backend:
its two random row gathers (neighbor positions+normals, then neighbor
SPFHs) move ~130 MB per 8k-point view at the TPU's pathological
random-gather bandwidth (~12 GB/s effective, round-4 XProf), and the
100-wide KNN sweep that feeds them exists only to produce those neighbor
lists. This engine removes both costs with the layout trick of
`ops/brickknn_pallas.py`, in pure XLA:

1. quantize once into cells of edge = ``radius`` (so a query's full
   neighbor ball is covered by its 3³ cell neighborhood), sort by packed
   cell id, pack each occupied cell into a ``slots``-wide static brick;
2. every query ROW (sorted order, no slot padding — a slot-overflow
   point still queries, it just stops appearing as a candidate) gathers
   its cell's 27 neighbor bricks as whole contiguous (S, ·) blocks;
3. Darboux angles + histogram run over the (27·S) candidate lanes with a
   radius mask — no per-pair index lists anywhere;
4. the SPFH table is re-read brick-wise for the weighted FPFH
   aggregation, again as whole bricks.

**Round-5 measurement (tunneled v5e, 24×8192 ring shape): this XLA form
LOSES — 2169 ms vs 556 ms for the gather engine — and is therefore NOT
the default.** The stage probe (`scripts/probe_fpfh_brick.py`) shows
why: with row-level queries the 27-brick gather alone is 1178 ms (each
row materializes its own 27·S·8-value candidate copy ≈ 27 KB/row, 10×
the gather engine's 2.8 KB/row neighbor rows), and cell-level queries
would share those gathers across S rows but multiply the 864-lane
Darboux/histogram work by the slot padding — the same 8.6× pair-work
regression the round-4 windowed-FPFH analysis predicted. The layout only
wins inside a Mosaic kernel that holds the 27 bricks in VMEM across a
cell's queries and streams the histogram without materializing pair
tensors; this module stays as the tested reference semantics for that
kernel (CPU parity pinned in tests/test_features_brick.py).

Semantic difference vs the gather engine, by design: the reference's
``KDTreeSearchParamHybrid(radius, max_nn=100)`` caps each histogram at
the 100 NEAREST in-radius neighbors — an efficiency bound on a CPU
k-d tree (`server/processing.py:92-94`), not part of the FPFH
definition. This engine histograms ALL in-radius pairs (up to the slot
capacity), i.e. the textbook estimator; sub-histograms are L1-normalized
either way, so descriptors agree closely (pinned in
tests/test_features_brick.py) and the registration quality gates
(ring-fitness floor in bench.py, ground-truth pose tests) hold
end-to-end.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .brickknn import (
    _BIG,
    _BITS,
    _floor_cell_edge,
    _GRID_MAX,
    _quantize_cells,
    _sorted_segments,
)
from .features import FPFH_DIM, N_BINS, _bin
from ..utils.log import get_logger

log = get_logger(__name__)

__all__ = ["fpfh_brick", "emit_overflow_warning"]


def _cell_ids(points, valid, h):
    """Packed cell id per point at cell edge ``h`` — the shared brickknn
    quantize (floored so a wide cloud still fits the 10-bit grid; larger
    cells stay exact here because the radius mask reapplies)."""
    h, mins = _floor_cell_edge(points, valid, h)
    return _quantize_cells(points, valid, h, mins)


def _row_neighbor_bricks(cid_s, ucid, m_cells):
    """(N, 27) brick index (m_cells = absent sentinel) for every sorted
    ROW's 3³ cell neighborhood — per row, not per cell, so rows whose
    cell fell past the brick budget still query their neighborhood."""
    x = cid_s >> (2 * _BITS)
    y = (cid_s >> _BITS) & _GRID_MAX
    z = cid_s & _GRID_MAX
    deltas = jnp.asarray([(dx, dy, dz) for dx in (-1, 0, 1)
                          for dy in (-1, 0, 1) for dz in (-1, 0, 1)],
                         jnp.int32)
    nxyz = jnp.stack([x, y, z], -1)[:, None, :] + deltas[None]
    in_grid = jnp.all((nxyz >= 0) & (nxyz <= _GRID_MAX), axis=-1) \
        & (cid_s < _BIG)[:, None]
    ncid = (nxyz[..., 0] << (2 * _BITS)) | (nxyz[..., 1] << _BITS) \
        | nxyz[..., 2]
    pos = jnp.searchsorted(ucid, jnp.where(in_grid, ncid, _BIG)
                           ).astype(jnp.int32)
    pos_c = jnp.minimum(pos, m_cells - 1)
    return jnp.where(in_grid & (ucid[pos_c] == ncid), pos_c, m_cells)


@functools.partial(jax.jit,
                   static_argnames=("slots", "max_cells", "chunk_rows"))
def fpfh_brick(
    points: jnp.ndarray,
    normals: jnp.ndarray,
    radius: float,
    valid: jnp.ndarray | None = None,
    slots: int = 48,
    max_cells: int = 1024,
    chunk_rows: int = 512,
):
    """(N, 33) float32 FPFH descriptors, (N,) validity, and the scalar
    overflow count, in brick layout.

    ``slots`` bounds per-cell candidate capacity (at the ring shape —
    3 mm voxel grid, 15 mm cells — a surface patch holds ~25 points, so
    48 covers dense curvature; overflow thins candidates, never drops a
    query). ``max_cells`` bounds the occupied-cell budget;
    ``chunk_rows`` is the lax.map tile that keeps the (rows, 27·S)
    broadcast intermediates inside a sane working set under the ring
    program's 24-view vmap.

    The third return value counts valid points lost to slot/cell-budget
    overflow: they still receive a descriptor (overflow never drops a
    QUERY row) but stop appearing as candidates in their neighbors'
    histograms, silently thinning descriptors when the cloud outgrows
    the (slots, max_cells) ring shape.  Same channel discipline as
    ``brick_knn``'s drop count: in-graph scalar for traced callers,
    :func:`emit_overflow_warning` for eager ones (no host callbacks
    from jitted code — see brickknn._emit_drop_warning for why).
    """
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    pts = jnp.asarray(points, jnp.float32)
    nrm = jnp.asarray(normals, jnp.float32)
    r2 = jnp.float32(radius * radius)
    S, M = slots, max_cells
    hi = jax.lax.Precision.HIGHEST

    cid = _cell_ids(pts, valid, jnp.float32(radius))
    (cid_s, pts_s, val_s, orig_s, _first, _rank, ok, dest,
     ucid) = _sorted_segments(pts, valid, cid, S, M)
    nrm_s = nrm[orig_s]

    # Brick tables (the trailing dump row absorbs overflow writes).
    def brick(vals, fill, dtype):
        shape = (M * S + 1,) + vals.shape[1:]
        t = jnp.full(shape, fill, dtype).at[dest].set(vals)
        return t[:-1].reshape((M, S) + vals.shape[1:])

    bp = brick(pts_s, 0.0, jnp.float32)
    bn = brick(nrm_s, 0.0, jnp.float32)
    bv = brick(ok, False, bool)
    bo = brick(orig_s, -1, jnp.int32)
    pad = lambda t, fill: jnp.concatenate(
        [t, jnp.full((1,) + t.shape[1:], fill, t.dtype)])
    bppad, bnpad, bvpad, bopad = (pad(bp, 0.0), pad(bn, 0.0),
                                  pad(bv, False), pad(bo, -1))

    nbr = _row_neighbor_bricks(cid_s, ucid, M)  # (N, 27)

    def pair_geometry(q, qo, qv, nb):
        """Shared candidate geometry for both stages: positions d² and
        the radius/self/validity pair mask over the 27·S lanes."""
        c = q.shape[0]
        kp = bppad[nb].reshape(c, 27 * S, 3)
        kv = bvpad[nb].reshape(c, 27 * S)
        ko = bopad[nb].reshape(c, 27 * S)
        q2 = jnp.sum(q * q, axis=-1, keepdims=True)          # (c, 1)
        p2 = jnp.sum(kp * kp, axis=-1)                        # (c, 27S)
        cross = jnp.einsum("cd,cnd->cn", q, kp, precision=hi)
        d2 = q2 + p2 - 2.0 * cross
        pair_ok = kv & (d2 <= r2) & (ko != qo[:, None]) & qv[:, None]
        return kp, d2, pair_ok

    def spfh_chunk(args):
        q, qn, qo, qv, nb = args
        c = q.shape[0]
        kp, d2, pair_ok = pair_geometry(q, qo, qv, nb)
        kn = bnpad[nb].reshape(c, 27 * S, 3)

        dvec = kp - q[:, None, :]
        dist = jnp.sqrt(jnp.maximum(jnp.sum(dvec * dvec, axis=-1), 1e-20))
        dn = dvec / dist[..., None]
        u = jnp.broadcast_to(qn[:, None, :], dvec.shape)
        v = jnp.cross(u, dn)
        v_norm = jnp.linalg.norm(v, axis=-1, keepdims=True)
        v = v / jnp.where(v_norm > 1e-12, v_norm, 1.0)
        w = jnp.cross(u, v)

        alpha = jnp.sum(v * kn, axis=-1)
        phi = jnp.sum(u * dn, axis=-1)
        theta = jnp.arctan2(jnp.sum(w * kn, axis=-1),
                            jnp.sum(u * kn, axis=-1))
        bins = jnp.stack([
            _bin(alpha, -1.0, 1.0),
            _bin(phi, -1.0, 1.0),
            _bin(theta, -jnp.pi, jnp.pi),
        ], axis=-1)  # (c, 27S, 3)
        onehot = jax.nn.one_hot(bins, N_BINS, dtype=jnp.float32)
        onehot = onehot * pair_ok[..., None, None]
        spfh = onehot.sum(axis=1).reshape(c, FPFH_DIM)
        cnt = jnp.sum(pair_ok, axis=1)
        return spfh / jnp.maximum(cnt, 1)[:, None].astype(jnp.float32), cnt

    # Chunked over sorted rows; every op inside is slot-count-free on the
    # query side, so padding waste is zero whatever the cell occupancy.
    pad_r = (-n) % chunk_rows

    def padded(x, fill):
        return jnp.concatenate(
            [x, jnp.full((pad_r,) + x.shape[1:], fill, x.dtype)]
        ) if pad_r else x

    def chunked(x):
        return x.reshape((-1, chunk_rows) + x.shape[1:])

    q_r = chunked(padded(pts_s, 0.0))
    qn_r = chunked(padded(nrm_s, 0.0))
    qo_r = chunked(padded(orig_s, -1))
    qv_r = chunked(padded(val_s, False))
    nb_r = chunked(padded(nbr, M))

    spfh_s, cnt_s = jax.lax.map(
        spfh_chunk, (q_r, qn_r, qo_r, qv_r, nb_r))
    spfh_s = spfh_s.reshape(-1, FPFH_DIM)[:n]
    cnt_s = cnt_s.reshape(-1)[:n]

    # SPFH brick table for the aggregation stage (same dump-row scatter).
    bs = jnp.zeros((M * S + 1, FPFH_DIM), jnp.float32).at[dest].set(
        jnp.where(ok[:, None], spfh_s, 0.0))
    bspad = jnp.concatenate(
        [bs[:-1].reshape(M, S, FPFH_DIM),
         jnp.zeros((1, S, FPFH_DIM), jnp.float32)])

    spfh_r = chunked(padded(spfh_s, 0.0))

    def fpfh_chunk(args):
        q, qo, qv, nb, own = args
        c = q.shape[0]
        _, d2, pair_ok = pair_geometry(q, qo, qv, nb)
        ks = bspad[nb].reshape(c, 27 * S, FPFH_DIM)
        dist = jnp.sqrt(jnp.maximum(d2, 1e-20))
        wgt = jnp.where(pair_ok, 1.0 / jnp.maximum(dist, 1e-12), 0.0)
        wsum = jnp.maximum(jnp.sum(wgt, axis=1), 1e-12)[:, None]
        return own + jnp.einsum("cn,cnf->cf", wgt, ks,
                                precision=hi) / wsum

    f_s = jax.lax.map(fpfh_chunk, (q_r, qo_r, qv_r, nb_r, spfh_r))
    f_s = f_s.reshape(-1, FPFH_DIM)[:n]

    f3 = f_s.reshape(n, 3, N_BINS)
    s = jnp.maximum(jnp.sum(f3, axis=-1, keepdims=True), 1e-12)
    f_s = (100.0 * f3 / s).reshape(n, FPFH_DIM)

    fv_s = val_s & (cnt_s >= 1)
    f_s = jnp.where(fv_s[:, None], f_s, 0.0)

    # Back to original row order (row scatter, unique destinations).
    rows = jnp.where(orig_s >= 0, orig_s, n)
    out_f = jnp.zeros((n + 1, FPFH_DIM), jnp.float32).at[rows].set(f_s)[:n]
    out_v = jnp.zeros((n + 1,), bool).at[rows].set(fv_s)[:n]
    # Valid rows whose brick slot was thinned away (candidate-side loss).
    n_overflow = jnp.sum(val_s & ~ok)
    return out_f, out_v, n_overflow


def emit_overflow_warning(n_overflow, n_total) -> None:
    """Surface candidate thinning at runtime — EAGER calls only (under a
    jit the count is a tracer and nothing is staged; traced consumers
    read the returned count instead)."""
    if isinstance(n_overflow, jax.core.Tracer):
        return
    no = int(n_overflow)
    if no > 0:
        log.warning(
            "fpfh_brick thinned %d/%d points out of the candidate set "
            "(cell-slot overflow or cell budget); their neighbors' "
            "descriptors are computed from fewer pairs — raise "
            "`slots`/`max_cells` (MergeParams.fpfh_slots/fpfh_max_cells) "
            "for full coverage", no, int(n_total))
