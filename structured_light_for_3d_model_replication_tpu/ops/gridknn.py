"""Spatial-grid KNN on device — the large-N engine behind :func:`..ops.knn.knn`.

The dense tiled-matmul KNN is O(N·M) and owns the small/medium regime, but a
1M-point cloud pays 10¹² distance evaluations for neighbors that are all
within a few voxels. This module buckets points into a uniform grid and
evaluates only the 27-cell neighborhood of each query — O(N·27C) with a
static per-cell candidate capacity C — entirely with XLA-friendly static
shapes:

1. cell size: estimated in-program from a sampled k-th-NN distance (a
   (S×P) brute-force block over strided subsets — exact enough to pick a
   scale), so callers never tune it;
2. one sort of packed 30-bit cell ids groups the points; per-cell segments
   are found by binary search (no hash tables, no dynamic shapes);
3. each query gathers ≤ C candidates from each of its 27 neighbor cells
   (capacity overflow drops the tail of a cell's segment — a bounded,
   documented approximation, like the two-stage ``approx_min_k`` path);
4. candidate distances reduce with one small exact top-k per query tile.

Returns the same (sq_dists, indices, neighbor_valid) contract as
:func:`..ops.knn.knn`, distances ascending. Accuracy: exact whenever every
true k-NN lies within one cell radius and its cell holds ≤ C points —
by construction of the cell-size estimate that covers the overwhelming
majority of queries; the miss modes degrade to near-neighbors, which the
statistical consumers (SOR, PCA normals, FPFH) absorb.

The reference delegates these queries to Open3D's C++ KDTree
(`server/processing.py:64,87,154`); a pointer-chasing tree maps terribly to
a vector unit, a sort + gather grid maps perfectly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BITS = 10           # 10 bits per axis → 1024³ addressable cells, id < 2³⁰
_GRID_MAX = (1 << _BITS) - 1


def _estimate_cell_size(points, valid, k):
    """Median sampled k-th-NN distance — the radius a cell must cover."""
    n = points.shape[0]
    s = max(1, n // 1024)
    p = max(1, n // 8192)
    # Index-array gathers, NOT strided slices: `points[::1024]` lowers to
    # a sequential dynamic-slice loop on TPU — XProf measured ~4.7 s of a
    # 1M-point brick_knn call inside these two sample lines. A small
    # explicit gather is microseconds.
    qi = jnp.arange(min(1024, (n + s - 1) // s), dtype=jnp.int32) * s
    pi = jnp.arange(min(8192, (n + p - 1) // p), dtype=jnp.int32) * p
    q_samp = points[qi]
    qv = valid[qi]
    p_samp = points[pi]
    pv = valid[pi]
    d2 = jnp.sum((q_samp[:, None, :] - p_samp[None, :, :]) ** 2, axis=-1)
    d2 = jnp.where(pv[None, :], d2, jnp.inf)
    kk = min(k + 1, p_samp.shape[0])  # +1: the sample may contain the query
    neg_top, _ = jax.lax.top_k(-d2, kk)
    kth = jnp.sqrt(jnp.maximum(-neg_top[:, -1], 1e-20))
    kth = jnp.where(qv & jnp.isfinite(kth), kth, jnp.nan)
    med = jnp.nanmedian(kth)
    # The sampled point set is p× sparser than the real one: k-th-NN
    # distance scales ~ (density)^(-1/3) for volumetric and ^(-1/2) for
    # surface data; use the (conservative) surface exponent.
    scale = jnp.float32(p) ** -0.5
    med = med * scale
    return jnp.where(jnp.isfinite(med) & (med > 0), med, jnp.float32(1.0))


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _grid_knn_impl(points, valid, k, capacity, q_tile, exclude_self):
    n = points.shape[0]
    h = _estimate_cell_size(points, valid, k)

    # Clamped 10-bit cell coordinates. If the cloud spans more than 1024
    # cells on an axis, the grid coarsens (h grows) instead of wrapping.
    mins = jnp.min(jnp.where(valid[:, None], points, jnp.inf), axis=0)
    maxs = jnp.max(jnp.where(valid[:, None], points, -jnp.inf), axis=0)
    extent = jnp.max(maxs - mins)
    h = jnp.maximum(h, extent / (_GRID_MAX - 2) + 1e-12)
    cell = jnp.clip(((points - mins) / h).astype(jnp.int32), 0, _GRID_MAX)
    cid = (cell[:, 0] << (2 * _BITS)) | (cell[:, 1] << _BITS) | cell[:, 2]
    cid = jnp.where(valid, cid, jnp.int32(1 << 30))  # invalid sorts last

    order = jnp.argsort(cid)
    cid_sorted = cid[order]

    # ARITHMETIC offsets (bitwise composition breaks for negative deltas):
    # q_cid + dx·2²⁰ + dy·2¹⁰ + dz equals the packed id of the neighbor
    # cell whenever the neighbor coordinates stay in range. When they do
    # NOT (query on a grid boundary), the arithmetic borrows/carries into
    # the adjacent axis field and the sum aliases the packed id of a REAL
    # far-away cell — e.g. (x, 0, z) + dy=-1 → (x-1, 1023, z) — whose
    # candidates would pass the id-equality check while being geometrically
    # distant. Each offset therefore carries its per-axis delta so the
    # query can mask offsets whose neighbor coordinate leaves [0, 2¹⁰).
    deltas = [(dx, dy, dz)
              for dx in (-1, 0, 1) for dy in (-1, 0, 1) for dz in (-1, 0, 1)]
    neighbor_offsets = jnp.asarray(
        [dx * (1 << (2 * _BITS)) + dy * (1 << _BITS) + dz
         for dx, dy, dz in deltas], jnp.int32)
    delta_xyz = jnp.asarray(deltas, jnp.int32)  # (27, 3)

    pts_sorted = points[order]

    def per_tile(args):
        q, q_cid, q_idx, qv = args  # (T,3) (T,) (T,) (T,)
        # 27 candidate cell ids per query; offsets whose per-axis neighbor
        # coordinate leaves the grid are masked (see aliasing note above).
        cand_cid = q_cid[:, None] + neighbor_offsets[None, :]  # (T, 27)
        q_xyz = jnp.stack([q_cid >> (2 * _BITS),
                           (q_cid >> _BITS) & _GRID_MAX,
                           q_cid & _GRID_MAX], axis=-1)        # (T, 3)
        nb_xyz = q_xyz[:, None, :] + delta_xyz[None, :, :]     # (T, 27, 3)
        in_grid = jnp.all((nb_xyz >= 0) & (nb_xyz <= _GRID_MAX), axis=-1)
        start = jnp.searchsorted(cid_sorted, cand_cid.reshape(-1),
                                 side="left").reshape(cand_cid.shape)
        # Candidate slots: start + 0..C-1 in the sorted order.
        slots = start[:, :, None] + jnp.arange(capacity, dtype=jnp.int32)
        slots_c = jnp.minimum(slots, n - 1)
        ok = (slots < n) & in_grid[:, :, None] \
            & (cid_sorted[slots_c] == cand_cid[:, :, None])
        cand = pts_sorted[slots_c]                      # (T, 27, C, 3)
        orig = order[slots_c]                            # (T, 27, C)
        d2 = jnp.sum((q[:, None, None, :] - cand) ** 2, axis=-1)
        if exclude_self:
            ok = ok & (orig != q_idx[:, None, None])
        d2 = jnp.where(ok, d2, jnp.inf)
        d2f = d2.reshape(q.shape[0], -1)
        origf = orig.reshape(q.shape[0], -1)
        # PartialReduce candidate selection + tiny exact sort for ascending
        # order (the same two-stage shape as the dense approx path).
        cd, carg = jax.lax.approx_min_k(d2f, k)
        ci = jnp.take_along_axis(origf, carg, axis=1)
        neg, arg = jax.lax.top_k(-cd, k)
        idx = jnp.take_along_axis(ci, arg, axis=1)
        dd = -neg
        nb_ok = jnp.isfinite(dd) & qv[:, None]
        return jnp.where(jnp.isfinite(dd), dd, 0.0), idx, nb_ok

    pad = (-n) % q_tile
    qp = jnp.concatenate([points, jnp.zeros((pad, 3), points.dtype)]) \
        if pad else points
    cp = jnp.concatenate([cid, jnp.full((pad,), 1 << 30, jnp.int32)]) \
        if pad else cid
    vp = jnp.concatenate([valid, jnp.zeros(pad, bool)]) if pad else valid
    ip = jnp.arange(qp.shape[0], dtype=jnp.int32)
    tiles = qp.shape[0] // q_tile
    d, i, v = jax.lax.map(per_tile, (
        qp.reshape(tiles, q_tile, 3),
        cp.reshape(tiles, q_tile),
        ip.reshape(tiles, q_tile),
        vp.reshape(tiles, q_tile)))
    return (d.reshape(-1, k)[:n], i.reshape(-1, k)[:n],
            v.reshape(-1, k)[:n])


def grid_knn(
    points: jnp.ndarray,
    k: int,
    points_valid: jnp.ndarray | None = None,
    exclude_self: bool = False,
    capacity: int = 16,
    q_tile: int = 8192,
):
    """Self-query KNN over a spatial grid (see module docstring).

    Same contract as ``knn(points, k, exclude_self=...)``: returns
    (sq_dists (N,k), indices (N,k), neighbor_valid (N,k)), ascending.
    """
    points = jnp.asarray(points, jnp.float32)
    n = points.shape[0]
    if points_valid is None:
        points_valid = jnp.ones(n, dtype=bool)
    if 27 * capacity < k:
        raise ValueError(f"capacity {capacity} too small for k={k}")
    return _grid_knn_impl(points, points_valid, k, capacity,
                          min(q_tile, max(256, n)), exclude_self)
