"""Plane segmentation (RANSAC) — background/wall removal.

Replaces Open3D ``segment_plane`` as used for background removal
(`server/processing.py:37-39`: distance_threshold, ransac_n=3,
num_iterations; `Old/blackground_remove.py:10-16`): find the dominant plane,
then DROP its inliers to keep the scanned object.

All hypotheses are vmapped: sample 3 points per hypothesis, get the plane
from one cross product, score every point densely, argmax — no sequential
trial loop, no early exit (finishing the batch is cheaper on TPU than a
data-dependent branch). A least-squares refit on the winning inlier set
polishes the model like Open3D does.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .pointcloud import smallest_eigenvector_sym3


@functools.partial(jax.jit, static_argnames=("num_iterations",))
def segment_plane(
    points: jnp.ndarray,
    distance_threshold: float = 10.0,
    num_iterations: int = 1000,
    valid: jnp.ndarray | None = None,
    key=None,
):
    """Returns (plane (4,) [a,b,c,d] with ‖n‖=1, inlier_mask (N,)).

    ``remove_background`` keeps ~inlier_mask (`server/processing.py:42`).
    """
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    if key is None:
        key = jax.random.PRNGKey(0)
    pts = jnp.asarray(points, jnp.float32)
    vf = valid.astype(jnp.float32)

    def hypothesis(k):
        i = jax.random.randint(k, (3,), 0, n)
        p0, p1, p2 = pts[i[0]], pts[i[1]], pts[i[2]]
        nrm = jnp.cross(p1 - p0, p2 - p0)
        ln = jnp.linalg.norm(nrm)
        ok = (ln > 1e-12) & jnp.all(valid[i])
        nrm = nrm / jnp.where(ln > 1e-12, ln, 1.0)
        d = -jnp.dot(nrm, p0, precision=jax.lax.Precision.HIGHEST)
        dist = jnp.abs(jnp.einsum("ni,i->n", pts, nrm,
                                  precision=jax.lax.Precision.HIGHEST) + d)
        cnt = jnp.sum((dist <= distance_threshold) * vf)
        return jnp.concatenate([nrm, d[None]]), jnp.where(ok, cnt, -1.0)

    # Hypotheses in vmapped batches under a scan: one (batch, N) distance
    # block resident at a time, best-so-far carried through.
    batch = min(256, num_iterations)
    n_batches = max(1, num_iterations // batch)

    def batch_step(carry, k):
        best_plane, best_cnt = carry
        planes, cnts = jax.vmap(hypothesis)(jax.random.split(k, batch))
        i = jnp.argmax(cnts)
        better = cnts[i] > best_cnt
        return (jnp.where(better, planes[i], best_plane),
                jnp.where(better, cnts[i], best_cnt)), None

    init = (jnp.array([0.0, 0.0, 1.0, 0.0], jnp.float32), jnp.float32(-1))
    (best, _), _ = jax.lax.scan(batch_step, init,
                                jax.random.split(key, n_batches))

    inl = (jnp.abs(pts @ best[:3] + best[3]) <= distance_threshold) & valid

    # Least-squares refit on the inliers: plane normal = smallest principal
    # direction of the inlier scatter (same polish Open3D applies).
    w = inl.astype(jnp.float32)[:, None]
    cnt = jnp.maximum(jnp.sum(w), 1.0)
    mu = jnp.sum(pts * w, axis=0) / cnt
    xc = (pts - mu) * w
    C = jnp.einsum("ni,nj->ij", xc, xc,
                   precision=jax.lax.Precision.HIGHEST) / cnt
    nrm = smallest_eigenvector_sym3(C)
    d = -jnp.dot(nrm, mu, precision=jax.lax.Precision.HIGHEST)
    refit = jnp.concatenate([nrm, d[None]])
    refit_inl = (jnp.abs(jnp.einsum("ni,i->n", pts, nrm,
                                    precision=jax.lax.Precision.HIGHEST) + d)
                 <= distance_threshold) & valid
    use_refit = jnp.sum(refit_inl) >= jnp.sum(inl)
    plane = jnp.where(use_refit, refit, best)
    inliers = jnp.where(use_refit, refit_inl, inl)
    return plane, inliers
