"""Pallas TPU kernel for the band-sparse screened-Poisson CG matvec.

The XLA matvec (`poisson_sparse._lap_band_flat` − W·x) is ~35-40 ms per
application at the 1M-point depth-10 shape (~183k active blocks) and the
Jacobi-PCG applies it ~70 times — ~2.6 s of the 5.9 s solve. Its cost is
pure memory choreography: six lane-rolls over the (M, 512) band, a
(M, 6, 64) face extraction, six neighbor-row gathers and six one-hot
placement matmuls, each materializing full-band intermediates (round-5
probe: concatenating the placement matmuls or lowering the interior
stencil to conv3d both measured level-or-worse — XLA has no cheaper
schedule for this op graph).

Two kernels, both measured at the 1M/depth-10 shape on the tunneled
v5e (XLA baseline 52 ms/apply, burst-amortized):

* ``matvec_pallas`` (v1) — whole-brick DMA: per block, six
  ``make_async_copy`` reads of the neighbor (512,) rows (absent → zero
  dump row), stencil + placement as masked lane-rolls in VMEM. In the
  flat layout (idx = (ix·8+iy)·8+iz) every cross-brick face placement
  is a roll — +x: roll(nb, 448) at ix=7, +y: roll(nb, 56) at iy=7, +z:
  roll(nb, 7) at iz=7, mirrored negatives. Measured **DMA-ISSUE-bound**:
  46.5 / 39.0 / 36.9 ms at cb = 8/16/32 (~1.2M tiny DMAs per matvec;
  run-coalescing into range DMAs was probed and rejected — only 46 % of
  8-windows are contiguous runs on the real band, 21 % along z).
* ``matvec_pallas_v2`` — the production path (**31 ms/apply**): XLA
  pre-extracts the (M, 6, 64) face tensor and row-gathers each block's
  six halos (the part XLA is fine at), then ONE fused kernel pass does
  interior rolls + halo placement (a (cb, 384) @ (384, 512) one-hot
  MXU matmul at HIGHEST — exact) + screening + band mask, with no
  manual DMA and single-streamed traffic. What v2 removes vs pure XLA
  is the 6 separate full-band accumulator passes around the placement
  matmuls.

Same numerical contract as the XLA form (pinned by
tests/test_poisson_pallas.py in interpret mode); the XLA path stays the
oracle and CPU fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _backend
from ..utils.log import get_logger

log = get_logger(__name__)

BS = 8
V = BS ** 3          # 512 voxels per block
CB = 8               # blocks per grid step
# Direction order matches poisson_sparse's neighbor-table columns
# (+x, -x, +y, -y, +z, -z): (flat roll offset placing the neighbor's
# opposite face onto our boundary, own-boundary axis, boundary value).
_FACE_ROLLS = ((448, 0, BS - 1), (-448, 0, 0),
               (56, 1, BS - 1), (-56, 1, 0),
               (7, 2, BS - 1), (-7, 2, 0))
_INTERIOR_DELTAS = (64, -64, 8, -8, 1, -1)


def available() -> bool:
    return _backend.tpu_backend()


def _axis_coords(shape):
    """(ix, iy, iz) int32 coordinate planes over the flat lane dim."""
    flat = jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1)
    return flat // (BS * BS), (flat // BS) % BS, flat % BS


def _interior_acc(x, coords):
    """Σ_d roll(x, −δ_d)·interior_d — the in-brick 6-neighbor sum, the
    stencil core shared by BOTH kernels (v1 whole-brick-DMA and v2
    hybrid): one definition so they cannot silently diverge."""
    acc = jnp.zeros_like(x)
    for delta in _INTERIOR_DELTAS:
        ax = (0 if abs(delta) == 64 else 1 if abs(delta) == 8 else 2)
        interior = (coords[ax] < BS - 1) if delta > 0 else (coords[ax] > 0)
        acc = acc + jnp.where(interior, jnp.roll(x, -delta, axis=1), 0.0)
    return acc


def _kernel(nbr_ref, x_ref, w_ref, bv_ref, x_hbm, out_ref, nbx, sem,
            *, cb: int = CB):
    # x_hbm is (M+1, 1, V): rank-3 so the tiled (sublane, lane) dims are
    # taken WHOLE by each copy — slicing single rows of a rank-2 (M, V)
    # array violates Mosaic's 8-sublane tiling ("slice shape along
    # dimension 0 must be aligned to tiling"), the same layout trick as
    # `brickknn_pallas`'s (M, 1, 128) candidate table.
    for b in range(cb):
        for d in range(6):
            pltpu.make_async_copy(
                x_hbm.at[nbr_ref[b, d]], nbx.at[b, d], sem.at[b, d]
            ).start()

    x = x_ref[...]                                   # (cb, V)
    coords = _axis_coords((cb, V))
    acc = _interior_acc(x, coords)

    for b in range(cb):
        for d in range(6):
            pltpu.make_async_copy(
                x_hbm.at[nbr_ref[b, d]], nbx.at[b, d], sem.at[b, d]
            ).wait()
    nb = nbx[...]                                    # (cb, 6, 1, V)
    for d, (off, ax, wall) in enumerate(_FACE_ROLLS):
        halo = jnp.roll(nb[:, d, 0, :], off, axis=1)
        acc = acc + jnp.where(coords[ax] == wall, halo, 0.0)

    out_ref[...] = bv_ref[...] * ((6.0 + w_ref[...]) * x - acc)


def _kernel_v2(x_ref, w_ref, bv_ref, halo_ref, place_ref, out_ref, *,
               cb: int):
    x = x_ref[...]                                   # (cb, V)
    acc = _interior_acc(x, _axis_coords((cb, V)))
    # Halo placement: one (cb, 384) @ (384, 512) one-hot matmul on the
    # MXU — exact at HIGHEST (one-hot rows), resident block constants.
    acc = acc + jax.lax.dot_general(
        halo_ref[...], place_ref[...], (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)
    out_ref[...] = bv_ref[...] * ((6.0 + w_ref[...]) * x - acc)


@functools.partial(jax.jit, static_argnames=("interpret", "cb"))
def matvec_pallas_v2(x, W, nbr, block_valid, interpret: bool = False,
                     cb: int = 32):
    """Hybrid form: XLA extracts the (M, 6, 64) face tensor and gathers
    each block's six halo rows (cheap fused gathers), then a single
    fused kernel pass does interior rolls + halo placement + screening —
    no manual DMAs at all (v1's 6-DMAs-per-block form measured
    DMA-issue-bound: 46.5/39.0/36.9 ms at cb 8/16/32 vs XLA's 51.4)."""
    from .poisson_sparse import _FACES_ALL, _OPP, _PLACE

    m = x.shape[0]
    faces = x[:, jnp.asarray(_FACES_ALL, jnp.int32)].reshape(m, 6, BS * BS)
    fpad = jnp.concatenate([faces, jnp.zeros((1, 6, BS * BS), x.dtype)])
    mq = jnp.minimum(nbr, m)  # absent -> zero dump row
    halos = jnp.stack([fpad[:, _OPP[d], :][mq[:, d]] for d in range(6)],
                      axis=1).reshape(m, 6 * BS * BS)
    place_all = jnp.concatenate([jnp.asarray(_PLACE[d], jnp.float32)
                                 for d in range(6)],
                                axis=0)                    # (384, 512)

    mp = ((m + cb - 1) // cb) * cb
    pad = mp - m

    def padr(a):
        return jnp.concatenate(
            [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]) if pad else a

    out = pl.pallas_call(
        functools.partial(_kernel_v2, cb=cb),
        grid=(mp // cb,),
        in_specs=[
            pl.BlockSpec((cb, V), lambda c: (c, 0)),
            pl.BlockSpec((cb, V), lambda c: (c, 0)),
            pl.BlockSpec((cb, 1), lambda c: (c, 0)),
            pl.BlockSpec((cb, 6 * BS * BS), lambda c: (c, 0)),
            pl.BlockSpec((6 * BS * BS, V), lambda c: (0, 0)),
        ],
        out_specs=pl.BlockSpec((cb, V), lambda c: (c, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, V), jnp.float32),
        interpret=interpret,
    )(padr(x), padr(W), padr(block_valid.astype(jnp.float32)[:, None]),
      padr(halos), place_all)
    return out[:m]


@functools.partial(jax.jit, static_argnames=("interpret", "cb"))
def matvec_pallas(x, W, nbr, block_valid, interpret: bool = False,
                  cb: int = CB):
    """One screened-stencil matvec: ``bvalid·((6+W)x − neighbor_sum(x))``
    — identical to ``-(lap_band(x) − W·x)`` masked to the band, i.e. the
    operator `poisson_sparse._cg_sparse` applies each PCG iteration.

    ``x``/``W`` are (M, 512) flat bricks, ``nbr`` (M, 6) neighbor slots
    with M = absent, ``block_valid`` (M,) bool. M is padded to the CB
    grid multiple here; the dump row serves absent neighbors.
    """
    m = x.shape[0]
    mp = ((m + cb - 1) // cb) * cb
    pad = mp - m
    # Dump row (zeros) at index mp for absent/overflow neighbor slots.
    xp = jnp.concatenate(
        [x, jnp.zeros((pad + 1, V), x.dtype)])
    wp = jnp.concatenate([W, jnp.zeros((pad, V), W.dtype)]) if pad else W
    bv = jnp.concatenate(
        [block_valid.astype(jnp.float32),
         jnp.zeros((pad,), jnp.float32)]) if pad else \
        block_valid.astype(jnp.float32)
    nbp = jnp.where(nbr >= m, mp, nbr).astype(jnp.int32)
    if pad:
        nbp = jnp.concatenate(
            [nbp, jnp.full((pad, 6), mp, jnp.int32)])

    out = pl.pallas_call(
        functools.partial(_kernel, cb=cb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(mp // cb,),
            in_specs=[
                pl.BlockSpec((cb, 6), lambda c: (c, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((cb, V), lambda c: (c, 0)),
                pl.BlockSpec((cb, V), lambda c: (c, 0)),
                pl.BlockSpec((cb, 1), lambda c: (c, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((cb, V), lambda c: (c, 0)),
            scratch_shapes=[
                pltpu.VMEM((cb, 6, 1, V), jnp.float32),
                pltpu.SemaphoreType.DMA((cb, 6)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((mp, V), jnp.float32),
        interpret=interpret,
    )(nbp, xp[:mp], wp, bv[:, None], xp.reshape(mp + 1, 1, V))
    return out[:m]
