"""Pallas TPU kernel for the Gray-code stack decode.

The decode is the per-pixel hot loop of the whole scanner
(`server/sl_system.py:544-572`: 22 full-frame passes + an XOR cascade). The
XLA path (ops/decode.py) fuses it well; this kernel goes one step further
and keeps the ENTIRE per-tile working set in VMEM for one pass over HBM: a
(F, bh, W) uint8 tile streams in, the pattern/inverse compares, the
MSB-first bit-pack and the doubling-XOR Gray→binary all run on the VPU
without ever materializing an (F, H, W) intermediate, and two (bh, W)
int32 tiles stream out.

The validity mask is NOT in the kernel: its thresholds are data-dependent
scalars in adaptive mode (global percentile/max reductions), scalar
operands batch awkwardly under ``vmap`` of a ``pallas_call``, and the mask
itself is two fused element-wise compares over the reference frames — XLA
territory. The kernel owns the 22-frame reduction, which is ~95% of the
decode's memory traffic.

Grid: (H/bh) full-width row bands (every supported capture width is a
lane multiple; rows pad to the sublane multiple and slice back).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ROW_BLOCK = 64   # sublane-aligned for uint8 (32) with headroom
_LANE = 128


def _decode_kernel(stack_ref, col_ref, row_ref,
                   *, col_bits: int, row_bits: int, downsample: int):
    def unpack(base: int, n_bits: int):
        gray = jnp.zeros(col_ref.shape, jnp.int32)
        for b in range(n_bits):  # unrolled: n_bits is a compile-time const
            # Mosaic has no direct uint8 compare/float cast; hop via int32.
            bit = (stack_ref[base + 2 * b].astype(jnp.int32)
                   > stack_ref[base + 2 * b + 1].astype(jnp.int32))
            gray = gray | (bit.astype(jnp.int32) << (n_bits - 1 - b))
        # Gray → binary: doubling XOR cascade (prefix XOR over bits).
        shift = 1
        while shift < n_bits:
            gray = gray ^ (gray >> shift)
            shift <<= 1
        return gray * downsample + (downsample - 1) // 2

    col_ref[:] = unpack(2, col_bits)
    row_ref[:] = unpack(2 + 2 * col_bits, row_bits)


@functools.partial(jax.jit, static_argnums=(1, 2),
                   static_argnames=("downsample", "interpret"))
def decode_maps_pallas(
    stack: jnp.ndarray,
    col_bits: int,
    row_bits: int,
    downsample: int = 1,
    interpret: bool = False,
):
    """(F, H, W) uint8 → (col_map i32, row_map i32) — the bit-unpack half
    of ``decode.decode_stack`` as one VMEM-resident kernel."""
    f, h, w = stack.shape
    if w % _LANE:
        stack = jnp.pad(stack, ((0, 0), (0, 0), (0, (-w) % _LANE)))
    if h % _ROW_BLOCK:
        stack = jnp.pad(stack, ((0, 0), (0, (-h) % _ROW_BLOCK), (0, 0)))
    hp, wp = stack.shape[1], stack.shape[2]

    # Width blocking: keep the uint8 input tile + two int32 output tiles
    # within a conservative VMEM budget (a full-width 4K band overflows the
    # ~16 MB VMEM and crashes the Mosaic compile).
    bw = wp
    while bw > _LANE and (f * _ROW_BLOCK * bw            # uint8 input tile
                          + 8 * _ROW_BLOCK * bw) > 8_000_000:
        bw //= 2
    bw = max(bw - bw % _LANE, _LANE)
    if wp % bw:
        extra = bw - (wp % bw)
        stack = jnp.pad(stack, ((0, 0), (0, 0), (0, extra)))
        wp = stack.shape[2]

    kernel = functools.partial(_decode_kernel, col_bits=col_bits,
                               row_bits=row_bits, downsample=downsample)
    grid = (hp // _ROW_BLOCK, wp // bw)
    out_shape = [
        jax.ShapeDtypeStruct((hp, wp), jnp.int32),
        jax.ShapeDtypeStruct((hp, wp), jnp.int32),
    ]
    tile = lambda: pl.BlockSpec((_ROW_BLOCK, bw), lambda i, j: (i, j))
    col_map, row_map = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((f, _ROW_BLOCK, bw),
                               lambda i, j: (0, i, j))],
        out_specs=[tile(), tile()],
        out_shape=out_shape,
        interpret=interpret,
    )(stack)
    return col_map[:h, :w], row_map[:h, :w]
