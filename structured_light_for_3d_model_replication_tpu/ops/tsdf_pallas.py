"""Pallas TPU kernel for the TSDF running-average combine.

The XLA form of the per-stop fold (`tsdf._combine`) materializes the
weight sum, its safe reciprocal and the where-masks as separate HBM
intermediates over five (cap, 512)/(cap, 512, 3) arrays. This kernel
fuses the whole fold into one streamed pass over brick blocks: every
intermediate lives in VMEM, each brick row is read and written exactly
once. The 512-voxel brick minor dimension is 4 native (8, 128) f32
lanes (the flat-brick tile rule of `ops/poisson_pallas.py`), and the
RGB channels enter as three separate (cap, 512) planes so every operand
in the kernel shares that layout — no 3-minor relayouts for Mosaic.

Numerical contract pinned against the XLA form in interpret mode by
tests/test_fusion.py; the XLA path stays the oracle and CPU fallback
(dispatch in `tsdf.integrate` behind ``_backend.tpu_backend()``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import _backend
from .poisson_sparse import BS

_V = BS ** 3


def available() -> bool:
    return _backend.tpu_backend()


def _kernel(wmax_ref, tsdf_ref, w_ref, num_ref, den_ref,
            r_ref, g_ref, b_ref, rn_ref, gn_ref, bn_ref,
            tsdf_out, w_out, r_out, g_out, b_out):
    t = tsdf_ref[...]
    w = w_ref[...]
    den = den_ref[...]
    wsum = w + den
    inv = 1.0 / jnp.maximum(wsum, 1e-12)
    hit = den > 0.0
    tsdf_out[...] = jnp.where(hit, (t * w + num_ref[...]) * inv, t)
    w_out[...] = jnp.minimum(wsum, wmax_ref[...])
    for c_ref, cn_ref, c_out in ((r_ref, rn_ref, r_out),
                                 (g_ref, gn_ref, g_out),
                                 (b_ref, bn_ref, b_out)):
        c = c_ref[...]
        c_out[...] = jnp.where(hit, (c * w + cn_ref[...]) * inv, c)


@functools.partial(jax.jit, static_argnames=("interpret", "cb"))
def combine_pallas(tsdf, weight, rgb, num, den, rgbnum, max_weight,
                   interpret: bool = False, cb: int = 8):
    """Fused running-average fold; same contract as ``tsdf._combine``.

    ``tsdf``/``weight``/``num``/``den`` are (cap, 512) f32; ``rgb``/
    ``rgbnum`` (cap, 512, 3). ``cb`` bricks per grid step (off-multiple
    capacities fall back to cb=1; the usual power-of-two ≥ 8 capacities
    take the full-speed path)."""
    cap = tsdf.shape[0]
    if cap % cb:
        # Integration must DEGRADE, never raise (the fusion contract):
        # an off-multiple capacity falls back to one-brick grid steps —
        # slower, same numbers.
        cb = 1
    wmax = jnp.full((cb, _V), max_weight, jnp.float32)
    chans = [rgb[:, :, i] for i in range(3)]
    nchans = [rgbnum[:, :, i] for i in range(3)]
    spec = pl.BlockSpec((cb, _V), lambda c: (c, 0))
    outs = pl.pallas_call(
        _kernel,
        grid=(cap // cb,),
        in_specs=[pl.BlockSpec((cb, _V), lambda c: (0, 0))]
        + [spec] * 10,
        out_specs=[spec] * 5,
        out_shape=[jax.ShapeDtypeStruct((cap, _V), jnp.float32)] * 5,
        interpret=interpret,
    )(wmax, tsdf, weight, num, den, *chans, *nchans)
    t_new, w_new, r_new, g_new, b_new = outs
    return t_new, w_new, jnp.stack([r_new, g_new, b_new], axis=-1)
