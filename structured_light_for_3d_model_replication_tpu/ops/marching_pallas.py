"""Pallas TPU kernel for the marching-tets active-cell classification.

The XLA form of the classify pass (`marching_jax._phase_corners`) builds
the (M, 729) inside mask and then eight (M, 512) gathered corner views,
OR-ing and AND-ing them into any/all — ~17 full-band boolean
intermediates materialized in HBM. This kernel fuses the whole pass into
one streamed read of the corner frame: ``inside`` never leaves VMEM, and
the 8-corner any/all reduction is three lane-roll combines per output
(the corner offsets +1 voxel per axis are flat-index shifts of +1, +9,
+81 on the (9, 9, 9) frame — the same roll-in-flat-space idiom as
`ops/poisson_pallas.py`). Positions whose shifted read would wrap out of
the frame are never consumed: the cell outputs live at coordinates ≤ 7
per axis, and every intermediate they touch stays in-frame.

The kernel returns the any/all planes on the FULL 729 frame (f32 0/1);
the dispatcher gathers the 512 cell positions — keeping the kernel free
of the non-affine 729→512 index map.

Numerical contract pinned vs the XLA form in interpret mode by
tests/test_marching_jax.py; the XLA path stays the oracle and CPU
fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import _backend

_NC = 729            # (9, 9, 9) corner frame, flat
_SHIFTS = (1, 9, 81)  # +z, +y, +x neighbor in flat frame coords


def available() -> bool:
    return _backend.tpu_backend()


def _kernel(d_ref, any_ref, all_ref):
    ins = (d_ref[...] > 0.0).astype(jnp.float32)      # (cb, 729)
    a = ins
    b = ins
    for s in _SHIFTS:
        a = jnp.maximum(a, jnp.roll(a, -s, axis=1))
        b = jnp.minimum(b, jnp.roll(b, -s, axis=1))
    any_ref[...] = a
    all_ref[...] = b


@functools.partial(jax.jit, static_argnames=("interpret", "cb"))
def classify_pallas(d, interpret: bool = False, cb: int = 64):
    """``d`` = corner frame minus iso, (M, 729) float32. Returns
    (any_in, all_in) as f32 0/1 planes over the same frame: position p
    holds the max/min of ``d > 0`` over the 8 cell corners at p — valid
    wherever p's coordinates are ≤ 7 per axis (the cell positions)."""
    m = d.shape[0]
    mp = ((m + cb - 1) // cb) * cb
    if mp != m:
        d = jnp.concatenate([d, jnp.zeros((mp - m, _NC), d.dtype)])
    any_f, all_f = pl.pallas_call(
        _kernel,
        grid=(mp // cb,),
        in_specs=[pl.BlockSpec((cb, _NC), lambda c: (c, 0))],
        out_specs=[pl.BlockSpec((cb, _NC), lambda c: (c, 0)),
                   pl.BlockSpec((cb, _NC), lambda c: (c, 0))],
        out_shape=[jax.ShapeDtypeStruct((mp, _NC), jnp.float32),
                   jax.ShapeDtypeStruct((mp, _NC), jnp.float32)],
        interpret=interpret,
    )(d)
    return any_f[:m], all_f[:m]
