"""Jitted compute kernels (the TPU replacement for the reference's NumPy/Open3D)."""

from . import patterns, decode, triangulate, knn, pointcloud, features, registration  # noqa: F401
