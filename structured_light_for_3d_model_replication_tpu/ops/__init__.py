"""Jitted compute kernels (the TPU replacement for the reference's NumPy/Open3D)."""

# decode_pallas (and the other *_pallas kernel modules) are NOT imported
# eagerly: they import jax.experimental.pallas at module scope, and the
# ops layer must stay importable on backends without pallas.  Dispatchers
# (decode.decode_maps, pointcloud._self_knn, ...) import them lazily
# behind a tpu_backend() gate — enforced by the `pallas-import` jaxlint
# rule (python -m structured_light_for_3d_model_replication_tpu.analysis).
from . import (  # noqa: F401
    cluster,
    decode,
    features,
    gridknn,
    knn,
    marching,
    mortonknn,
    orientation,
    patterns,
    pointcloud,
    poisson,
    posegraph,
    registration,
    segmentation,
    triangulate,
)
