"""Jitted compute kernels (the TPU replacement for the reference's NumPy/Open3D)."""

from . import (  # noqa: F401
    cluster,
    decode,
    decode_pallas,
    features,
    gridknn,
    knn,
    marching,
    mortonknn,
    orientation,
    patterns,
    pointcloud,
    poisson,
    posegraph,
    registration,
    segmentation,
    triangulate,
)
