"""Rigid registration: Kabsch/Umeyama, feature RANSAC, ICP, information matrix.

Replaces the Open3D registration pipeline the reference drives in
`server/processing.py:98-156` and `Old/360Merge.py:26-37`:

* ``registration_ransac_based_on_feature_matching`` (mutual filter ON,
  PointToPoint estimation, ransac_n=3, edge-length checker 0.9 + distance
  checker, 100k iters / 0.999 confidence, `server/processing.py:104-111`)
  → :func:`ransac_feature_registration` — hypotheses are VMAPPED in fixed-size
  batches instead of a sequential trial loop: every batch samples triplets,
  solves Kabsch in parallel on the MXU, prunes with the same two checkers,
  and scores inliers densely.
* ``registration_icp`` PointToPlane / PointToPoint
  (`server/processing.py:154-156`, `Old/360Merge.py:26-34`)
  → :func:`icp` — a ``lax.scan`` over iterations; correspondences come from
  the tiled-matmul KNN each step; the point-to-plane step solves the 6×6
  linearized normal equations, the point-to-point step is weighted Kabsch.
* ``get_information_matrix_from_point_clouds`` (`Old/360Merge.py:37`)
  → :func:`information_matrix` — the 6×6 Σ JᵀJ over inlier correspondences.

Transforms are 4×4 float32, row-convention ``x' = T[:3,:3] @ x + T[:3,3]``,
pose order (rotation | translation) = (α β γ | a b c) like Open3D's pose
graphs so information matrices interoperate with ops/posegraph.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import _backend
from .knn import knn


def _nn1(moved, dst_pts, dst_valid, src_valid, table=None):
    """k=1 correspondence sweep: the fused pallas running-argmin kernel on
    TPU backends (ops/nn_pallas.py — the XLA path materializes the full
    (M, N) distance field in HBM), the tiled-matmul KNN elsewhere.
    Returns (idx (N,), found (N,), d2 (N,)) with d2 = +inf where no valid
    key exists. ``table`` optionally reuses a precomputed
    ``nn_pallas.key_table`` when the same keys are swept repeatedly."""
    n = dst_pts.shape[0]
    if _backend.tpu_backend():
        # Kernel module imported only on the TPU path: nn_pallas imports
        # jax.experimental.pallas at module scope, and CPU deployments
        # must not depend on pallas importability (pallas-import rule).
        from . import nn_pallas

        if n <= nn_pallas.max_keys():
            if table is None:
                table = nn_pallas.key_table(dst_pts, dst_valid)
            d2, idx = nn_pallas.nearest_one(moved, *table)
            found = jnp.isfinite(d2)
            if src_valid is not None:
                found = found & src_valid
            return idx, found, jnp.where(jnp.isfinite(d2), d2, jnp.inf)
    d2, idx, nbv = knn(dst_pts, 1, queries=moved,
                       points_valid=dst_valid, queries_valid=src_valid,
                       q_tile=min(4096, max(256, moved.shape[0])),
                       fast_dots=True)
    return (idx[:, 0], nbv[:, 0],
            jnp.where(nbv[:, 0], d2[:, 0], jnp.inf))


def transform_points(T: jnp.ndarray, pts: jnp.ndarray) -> jnp.ndarray:
    hi = jax.lax.Precision.HIGHEST
    return jnp.einsum("ij,nj->ni", T[:3, :3], pts, precision=hi) + T[:3, 3]


def skew(v: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) -> (..., 3, 3) cross-product matrix."""
    x, y, z = v[..., 0], v[..., 1], v[..., 2]
    zero = jnp.zeros_like(x)
    return jnp.stack([
        jnp.stack([zero, -z, y], axis=-1),
        jnp.stack([z, zero, -x], axis=-1),
        jnp.stack([-y, x, zero], axis=-1),
    ], axis=-2)


def exp_so3(omega: jnp.ndarray) -> jnp.ndarray:
    """Rotation vector -> 3×3 rotation (Rodrigues, small-angle-safe)."""
    th = jnp.linalg.norm(omega)
    safe = jnp.where(th > 1e-12, th, 1.0)
    k = omega / safe
    K = skew(k)
    I = jnp.eye(3, dtype=omega.dtype)
    R = I + jnp.sin(th) * K + (1.0 - jnp.cos(th)) * (K @ K)
    return jnp.where(th > 1e-12, R, I)


def exp_se3(omega: jnp.ndarray, t: jnp.ndarray) -> jnp.ndarray:
    """Rotation-vector + translation -> 4×4 (rotation via Rodrigues; the
    translation is applied directly, matching the ICP small-step update).
    Assembled by concatenation — see :func:`_assemble_rigid`."""
    return _assemble_rigid(exp_so3(omega), t)


def _quat_to_rot(q: jnp.ndarray) -> jnp.ndarray:
    """(..., 4) unit quaternion (w, x, y, z) → (..., 3, 3) rotation."""
    w, x, y, z = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    return jnp.stack([
        jnp.stack([1 - 2 * (y * y + z * z), 2 * (x * y - w * z),
                   2 * (x * z + w * y)], axis=-1),
        jnp.stack([2 * (x * y + w * z), 1 - 2 * (x * x + z * z),
                   2 * (y * z - w * x)], axis=-1),
        jnp.stack([2 * (x * z - w * y), 2 * (y * z + w * x),
                   1 - 2 * (x * x + y * y)], axis=-1),
    ], axis=-2)


def kabsch(
    src: jnp.ndarray,
    dst: jnp.ndarray,
    weights: jnp.ndarray | None = None,
    power_iters: int = 24,
    ensure_converged: bool = False,
) -> jnp.ndarray:
    """Optimal rigid transform src→dst (weighted), (..., N, 3) batched.

    Horn's quaternion method instead of the classical SVD: the optimal
    rotation is the dominant eigenvector of a 4×4 symmetric matrix built
    from the correlation H, found here by a fixed-count shifted power
    iteration. On TPU this is the difference between a branch-free vmapped
    polynomial (RANSAC solves ~100k 3-point instances per edge) and ~100k
    LAPACK-style 3×3 SVD iterations — and it cannot return a reflection,
    so no det() fix-up is needed.

    ``ensure_converged``: a fixed 24-step iteration can stall short of the
    top eigenvector when the spectral gap is small (near-degenerate or
    noisy samples), returning a blended quaternion. Inside RANSAC's
    hypothesis batches that is fine — a bad hypothesis loses the inlier
    vote — but one-shot consumers (the all-inlier polish, point-to-point
    ICP steps) should pass True: a bounded ``lax.while_loop`` then keeps
    iterating until the Rayleigh residual ‖Aq − λq‖ < 1e-6 (or 160 extra
    steps). Converged entries are at a fixpoint, so batched inputs only pay
    until their slowest row settles.
    """
    if weights is None:
        weights = jnp.ones(src.shape[:-1], src.dtype)
    w = weights[..., None]
    wsum = jnp.maximum(jnp.sum(w, axis=-2, keepdims=True), 1e-12)
    cs = jnp.sum(src * w, axis=-2, keepdims=True) / wsum
    cd = jnp.sum(dst * w, axis=-2, keepdims=True) / wsum
    s = (src - cs) * w
    d = dst - cd
    hi = jax.lax.Precision.HIGHEST
    H = jnp.einsum("...ni,...nj->...ij", s, d, precision=hi)

    # Horn's K matrix (4×4 symmetric); its top eigenvector is the optimal
    # quaternion (w, x, y, z).
    S = H / jnp.maximum(
        jnp.linalg.norm(H, axis=(-2, -1), keepdims=True), 1e-12)
    t0, t1, t2 = S[..., 0, 0], S[..., 1, 1], S[..., 2, 2]
    K = jnp.stack([
        jnp.stack([t0 + t1 + t2, S[..., 1, 2] - S[..., 2, 1],
                   S[..., 2, 0] - S[..., 0, 2],
                   S[..., 0, 1] - S[..., 1, 0]], axis=-1),
        jnp.stack([S[..., 1, 2] - S[..., 2, 1], t0 - t1 - t2,
                   S[..., 0, 1] + S[..., 1, 0],
                   S[..., 0, 2] + S[..., 2, 0]], axis=-1),
        jnp.stack([S[..., 2, 0] - S[..., 0, 2],
                   S[..., 0, 1] + S[..., 1, 0], -t0 + t1 - t2,
                   S[..., 1, 2] + S[..., 2, 1]], axis=-1),
        jnp.stack([S[..., 0, 1] - S[..., 1, 0],
                   S[..., 0, 2] + S[..., 2, 0],
                   S[..., 1, 2] + S[..., 2, 1], -t0 - t1 + t2], axis=-1),
    ], axis=-2)
    # Shift by 2·I: K's spectrum lies in [-2, 2] after normalization, so
    # K + 2I is PSD and the power iteration converges to the TOP eigenvalue.
    A = K + 2.0 * jnp.eye(4, dtype=K.dtype)
    # Deterministic non-axis-aligned start (never orthogonal to the target
    # for any input-independent reason).
    q = jnp.broadcast_to(
        jnp.asarray([0.5377, 0.2810, 0.4821, 0.6317], K.dtype),
        K.shape[:-2] + (4,))

    # UNROLLED power iteration: a lax.scan here would nest inside RANSAC's
    # batch scan and serialize ~10k tiny matvec steps per edge; unrolled it
    # fuses into one straight-line vmapped kernel.
    for _ in range(power_iters):
        q = jnp.einsum("...ij,...j->...i", A, q, precision=hi)
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True),
                            1e-20)
    if ensure_converged:
        def residual(qv):
            Aq = jnp.einsum("...ij,...j->...i", A, qv, precision=hi)
            lam = jnp.sum(qv * Aq, axis=-1, keepdims=True)
            return jnp.linalg.norm(Aq - lam * qv, axis=-1)

        def cond(state):
            qv, it = state
            return (it < 160) & (jnp.max(residual(qv)) > 1e-6)

        def step(state):
            qv, it = state
            qv = jnp.einsum("...ij,...j->...i", A, qv, precision=hi)
            qv = qv / jnp.maximum(
                jnp.linalg.norm(qv, axis=-1, keepdims=True), 1e-20)
            return qv, it + 1

        q, _ = jax.lax.while_loop(cond, step, (q, jnp.int32(0)))
    # Degenerate problem (H ≈ 0: no/zero-weight correspondences) → identity,
    # matching the old SVD path's benign behavior; otherwise the start
    # vector would pass through as an arbitrary rotation.
    degenerate = jnp.linalg.norm(H, axis=(-2, -1)) < 1e-12
    q = jnp.where(degenerate[..., None],
                  jnp.asarray([1.0, 0.0, 0.0, 0.0], q.dtype), q)
    R = _quat_to_rot(q)
    t = cd[..., 0, :] - jnp.einsum("...ij,...j->...i", R, cs[..., 0, :],
                                   precision=hi)
    return _assemble_rigid(R, t)


def _assemble_rigid(R: jnp.ndarray, t: jnp.ndarray) -> jnp.ndarray:
    """[R | t; 0 0 0 1] via CONCATENATION, batched. ``.at[...].set`` on a
    (..., 4, 4) lowers to a scatter/dynamic-update-slice that ran at
    ~0.1 GiB/s on TPU — two such assemblies were 0.7 s of every 100k-RANSAC
    edge batch (XProf, registration.py kabsch). Concatenate lowers to
    cheap layout ops instead."""
    top = jnp.concatenate([R, t[..., :, None]], axis=-1)      # (..., 3, 4)
    bottom = jnp.broadcast_to(
        jnp.asarray([0.0, 0.0, 0.0, 1.0], R.dtype),
        R.shape[:-2] + (1, 4))
    return jnp.concatenate([top, bottom], axis=-2)


class RegistrationResult(NamedTuple):
    transformation: jnp.ndarray  # (4, 4)
    fitness: jnp.ndarray         # inliers / valid source points
    inlier_rmse: jnp.ndarray


def _triplet_rigid(s: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """Exact rigid transform from a 3-point correspondence via triangle
    frames: R maps src's orthonormal triangle frame onto dst's.

    RANSAC's hypothesis solver. For an exactly-rigid triplet this equals
    the LS solution; for a noisy one it differs slightly from
    :func:`kabsch` — irrelevant inside RANSAC, where every hypothesis is
    judged by its inlier vote and the winner is re-solved with a
    converged Kabsch on all inliers. What matters is cost: ~40 flops and
    a short dependency chain, vs the unrolled 4×4 power-iteration chain
    that was the latency floor of every vmapped hypothesis batch.
    Degenerate (near-collinear) triplets produce garbage rotations that
    lose the vote, exactly like a degenerate Kabsch sample."""
    def frame(p):
        u = p[1] - p[0]
        v = p[2] - p[0]
        e1 = u / jnp.maximum(jnp.linalg.norm(u), 1e-12)
        w = v - jnp.dot(v, e1) * e1
        e2 = w / jnp.maximum(jnp.linalg.norm(w), 1e-12)
        e3 = jnp.cross(e1, e2)
        return jnp.stack([e1, e2, e3], axis=1)         # columns
    hi = jax.lax.Precision.HIGHEST
    R = jnp.matmul(frame(d), frame(s).T, precision=hi)
    t = jnp.mean(d, axis=0) - jnp.matmul(R, jnp.mean(s, axis=0),
                                         precision=hi)
    return _assemble_rigid(R, t)


# ---------------------------------------------------------------------------
# Global registration: feature matching + vmapped RANSAC
# ---------------------------------------------------------------------------


def match_features(
    src_feat: jnp.ndarray,
    dst_feat: jnp.ndarray,
    src_valid: jnp.ndarray | None = None,
    dst_valid: jnp.ndarray | None = None,
    mutual: bool = True,
):
    """Nearest-neighbor correspondence per source feature (33-dim KNN).

    Returns (dst_index (N,), corr_valid (N,)). ``mutual`` keeps only pairs
    that are each other's nearest neighbors — the reference passes
    mutual_filter=True (`server/processing.py:105`).
    """
    # fast_dots: 3-pass bf16 for the 33-D feature distance matmuls — the
    # k=1 match only flips between near-equidistant descriptors, and the
    # HIGHEST-precision sweep (6-pass bf16) was half the measured
    # feature-matching cost of every ring edge.
    _, idx_sd, v_sd = knn(dst_feat, 1, queries=src_feat,
                          points_valid=dst_valid, queries_valid=src_valid,
                          fast_dots=True)
    nn = idx_sd[:, 0]
    ok = v_sd[:, 0]
    if mutual:
        _, idx_ds, v_ds = knn(src_feat, 1, queries=dst_feat,
                              points_valid=src_valid, queries_valid=dst_valid,
                              fast_dots=True)
        back = idx_ds[:, 0][nn]
        ok = ok & v_ds[:, 0][nn] & (back == jnp.arange(src_feat.shape[0]))
    return nn, ok


@functools.partial(
    jax.jit,
    static_argnames=("num_iterations", "batch", "ransac_n"),
)
def _ransac_core(
    key,
    src_pts, dst_pts, corr_idx, corr_ok,
    distance_threshold,
    edge_length_ratio,
    num_iterations: int,
    batch: int,
    ransac_n: int,
):
    n = src_pts.shape[0]
    n_batches = max(1, num_iterations // batch)

    def score_T(T):
        moved = transform_points(T, src_pts)
        d2 = jnp.sum((moved - dst_pts[corr_idx]) ** 2, axis=-1)
        inl = corr_ok & (d2 <= distance_threshold**2)
        cnt = jnp.sum(inl)
        rmse = jnp.sqrt(jnp.sum(jnp.where(inl, d2, 0.0))
                        / jnp.maximum(cnt, 1))
        return cnt, rmse, inl

    # Hypothesis RANKING runs on a strided subset of the correspondences —
    # scoring 100k hypotheses against every point is >90% of RANSAC's FLOPs
    # and the ranking is statistically identical; the winner is re-scored
    # and polished on the FULL set below. 256 points still separate
    # hypotheses by inlier count decisively (the margin between a correct
    # and a wrong pose is ~a hundred inliers at typical inlier ratios).
    sub = max(1, n // 256)
    sub_src = src_pts[::sub]
    sub_dst = dst_pts[corr_idx][::sub]
    sub_ok = corr_ok[::sub]

    def score_subset(T):
        moved = transform_points(T, sub_src)
        d2 = jnp.sum((moved - sub_dst) ** 2, axis=-1)
        return jnp.sum(sub_ok & (d2 <= distance_threshold**2))

    # ONE packed sample table: (src | dst[corr] | ok) rows, so each
    # hypothesis triplet is a single 7-wide gather instead of four chained
    # gathers (src, corr_idx, corr_ok, dst) — the chained form was ~250 ms
    # of every 100k-budget edge batch on TPU (XProf fusion.303/.305/.306/
    # .311: row-gather overhead, not bytes).
    tbl = jnp.concatenate(
        [src_pts, dst_pts[corr_idx], corr_ok.astype(jnp.float32)[:, None]],
        axis=1)                                            # (n, 7)

    def hypothesis(k):
        samp = jax.random.randint(k, (ransac_n,), 0, n)
        rows = tbl[samp]                                   # (ransac_n, 7)
        s = rows[:, :3]
        d = rows[:, 3:6]
        ok = jnp.all(rows[:, 6] > 0.5)
        # Edge-length checker: every pairwise edge ratio within
        # [ratio, 1/ratio] (`CorrespondenceCheckerBasedOnEdgeLength(0.9)`).
        ii, jj = jnp.triu_indices(ransac_n, 1)
        es = jnp.linalg.norm(s[ii] - s[jj], axis=-1)
        ed = jnp.linalg.norm(d[ii] - d[jj], axis=-1)
        ratio = jnp.minimum(es, ed) / jnp.maximum(jnp.maximum(es, ed), 1e-12)
        ok &= jnp.all(ratio >= edge_length_ratio)
        # Triangle-frame solve (see _triplet_rigid): exact for rigid
        # triplets at a fraction of a power-iteration Kabsch; the winner
        # is re-solved converged in the polish. (Non-default sample sizes
        # need the general LS solve.)
        T = (_triplet_rigid(s, d) if ransac_n == 3
             else kabsch(s, d, power_iters=12))
        # Distance checker on the sampled set.
        moved = transform_points(T, s)
        ok &= jnp.all(jnp.linalg.norm(moved - d, axis=-1)
                      <= distance_threshold)
        cnt = score_subset(T)
        return T, jnp.where(ok, cnt, -1)

    def batch_step(carry, k):
        best_T, best_cnt = carry
        keys = jax.random.split(k, batch)
        Ts, cnts = jax.vmap(hypothesis)(keys)
        i = jnp.argmax(cnts)
        better = cnts[i] > best_cnt
        return (jnp.where(better, Ts[i], best_T),
                jnp.where(better, cnts[i], best_cnt)), None

    init = (jnp.eye(4, dtype=jnp.float32), jnp.int32(-1))
    keys = jax.random.split(key, n_batches)
    (best_T, best_cnt), _ = jax.lax.scan(batch_step, init, keys)

    # Polish: re-estimate from ALL inliers of the best hypothesis. This is
    # a single solve whose result ships, so insist on eigenvector
    # convergence (the batched hypotheses above filter their own failures
    # through the inlier vote).
    cnt0, _, inl = score_T(best_T)
    T_ref = kabsch(src_pts, dst_pts[corr_idx], weights=inl.astype(jnp.float32),
                   ensure_converged=True)
    cnt1, rmse1, _ = score_T(T_ref)
    use_ref = cnt1 >= cnt0
    T_fin = jnp.where(use_ref, T_ref, best_T)
    cntf, rmsef, _ = score_T(T_fin)
    fitness = cntf / jnp.maximum(jnp.sum(corr_ok), 1)
    return RegistrationResult(T_fin, fitness, rmsef)


def ransac_feature_registration(
    src_pts, src_feat, dst_pts, dst_feat,
    distance_threshold: float,
    src_valid=None, dst_valid=None,
    mutual: bool = True,
    edge_length_ratio: float = 0.9,
    num_iterations: int = 100_000,
    # 8192 hypotheses per vmapped step: fewer, wider sequential steps (a
    # 100k budget becomes ~12 steps instead of ~196 at 512 — the step
    # chain, not the FLOPs, bounds RANSAC wall clock on TPU: XProf showed
    # ~15 ms/step of fixed dispatch+small-kernel latency at batch 2048,
    # so quadrupling the batch quarters the sequential chain for the same
    # hypothesis budget and negligible extra memory).
    batch: int = 8192,
    ransac_n: int = 3,
    key=None,
) -> RegistrationResult:
    """Global registration à la
    ``registration_ransac_based_on_feature_matching``
    (`server/processing.py:104-111`; defaults match its call: 1.5·voxel
    threshold, edge-length 0.9, 100k iterations).

    All ``num_iterations`` hypotheses run as vmapped fixed-size batches under
    one ``lax.scan`` — there is no early-exit confidence test (the 0.999
    criterion) because on TPU finishing the remaining vmapped trials is
    cheaper than a data-dependent branch; equivalent to confidence=1.0,
    i.e. never worse than the reference's search.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    src_pts = jnp.asarray(src_pts, jnp.float32)
    dst_pts = jnp.asarray(dst_pts, jnp.float32)
    corr_idx, corr_ok = match_features(src_feat, dst_feat, src_valid,
                                       dst_valid, mutual=mutual)
    if src_valid is not None:
        corr_ok = corr_ok & src_valid
    return _ransac_core(key, src_pts, dst_pts, corr_idx, corr_ok,
                        distance_threshold, edge_length_ratio,
                        num_iterations, batch, ransac_n)


# ---------------------------------------------------------------------------
# ICP
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("max_iterations", "method",
                                             "schedule",
                                             "warmup_subsample"))
def icp(
    src_pts: jnp.ndarray,
    dst_pts: jnp.ndarray,
    max_correspondence_distance: float,
    init: jnp.ndarray | None = None,
    dst_normals: jnp.ndarray | None = None,
    src_valid: jnp.ndarray | None = None,
    dst_valid: jnp.ndarray | None = None,
    max_iterations: int = 30,
    method: str = "point_to_plane",
    schedule: tuple | None = None,
    warmup_subsample: int = 1,
) -> RegistrationResult:
    """Iterative closest point, ``registration_icp`` semantics
    (`server/processing.py:154-156`: point-to-plane, seeded with the RANSAC
    transform, max distance = voxel size; Open3D's default 30 iterations).

    Fixed-iteration ``lax.scan`` (no convergence branch — XLA-friendly, and
    extra iterations of a converged solve are no-ops numerically).
    point_to_plane requires ``dst_normals``.

    ``schedule``: optional per-iteration multipliers on the correspondence
    distance (length max_iterations, e.g. geometric 4→1) — coarse-to-fine
    annealing that converges from rough initializations where a fixed
    tight radius finds zero correspondences and stalls. The final fitness/
    rmse are always evaluated at the base distance.

    ``warmup_subsample`` > 1 runs the first 80% of iterations on every
    S-th source point (≥ 8 iterations only): the early sweeps only need a
    descent direction, and a 2048-point subset still overdetermines the 6
    DoF ~300×; the last 20% and the final fitness/rmse always use the
    full set. The correspondence sweep is ICP's measured wall-clock floor,
    so this cuts it ~4× with no observable pose change (ring tests).
    """
    src_pts = jnp.asarray(src_pts, jnp.float32)
    dst_pts = jnp.asarray(dst_pts, jnp.float32)
    n = src_pts.shape[0]
    if init is None:
        init = jnp.eye(4, dtype=jnp.float32)
    if src_valid is None:
        src_valid = jnp.ones(n, dtype=bool)
    if method == "point_to_plane" and dst_normals is None:
        raise ValueError("point_to_plane ICP needs dst_normals")

    md2 = max_correspondence_distance**2
    hi = jax.lax.Precision.HIGHEST
    if schedule is None:
        mults = jnp.ones((max_iterations,), jnp.float32)
    else:
        if len(schedule) != max_iterations:
            raise ValueError(f"schedule length {len(schedule)} != "
                             f"max_iterations {max_iterations}")
        mults = jnp.asarray(schedule, jnp.float32)

    # The key side is constant across iterations: build the kernel table
    # once (a transpose + squared norms), not per sweep.  Lazy gated
    # import — see _nn1.
    table = None
    if _backend.tpu_backend():
        from . import nn_pallas

        if dst_pts.shape[0] <= nn_pallas.max_keys():
            table = nn_pallas.key_table(dst_pts, dst_valid)

    def correspondences(T, pts, valid, m2=1.0):
        moved = transform_points(T, pts)
        idx, found, d2 = _nn1(moved, dst_pts, dst_valid, valid, table)
        ok = found & (d2 <= md2 * m2)
        return moved, idx, ok, jnp.where(jnp.isfinite(d2), d2, 0.0)

    def make_step(pts, valid):
        def step(T, mult):
            moved, idx, ok, _ = correspondences(T, pts, valid, mult * mult)
            w = ok.astype(jnp.float32)
            q = dst_pts[idx]
            if method == "point_to_point":
                dT = kabsch(moved, q, weights=w, ensure_converged=True)
            else:
                nq = dst_normals[idx]
                r = jnp.sum((moved - q) * nq, axis=-1)      # (N,)
                J = jnp.concatenate([jnp.cross(moved, nq), nq],
                                    axis=-1)                # (N, 6)
                A = jnp.einsum("ni,nj->ij", J * w[:, None], J, precision=hi)
                b = -jnp.einsum("ni,n->i", J * w[:, None], r, precision=hi)
                x = jnp.linalg.solve(A + 1e-9 * jnp.eye(6, dtype=A.dtype), b)
                dT = exp_se3(x[:3], x[3:])
            return jnp.matmul(dT, T, precision=hi), None
        return step

    T = init.astype(jnp.float32)
    if warmup_subsample > 1 and max_iterations >= 8:
        # int() runs on a static python scalar (max_iterations is a
        # static argname), never a tracer. # jaxlint: disable=host-sync-in-jit
        n_warm = int(round(0.8 * max_iterations))
        T, _ = jax.lax.scan(
            make_step(src_pts[::warmup_subsample],
                      src_valid[::warmup_subsample]), T, mults[:n_warm])
        T, _ = jax.lax.scan(make_step(src_pts, src_valid), T,
                            mults[n_warm:])
    else:
        T, _ = jax.lax.scan(make_step(src_pts, src_valid), T, mults)
    _, idx, ok, d2 = correspondences(T, src_pts, src_valid)
    cnt = jnp.sum(ok)
    fitness = cnt / jnp.maximum(jnp.sum(src_valid), 1)
    rmse = jnp.sqrt(jnp.sum(jnp.where(ok, d2, 0.0)) / jnp.maximum(cnt, 1))
    return RegistrationResult(T, fitness, rmse)


# ---------------------------------------------------------------------------
# Information matrix (for pose-graph optimization)
# ---------------------------------------------------------------------------


@jax.jit
def information_matrix(
    src_pts: jnp.ndarray,
    dst_pts: jnp.ndarray,
    T: jnp.ndarray,
    max_correspondence_distance: float,
    src_valid: jnp.ndarray | None = None,
    dst_valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """6×6 Σ JᵀJ over inlier correspondences, the
    ``get_information_matrix_from_point_clouds`` analogue
    (`Old/360Merge.py:37`): J_i = [ −[q_i]ₓ | I ] with q_i the matched
    TARGET point, pose order (rotation | translation)."""
    src_pts = jnp.asarray(src_pts, jnp.float32)
    dst_pts = jnp.asarray(dst_pts, jnp.float32)
    moved = transform_points(jnp.asarray(T, jnp.float32), src_pts)
    idx, found, d2 = _nn1(moved, dst_pts, dst_valid, src_valid)
    ok = found & (d2 <= max_correspondence_distance**2)
    q = dst_pts[idx]
    J = jnp.concatenate([-skew(q), jnp.broadcast_to(jnp.eye(3), q.shape[:-1] + (3, 3))], axis=-1)  # (N, 3, 6)
    w = ok.astype(jnp.float32)[:, None, None]
    hi = jax.lax.Precision.HIGHEST
    return jnp.einsum("nij,nik->jk", J * w, J, precision=hi)
