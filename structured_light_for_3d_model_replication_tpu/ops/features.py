"""FPFH (Fast Point Feature Histograms) — batched, branch-free.

Replaces Open3D's ``compute_fpfh_feature`` (call site
`server/processing.py:92-94`: radius = 5·voxel, max_nn = 100). The classic
implementation loops over points and their neighbor lists; here the whole
cloud is processed as one (N, max_nn) batch:

1. neighborhoods from the tiled-matmul KNN, radius-masked;
2. the three Darboux-frame angles (α, φ, θ) for every (point, neighbor) pair
   at once — pure vectorized trig;
3. SPFH histograms via one-hot scatter-sums (no data-dependent loops);
4. FPFH = SPFH(p) + mean_k ( SPFH(q_k) / ‖p−q_k‖ ), then each 11-bin
   sub-histogram L1-normalized to 100 (PCL convention) so descriptors are
   density-invariant.

33 dims = 3 angles × 11 bins. Rotation-invariant by construction (verified in
tests/test_registration.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .knn import check_neighbors, knn

N_BINS = 11
FPFH_DIM = 3 * N_BINS


def _bin(x: jnp.ndarray, lo: float, hi: float) -> jnp.ndarray:
    b = jnp.floor((x - lo) / (hi - lo) * N_BINS).astype(jnp.int32)
    return jnp.clip(b, 0, N_BINS - 1)


@functools.partial(jax.jit, static_argnames=("max_nn",))
def fpfh(
    points: jnp.ndarray,
    normals: jnp.ndarray,
    radius: float,
    valid: jnp.ndarray | None = None,
    max_nn: int = 100,
    neighbors=None,
):
    """(N, 33) float32 FPFH descriptors (+ (N,) validity).

    ``radius``/``max_nn`` mirror the reference's KDTreeSearchParamHybrid.
    ``neighbors`` optionally supplies a precomputed ``(d2, idx, nb_valid)``
    self-query KNN (ascending, ≥ max_nn columns); it may have been built
    against a slightly wider validity mask — pairs re-mask against
    ``valid`` below either way.
    """
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    pts = jnp.asarray(points, jnp.float32)
    nrm = jnp.asarray(normals, jnp.float32)

    if neighbors is not None:
        check_neighbors(neighbors, n, max_nn)
        d2, idx, nbv = (a[:, :max_nn] for a in neighbors)
    else:
        d2, idx, nbv = knn(pts, max_nn, points_valid=valid)
    own = jnp.arange(n, dtype=jnp.int32)[:, None]

    # ONE gather for positions+normals+validity (random gathers are the
    # measured cost of this op on TPU; interleaving halves the gather row
    # count, and folding ``valid`` in as a float channel removes a
    # separate pred[N·K] gather that XProf measured at ~200 ms per ring —
    # bool gathers lower to a pathological element-at-a-time path).
    pnv = jnp.concatenate(
        [pts, nrm, valid.astype(jnp.float32)[:, None]], axis=1)[idx]
    q = pnv[..., :3]                # (N, K, 3) neighbor positions
    nt = pnv[..., 3:6]              # (N, K, 3) neighbor normals
    pair_ok = nbv & (d2 <= radius * radius) & (idx != own) \
        & (pnv[..., 6] > 0.5) & valid[:, None]              # (N, K)
    dvec = q - pts[:, None, :]
    dist = jnp.sqrt(jnp.maximum(jnp.sum(dvec * dvec, axis=-1), 1e-20))
    dn = dvec / dist[..., None]

    # Darboux frame at the source point: u = n_s, v = u × d̂, w = u × v.
    u = jnp.broadcast_to(nrm[:, None, :], dvec.shape)
    v = jnp.cross(u, dn)
    v_norm = jnp.linalg.norm(v, axis=-1, keepdims=True)
    v = v / jnp.where(v_norm > 1e-12, v_norm, 1.0)
    w = jnp.cross(u, v)

    alpha = jnp.sum(v * nt, axis=-1)                 # ∈ [-1, 1]
    phi = jnp.sum(u * dn, axis=-1)                   # ∈ [-1, 1]
    theta = jnp.arctan2(jnp.sum(w * nt, axis=-1),
                        jnp.sum(u * nt, axis=-1))    # ∈ [-π, π]

    bins = jnp.stack([
        _bin(alpha, -1.0, 1.0),
        _bin(phi, -1.0, 1.0),
        _bin(theta, -jnp.pi, jnp.pi),
    ], axis=-1)  # (N, K, 3)

    onehot = jax.nn.one_hot(bins, N_BINS, dtype=jnp.float32)  # (N, K, 3, 11)
    onehot = onehot * pair_ok[..., None, None]
    spfh = onehot.sum(axis=1).reshape(n, FPFH_DIM)  # (N, 33)
    # Normalize SPFH per point by its pair count (so the weighted neighbor
    # sum below doesn't favor dense points).
    cnt = jnp.maximum(jnp.sum(pair_ok, axis=1), 1)[:, None].astype(jnp.float32)
    spfh = spfh / cnt

    # FPFH: own SPFH + distance-weighted mean of neighbors' SPFHs.
    # (Stays f32: a bf16 variant of this gather+einsum measured SLOWER on
    # the tunneled v5e — 170 ms vs 131 ms per ring — the converts cost
    # more than the halved gather bytes save.)
    wgt = jnp.where(pair_ok, 1.0 / jnp.maximum(dist, 1e-12), 0.0)  # (N, K)
    nb_spfh = spfh[idx]  # (N, K, 33)
    wsum = jnp.maximum(jnp.sum(wgt, axis=1), 1e-12)[:, None]
    f = spfh + jnp.einsum("nk,nkf->nf", wgt, nb_spfh,
                          precision=jax.lax.Precision.HIGHEST) / wsum

    # L1-normalize each 11-bin sub-histogram to 100.
    f3 = f.reshape(n, 3, N_BINS)
    s = jnp.maximum(jnp.sum(f3, axis=-1, keepdims=True), 1e-12)
    f = (100.0 * f3 / s).reshape(n, FPFH_DIM)

    feat_valid = valid & (jnp.sum(pair_ok, axis=1) >= 1)
    return jnp.where(feat_valid[:, None], f, 0.0), feat_valid
