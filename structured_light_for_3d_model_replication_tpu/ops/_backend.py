"""Backend gate shared by every pallas dispatcher.

The ``*_pallas.py`` kernel modules import ``jax.experimental.pallas``
at module scope — they are the ONLY files allowed to (enforced by the
``pallas-import`` jaxlint rule).  Dispatchers must decide whether the
kernel path applies WITHOUT importing the kernel module, so that
CPU-only deployments never depend on pallas importability; this helper
is that decision, split out so it carries no pallas dependency itself.
"""

from __future__ import annotations

import jax

__all__ = ["tpu_backend"]


def tpu_backend() -> bool:
    """True on TPU-family backends.

    'axon' is the tunneled dev-TPU platform name in this environment —
    ``jax.default_backend()`` reports it instead of 'tpu' (the round-3
    lesson: never feature-gate on the literal 'tpu' alone).
    """
    return jax.default_backend() in ("tpu", "axon")
