"""Brick-grid KNN — high-recall large-N engine (dispatch ``"rescue"``).

The Morton-blocked engine (ops/mortonknn.py) misses ~7 % of true
neighbors at k=20 (window-limited: a neighbor more than one block away
along the curve is invisible), and the 27-cell grid engine
(ops/gridknn.py) fixes that but pays a per-query RANDOM gather of
27×capacity candidate rows — measured 14× slower than Morton at 1M on
TPU. This module keeps the grid's 27-cell exactness and the Morton
engine's dense memory behavior:

1. estimate a cell size from a sampled k-th-NN distance (the grid
   engine's estimator, scaled up so a query's true neighbor ball fits its
   3³ cell neighborhood);
2. sort by packed cell id ONCE; **brick** every occupied cell into S
   static slots — a (M, S) dense layout built with one scatter (cells
   with more than S points drop the overflow: bounded, documented
   approximation, sized so p99 occupancy fits);
3. each cell's candidates are its 27 neighbor BRICKS — a gather of whole
   (S, 3) bricks (contiguous rows), not of scattered points;
4. distances are one (S × 27S) matmul expansion per cell (chunked
   ``lax.map``), reduced with ``approx_min_k`` + a tiny exact sort.

Exact whenever the k-th neighbor lies within one cell radius and no
involved cell overflows S — by construction of the cell-size estimate
that holds for the overwhelming majority of queries: measured recall
≥ 0.99 at 1M/k=20 (tests/test_spatial_knn.py) vs 0.93 for the Morton
engine.

Two implementations share this setup: the XLA path below (the exact
oracle and CPU fallback — ~4.6 s at 1M/k=20 on a v5e, bounded by
take_along_axis/approx_top_k/scatter bookkeeping) and the Mosaic kernel
(`ops/brickknn_pallas.py` — ~1.15 s, 1.19× the Morton engine), which is
the default on TPU backends and makes high recall cheap enough to be the
large-N default for every consumer (`ops/pointcloud.py:_self_knn`).
Round 2 measured the old gather-based grid engine at the same recall at
~14×.

Same (sq_dists, indices, neighbor_valid) contract as :func:`..ops.knn.knn`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import _backend
from .gridknn import _estimate_cell_size
from ..utils.log import get_logger

log = get_logger(__name__)

_BITS = 10
_GRID_MAX = (1 << _BITS) - 1
S_PALLAS = 32  # the Mosaic kernel's fixed brick capacity
# Plain Python int, NOT jnp.int32: a module-level jax value would
# initialize the XLA backend at import time, which breaks
# jax.distributed.initialize for multi-host users importing the package.
_BIG = 1 << 30


def _floor_cell_edge(points, valid, h):
    """Clamp a requested cell edge so the grid fits 10 bits/axis for this
    cloud's extent (larger cells are always correct for 27-neighborhood
    coverage — just more candidates per query)."""
    mins = jnp.min(jnp.where(valid[:, None], points, jnp.inf), axis=0)
    maxs = jnp.max(jnp.where(valid[:, None], points, -jnp.inf), axis=0)
    extent = jnp.max(maxs - mins)
    return jnp.maximum(h, extent / (_GRID_MAX - 2) + 1e-12), mins


def _quantize_cells(points, valid, h, mins):
    """Packed 10-bit/axis cell id per point (invalid → +∞ sentinel).
    THE shared quantize step: the XLA engine below, the Mosaic kernel
    (`ops/brickknn_pallas.py`) and the brick FPFH
    (`ops/features_brick.py`) all grid through here — a divergence
    would silently break the kernel's oracle tests against this path."""
    cell = jnp.clip(((points - mins) / h).astype(jnp.int32), 0, _GRID_MAX)
    cc = (cell[:, 0] << (2 * _BITS)) | (cell[:, 1] << _BITS) | cell[:, 2]
    return jnp.where(valid, cc, _BIG)


def _grid_cells(points, valid, k, cell_scale_x100):
    """Shared cell assignment: the r_k cell-size estimate (floored so the
    grid fits 10 bits/axis) and the packed per-point cell id."""
    h = _estimate_cell_size(points, valid, k) * (cell_scale_x100 / 100.0)
    h, mins = _floor_cell_edge(points, valid, h)
    return h, lambda hh: _quantize_cells(points, valid, hh, mins)


def _sorted_segments(points, valid, cid, slots, max_cells):
    """Shared sort + segment structure + brick destinations (module
    docstring step 2). Returns the sorted views and the per-point brick
    destination (dump row = max_cells·slots for overflow/budget drops)."""
    n = points.shape[0]
    order = jnp.argsort(cid)
    cid_s = cid[order]
    pts_s = points[order]
    val_s = valid[order] & (cid_s < _BIG)
    orig_s = order.astype(jnp.int32)

    first = jnp.concatenate([jnp.ones(1, bool), cid_s[1:] != cid_s[:-1]])
    first = first & val_s
    cell_rank = jnp.cumsum(first.astype(jnp.int32)) - 1       # (N,)
    seg_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, jnp.arange(n, dtype=jnp.int32), 0))
    within = jnp.arange(n, dtype=jnp.int32) - seg_start

    ok = val_s & (within < slots) & (cell_rank < max_cells)
    dest = jnp.where(ok, cell_rank * slots + within, max_cells * slots)
    # Sorted unique cell ids (ascending) for neighbor lookup.
    ucid = jnp.full((max_cells + 1,), _BIG, jnp.int32).at[
        jnp.where(first & (cell_rank < max_cells), cell_rank,
                  max_cells)].set(jnp.where(first, cid_s, _BIG))[:-1]
    return cid_s, pts_s, val_s, orig_s, first, cell_rank, ok, dest, ucid


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7))
def _brick_knn_impl(points, valid, k, slots, chunk_cells, exclude_self,
                    cell_scale_x100, max_cells):
    n = points.shape[0]
    S = slots
    # Static cell budget: cells are sized to hold ~O(k) points (the cell
    # edge tracks the k-th-NN distance), so the occupied count is far
    # below N; cells ranked past the budget are dropped (their points
    # report no neighbors — degenerate inputs only).
    m_cells = max_cells

    h, quantize = _grid_cells(points, valid, k, cell_scale_x100)
    cid = quantize(h)
    (cid_s, pts_s, val_s, orig_s, first, cell_rank, ok, dest,
     ucid) = _sorted_segments(points, valid, cid, S, m_cells)

    bp = jnp.zeros((m_cells * S + 1, 3), jnp.float32).at[dest].set(pts_s)
    bo = jnp.full((m_cells * S + 1,), -1, jnp.int32).at[dest].set(orig_s)
    bv = jnp.zeros((m_cells * S + 1,), bool).at[dest].set(ok)
    bp = bp[:-1].reshape(m_cells, S, 3)
    bo = bo[:-1].reshape(m_cells, S)
    bv = bv[:-1].reshape(m_cells, S)

    # 27 neighbor cell ranks per cell (boundary-masked per axis — packed-id
    # arithmetic aliases across axis borrows otherwise, see ops/gridknn.py).
    ux = ucid >> (2 * _BITS)
    uy = (ucid >> _BITS) & _GRID_MAX
    uz = ucid & _GRID_MAX
    deltas = jnp.asarray([(dx, dy, dz) for dx in (-1, 0, 1)
                          for dy in (-1, 0, 1) for dz in (-1, 0, 1)],
                         jnp.int32)
    nxyz = jnp.stack([ux, uy, uz], -1)[:, None, :] + deltas[None]
    in_grid = jnp.all((nxyz >= 0) & (nxyz <= _GRID_MAX), axis=-1) \
        & (ucid < _BIG)[:, None]
    ncid = (nxyz[..., 0] << (2 * _BITS)) | (nxyz[..., 1] << _BITS) \
        | nxyz[..., 2]
    pos = jnp.searchsorted(ucid, jnp.where(in_grid, ncid, _BIG)
                           ).astype(jnp.int32)
    pos_c = jnp.minimum(pos, m_cells - 1)
    nbr = jnp.where(in_grid & (ucid[pos_c] == ncid), pos_c, m_cells)
    # (M, 27); m_cells = "absent" sentinel

    bppad = jnp.concatenate([bp, jnp.zeros((1, S, 3), jnp.float32)])
    bopad = jnp.concatenate([bo, jnp.full((1, S), -1, jnp.int32)])
    bvpad = jnp.concatenate([bv, jnp.zeros((1, S), bool)])

    hi = jax.lax.Precision.HIGHEST

    def per_chunk(args):
        q, qv, qo, nb = args              # (C,S,3) (C,S) (C,S) (C,27)
        c = q.shape[0]
        kp = bppad[nb].reshape(c, 27 * S, 3)
        kv = bvpad[nb].reshape(c, 27 * S)
        ko = bopad[nb].reshape(c, 27 * S)
        q2 = jnp.sum(q * q, axis=-1)                      # (C, S)
        p2 = jnp.sum(kp * kp, axis=-1)                    # (C, 27S)
        cross = jnp.einsum("csd,cnd->csn", q, kp, precision=hi)
        d2 = q2[..., :, None] + p2[..., None, :] - 2.0 * cross
        bad = ~kv[..., None, :]
        if exclude_self:
            bad = bad | (qo[..., :, None] == ko[..., None, :])
        d2 = jnp.where(bad, jnp.inf, d2)
        flat = d2.reshape(-1, 27 * S)
        cd, carg = jax.lax.approx_min_k(flat, k, recall_target=0.99)
        # Row r of `flat` is query slot r%S of cell r//S → its candidate
        # index row is that cell's ko row.
        ci = jnp.take_along_axis(
            jnp.repeat(ko, S, axis=0).reshape(flat.shape[0], -1),
            carg, axis=1)
        neg, arg = jax.lax.top_k(-cd, k)
        idx = jnp.take_along_axis(ci, arg, axis=1)
        dd = jnp.maximum(-neg, 0.0)
        nb_ok = jnp.isfinite(dd) & qv.reshape(-1)[:, None]
        return jnp.where(jnp.isfinite(dd), dd, 0.0), idx, nb_ok

    cb = chunk_cells
    pad_c = (-m_cells) % cb
    if pad_c:
        def padc(x, fill):
            return jnp.concatenate(
                [x, jnp.full((pad_c,) + x.shape[1:], fill, x.dtype)])
        bpq = padc(bp, 0)
        bvq = padc(bv, False)
        boq = padc(bo, -1)
        nbq = padc(nbr, m_cells)
    else:
        bpq, bvq, boq, nbq = bp, bv, bo, nbr
    groups = bpq.shape[0] // cb

    def g(x):
        return x.reshape((groups, cb) + x.shape[1:])

    d, i, v = jax.lax.map(per_chunk, (g(bpq), g(bvq), g(boq), g(nbq)))
    d = d.reshape(-1, k)[: m_cells * S]
    i = i.reshape(-1, k)[: m_cells * S]
    v = v.reshape(-1, k)[: m_cells * S]

    # Scatter back to original rows (dropped-overflow points keep no
    # neighbors — they scatter from nowhere; fill via dump defaults).
    qo_flat = bo.reshape(-1)
    rows = jnp.where(qo_flat >= 0, qo_flat, n)
    out_d = jnp.zeros((n + 1, k), jnp.float32).at[rows].set(d)[:n]
    out_i = jnp.zeros((n + 1, k), jnp.int32).at[rows].set(i)[:n]
    out_v = jnp.zeros((n + 1, k), bool).at[rows].set(v)[:n]
    # Points lost to slot overflow or the cell budget report zero neighbors
    # (out_v False); surface the count so precision-sensitive callers can
    # see the truncation at runtime, not just in the docstring.
    n_dropped = jnp.sum(val_s & ~ok)
    return out_d, out_i, out_v, n_dropped


@functools.partial(jax.jit, static_argnames=("exclude_self", "max_rescue"))
def _rescue_dropped(points, points_valid, d, i, v, *, exclude_self,
                    max_rescue):
    """Exact second pass for slot/budget-dropped rows (all-False ``v``).

    Compacts up to ``max_rescue`` dropped-but-valid rows into a static
    query block, brute-force exact-KNNs them against the WHOLE cloud,
    and row-scatters the results back. The sweep is purpose-built rather
    than `ops/knn.knn`: that path's 2k-wide key tiles mean ~512
    sequential top-k merge steps at 1M points (~0.75 s measured on the
    tunneled v5e for ONE rescue call); here each 64k-wide corpus chunk
    takes one exact ``top_k`` and the ~16 per-chunk candidate sets merge
    with a single final ``top_k`` — tens of ms for the same exact
    result. Cost is micro at the drop rates the brick engine produces
    (tens of rows per million), so full coverage no longer requires
    oversizing ``slots``/``max_cells`` for the worst cell. Rows beyond
    ``max_rescue`` stay dropped and are reported in the returned
    remaining-drop count."""
    n, k = d.shape[0], d.shape[1]
    dropped = points_valid & ~jnp.any(v, axis=1)
    n_drop = jnp.sum(dropped.astype(jnp.int32))
    # Static-size compaction; fill rows point at the out-of-range dump
    # row n (scattered into (n+1)-row buffers below and sliced off) — a
    # real-row fill value would collide when that row is itself dropped:
    # duplicate scatter destinations race and the padding write can win,
    # silently leaving the row unrescued while remaining-drops reads 0.
    (qidx,) = jnp.nonzero(dropped, size=max_rescue, fill_value=n)
    qok = jnp.arange(max_rescue) < n_drop
    q = points[jnp.minimum(qidx, n - 1)]
    kk = k + 1 if exclude_self else k

    CH = 1 << 16
    pad = (-n) % CH
    cpts = jnp.concatenate(
        [points, jnp.zeros((pad, 3), jnp.float32)]) if pad else points
    cval = jnp.concatenate(
        [points_valid, jnp.zeros(pad, bool)]) if pad else points_valid
    q2 = jnp.sum(q * q, axis=-1, keepdims=True)
    hi = jax.lax.Precision.HIGHEST

    def per_chunk(args):
        kp, kv, base = args                        # (CH,3) (CH,) ()
        p2 = jnp.sum(kp * kp, axis=-1)
        cross = jnp.dot(q, kp.T, precision=hi)
        d2c = jnp.where(kv[None, :], q2 + p2[None, :] - 2.0 * cross,
                        jnp.inf)
        neg, idx = jax.lax.top_k(-d2c, kk)         # exact per chunk
        return -neg, base + idx.astype(jnp.int32)
    n_ch = cpts.shape[0] // CH
    cd, ci = jax.lax.map(
        per_chunk,
        (cpts.reshape(n_ch, CH, 3), cval.reshape(n_ch, CH),
         jnp.arange(n_ch, dtype=jnp.int32) * CH))
    cd = jnp.moveaxis(cd, 0, 1).reshape(max_rescue, -1)  # (R, n_ch·kk)
    ci = jnp.moveaxis(ci, 0, 1).reshape(max_rescue, -1)
    neg, arg = jax.lax.top_k(-cd, kk)              # exact global merge
    rd = jnp.maximum(-neg, 0.0)
    ri = jnp.take_along_axis(ci, arg, axis=1)
    rv = jnp.isfinite(-neg) & qok[:, None]
    rd = jnp.where(rv, rd, 0.0)
    if exclude_self:
        # Drop the query's own index (distance-0 row, sorts first up to
        # ties) with the stable shift-left trick.
        keep = ri != qidx[:, None]
        order = jnp.argsort(~keep, axis=1, stable=True)
        rd = jnp.take_along_axis(rd, order, axis=1)[:, :k]
        ri = jnp.take_along_axis(ri, order, axis=1)[:, :k]
        rv = jnp.take_along_axis(rv & keep, order, axis=1)[:, :k]
    def put(buf, upd):
        padded = jnp.concatenate([buf, jnp.zeros((1, k), buf.dtype)])
        return padded.at[qidx].set(upd)[:n]

    d = put(d, rd)
    i = put(i, ri)
    v = put(v, rv)
    return d, i, v, jnp.maximum(n_drop - max_rescue, 0)


def brick_knn(
    points: jnp.ndarray,
    k: int,
    points_valid: jnp.ndarray | None = None,
    exclude_self: bool = False,
    slots: int = 32,
    chunk_cells: int = 2048,
    cell_scale: float = 1.4,
    max_cells: int | None = None,
    use_pallas: bool | None = None,
    return_dropped: bool = False,
    rescue: bool = False,
    max_rescue: int = 1024,
):
    """High-recall brick-grid self-query KNN (module docstring).

    Same contract as ``knn(points, k, exclude_self=...)``. ``slots`` is
    the static per-cell capacity (overflow points lose their neighbor
    rows — sized for p99 occupancy at the estimated cell size);
    ``cell_scale`` widens cells beyond the sampled k-th-NN distance so
    the 3³ neighborhood covers the true neighbor ball. ``max_cells``
    bounds the static occupied-cell budget (default n/8 + 1024 — cells
    hold ~O(k) points by construction, so real clouds occupy far fewer).

    ``use_pallas``: None = the Mosaic kernel (`ops/brickknn_pallas.py`)
    on TPU backends when ``slots==32`` and ``k<=32``, XLA elsewhere;
    True forces it in interpret mode off-TPU (tests). The kernel clears
    the low 10 mantissa bits of returned d² (≤ 2⁻¹³ relative); the XLA
    path is exact. With ``rescue``, the d² precision is therefore MIXED
    on the pallas path: rescued rows are re-solved by the exact XLA
    sweep and carry full-precision d², while every non-rescued row keeps
    the kernel's truncated values — don't diff d² across the two row
    classes at tighter than 2⁻¹³ relative (neighbor INDICES are
    unaffected).

    ``return_dropped``: also return the scalar count of points lost to
    slot/budget overflow (they report all-False ``neighbor_valid`` rows)
    — the in-graph channel for precision-sensitive callers; under an
    outer jit no host-side warning can be emitted (see
    :func:`_emit_drop_warning`).

    ``rescue``: run the exact second pass (:func:`_rescue_dropped`) over
    up to ``max_rescue`` dropped rows, making coverage complete for any
    realistic drop rate (the official 1M bench cloud drops ~tens of
    rows). The returned/warned drop count is then the POST-rescue
    remainder, which is 0 unless more than ``max_rescue`` rows dropped.
    """
    points = jnp.asarray(points, jnp.float32)
    n = points.shape[0]
    if points_valid is None:
        points_valid = jnp.ones(n, dtype=bool)
    if 27 * slots < k + (1 if exclude_self else 0):
        raise ValueError(f"slots {slots} too small for k={k}")
    if max_cells is None:
        max_cells = n // 8 + 1024

    # Resolve the engine BEFORE importing the kernel module: CPU-only
    # deployments (use_pallas=False, or None off-TPU) must never import
    # brickknn_pallas → jax.experimental.pallas (pallas-import rule).
    # Truthy (not just `is True`) so np.True_/1 keep the documented
    # unfit-shape ValueError instead of silently falling back to XLA.
    forced = use_pallas is not None and bool(use_pallas)
    if use_pallas is None:
        use_pallas = _backend.tpu_backend()
    if use_pallas:
        from . import brickknn_pallas

        kernel_fits = (slots == S_PALLAS and k <= brickknn_pallas.MAX_K
                       and n <= brickknn_pallas.MAX_N)
        if not kernel_fits:
            if forced:
                raise ValueError(
                    f"use_pallas=True but the Mosaic brick kernel requires "
                    f"slots={S_PALLAS}, k<={brickknn_pallas.MAX_K} and "
                    f"n<={brickknn_pallas.MAX_N} (got slots={slots}, k={k}, "
                    f"n={n})")
            use_pallas = False  # auto mode: fall back to the XLA path
    if use_pallas:
        d, i, v, n_dropped = brickknn_pallas.brick_knn_pallas(
            points, points_valid, k, exclude_self,
            int(round(cell_scale * 100)), max_cells,
            interpret=not brickknn_pallas.available())
    else:
        cc = min(chunk_cells, max(256, max_cells))
        if max_cells % cc:  # static chunking needs a divisor-friendly budget
            max_cells = ((max_cells + cc - 1) // cc) * cc
        d, i, v, n_dropped = _brick_knn_impl(
            points, points_valid, k, slots, cc, exclude_self,
            int(round(cell_scale * 100)), max_cells)
    if rescue:
        d, i, v, n_dropped = _rescue_dropped(
            points, points_valid, d, i, v, exclude_self=exclude_self,
            max_rescue=max_rescue)
    _emit_drop_warning(n_dropped, n)
    if return_dropped:
        return d, i, v, n_dropped
    return d, i, v


def _emit_drop_warning(n_dropped, n_total) -> None:
    """Surface the truncation count at runtime — EAGER calls only.

    Under an outer jit the count is a tracer and NOTHING is staged: a
    ``jax.debug.callback`` here crashed round 3's bench at dispatch
    (`UNIMPLEMENTED: axon_pjrt does not support host send/recv
    callbacks`) because this image's TPU PJRT has no host-callback
    support, and a backend-name guard proved unreliable
    (``jax.default_backend()`` returns ``"tpu"`` on the axon platform).
    Library kernels must not emit host callbacks from jitted code at
    all: traced consumers observe drops through the returned
    ``neighbor_valid`` mask (all-False rows — which
    ``ops/pointcloud.statistical_outlier_removal`` treats as
    conservatively invalid) or request the in-graph count via
    ``return_dropped``."""
    if isinstance(n_dropped, jax.core.Tracer):
        return
    _warn_dropped(n_dropped, n_total)


def _warn_dropped(n_dropped, n_total) -> None:
    nd = int(n_dropped)
    if nd > 0:
        log.warning(
            "brick_knn dropped %d/%d points (cell-slot overflow or cell "
            "budget); they report zero neighbors — raise `slots`/"
            "`max_cells` for full coverage", nd, int(n_total))
