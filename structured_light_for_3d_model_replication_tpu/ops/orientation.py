"""Globally consistent normal orientation (tangent-plane MST propagation).

Replaces Open3D's ``orient_normals_consistent_tangent_plane``
(`server/processing.py:201,282`). The algorithm is Hoppe's classic: build a
Riemannian graph over k nearest neighbors, weight edges by how parallel the
endpoint normals are, take a minimum spanning tree, and propagate a sign flip
along it.

Split TPU-idiomatically: the O(N²)-flavored part (KNN graph construction) runs
on device via the tiled-matmul :func:`..ops.knn.knn`; the inherently
sequential part (MST + traversal) is a tiny host-side sparse-graph pass
(scipy). Point-at / radial orientation stays fully on device in
:func:`..ops.pointcloud.orient_normals`.
"""

from __future__ import annotations

import numpy as np
from scipy.sparse import coo_matrix
from scipy.sparse.csgraph import breadth_first_order, connected_components, \
    minimum_spanning_tree

from .knn import knn


def orient_normals_consistent_tangent_plane(
    points: np.ndarray,
    normals: np.ndarray,
    k: int = 100,
    outward: bool = True,
) -> np.ndarray:
    """Flip normal signs for global consistency; returns oriented normals.

    ``k`` mirrors the reference's
    ``orient_normals_consistent_tangent_plane(100)``
    (`server/processing.py:282`). Each connected component is rooted at its
    point furthest from the cloud centroid, whose normal is seeded to point
    away from (``outward=True``) the centroid — the convention the radial
    fallback in `server/processing.py:283-289` also produces.
    """
    pts = np.asarray(points, np.float32)
    nrm = np.asarray(normals, np.float32).copy()
    n = pts.shape[0]
    if n == 0:
        return nrm
    k_eff = min(k, n)

    # Device: KNN graph (indices + distances), one tiled-matmul pass.
    d2, idx, nbv = (np.asarray(a) for a in knn(pts, k_eff))

    # Native fast path: C++ Prim MST + flip propagation over the SYMMETRIZED
    # graph (reverse KNN edges included, so Prim's reachability matches the
    # undirected union-find components used for the vote below; edge weights
    # 1−|n·n| are flip-invariant, so propagation order cannot change them),
    # then a per-component majority radial vote to pick the outward sign —
    # same convention as the scipy path's root seeding.
    from .. import native

    if native.available():
        out, _ = native.mst_orient_normals(pts, nrm, idx, nbv,
                                           seed_dir=(0.0, 0.0, 0.0))
        labels, ncomp = native.connected_components(idx, nbv)
        r = pts - pts.mean(axis=0)
        vote = np.einsum("ij,ij->i", out, r)
        for comp in range(ncomp):
            m = labels == comp
            total = float(vote[m].sum())
            if (total < 0) == outward and total != 0.0:
                out[m] = -out[m]
        return out

    rows = np.repeat(np.arange(n), k_eff)
    cols = idx.reshape(-1)
    mask = nbv.reshape(-1) & (rows != cols)
    rows, cols = rows[mask], cols[mask]
    # Edge weight: 1 - |n_i · n_j| (small when tangent planes agree) with an
    # epsilon so MST keeps even perfectly-parallel edges.
    dots = np.abs(np.einsum("ij,ij->i", nrm[rows], nrm[cols]))
    w = np.maximum(1.0 - dots, 1e-6)
    graph = coo_matrix((w, (rows, cols)), shape=(n, n))
    # Union-symmetrize: sparse minimum() would drop one-sided KNN edges
    # (elementwise min against an implicit zero), disconnecting exactly the
    # sparse→dense links Hoppe's graph needs.
    graph = graph.maximum(graph.T)
    ncomp, labels = connected_components(graph, directed=False)
    mst = minimum_spanning_tree(graph)
    sym = mst + mst.T
    sym_csr = sym.tocsr()

    centroid = pts.mean(axis=0)
    r = pts - centroid
    # Flip factor f ∈ {+1,−1} per point. Along a tree edge pred→node,
    # f[node] = f[pred] · sign(n_node · n_pred) (dots on ORIGINAL normals, so
    # levels can be processed as vectorized waves instead of per-node).
    f = np.ones(n, np.float32)
    for comp in range(ncomp):
        members = np.where(labels == comp)[0]
        root = members[np.argmax(np.einsum("ij,ij->i", r[members],
                                           r[members]))]
        order, pred = breadth_first_order(sym_csr, root, directed=False)
        # Seed: root normal points away from (toward) the centroid.
        s = float(np.dot(nrm[root], r[root]))
        f[root] = -1.0 if ((s < 0) == outward and s != 0.0) else 1.0
        # Depth of each node in BFS-tree; process one depth level at a time.
        depth = np.zeros(n, np.int64)
        for node in order[1:]:
            depth[node] = depth[pred[node]] + 1
        if len(order) > 1:
            nodes = order[1:]
            dlev = depth[nodes]
            edge_sign = np.sign(np.einsum(
                "ij,ij->i", nrm[nodes], nrm[pred[nodes]]))
            edge_sign[edge_sign == 0] = 1.0
            for d in range(1, int(dlev.max()) + 1):
                lvl = nodes[dlev == d]
                f[lvl] = f[pred[lvl]] * edge_sign[dlev == d]
    return nrm * f[:, None]
