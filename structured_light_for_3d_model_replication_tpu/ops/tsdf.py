"""Sparse brick-grid TSDF integration (device-side, in-place).

The second scene representation next to the Poisson solve
(`ops/poisson*.py`): a truncated-signed-distance volume fused one stop at
a time, Gaussian-Plus-SDF SLAM style (PAPERS.md) — per-point COLOR rides
along (the Poisson path discards it), unobserved space stays open
(non-watertight scenes), and per-stop integration is a fixed-shape
scatter instead of a from-scratch solve.

Layout follows `ops/poisson_sparse.py`: the volume is a virtual
``2^grid_depth`` cube of voxels, stored as flat 8³ **bricks**
(``BS = 8``; flat (cap, 512) per the solver's tile rule — a trailing
(8, 8) shape pads 16× under the TPU (8, 128) tile). Splatonic's lesson
(PAPERS.md) is that only the active surface *shell* needs processing, so
brick storage is a fixed-capacity pool addressed through a DENSE brick
directory (``(NB³,) int32`` slot map, NB = 2^grid_depth / 8 — 128 KB at
depth 8): allocation is a prefix-sum over newly touched directory cells,
never a host-side hash table, and every shape in the per-stop integrate
program is static. The whole update runs as ONE jitted program with the
volume buffers donated in/out — true in-place integration, the same
discipline as `stream/session.py`'s ``_fuse_fn``.

Sign convention: **positive = inside** (behind the observed surface),
matching the Poisson χ so the marching extractors' ``inside = value >
iso`` logic (iso = 0 here) carries over unchanged. Each valid point
updates the ``(2·splat_radius+1)³`` voxel window around it with the
projective point-to-plane distance ``dot(voxel_center − p, d̂)`` where
``d̂`` is the per-point INWARD unit direction — the viewing ray for
streaming stops (:func:`camera_dirs`), ``−n̂`` for oriented clouds —
clamped to ±1 truncation unit. Weights taper linearly to the truncation
band edge; TSDF/weight/RGB fold in by weighted running average with the
classic weight clamp. No free-space carving: the target scenes are
static turntable captures (documented in docs/MESHING.md).

The elementwise combine (five (cap, 512)-shaped running-average updates)
optionally runs as a fused Pallas kernel (:mod:`.tsdf_pallas`) behind
``_backend.tpu_backend()``; :func:`integrate_oracle` is the NumPy oracle
(dense grid, same formulas, float32) every device result is pinned
against in tests/test_fusion.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as _np

import jax
import jax.numpy as jnp

from . import _backend
from .poisson_sparse import BS
from ..utils.log import get_logger

log = get_logger(__name__)

V = BS ** 3                  # 512 voxels per brick


class TSDFParams(NamedTuple):
    """Static (program-keying) half of a TSDF volume's configuration.

    Hashable on purpose: these values are compile-time constants of the
    integrate/extract programs (`jax.jit` static args), exactly like
    ``PoissonParams`` keys the sparse solver."""

    grid_depth: int = 8          # virtual cube = 2^grid_depth voxels/axis
    max_bricks: int = 8192       # fixed brick-pool capacity
    splat_radius: int = 1        # update window = (2r+1)³ voxels per point
    trunc_voxels: float = 3.0    # truncation distance in voxels
    max_weight: float = 64.0     # running-average weight clamp
    # Free-space carving (off by default — docs/MESHING.md): each valid
    # point also marches ``carve_steps`` one-voxel samples from the
    # truncation-band edge TOWARD the camera (observed-empty space;
    # the reach in voxels beyond the band) and DECAYS those voxels'
    # weight multiplicatively (×exp(−carve_weight) per sample — scale-
    # free in the accumulated weight), so a moving sensor erases stale
    # surface instead of ghosting it. Samples only touch ALREADY-
    # allocated bricks (carving never allocates), and a voxel decayed
    # under 1e-3 weight resets to unobserved. With the default 0 the
    # integrate program is the historical one, bit for bit (the carve
    # branch is trace-time gated).
    carve_steps: int = 0
    carve_weight: float = 0.25

    @property
    def resolution(self) -> int:
        return 1 << int(self.grid_depth)

    @property
    def nb(self) -> int:
        return self.resolution // BS


class TSDFState(NamedTuple):
    """Device-resident volume buffers (all shapes fixed by TSDFParams).

    ``tsdf`` is in truncation units (±1 = ± one truncation distance),
    positive inside; unobserved voxels hold −1 and weight 0 — extraction
    masks them out, so open scenes stay open."""

    dir_map: jnp.ndarray       # (NB³,) int32 brick slot, −1 = inactive
    tsdf: jnp.ndarray          # (cap, 512) float32, trunc units, + inside
    weight: jnp.ndarray        # (cap, 512) float32 accumulated weight
    rgb: jnp.ndarray           # (cap, 512, 3) float32 running mean color
    brick_coords: jnp.ndarray  # (cap, 3) int32 brick coords of each slot
    n_bricks: jnp.ndarray      # () int32 active slots


def init_state(params: TSDFParams) -> TSDFState:
    cap = int(params.max_bricks)
    nb3 = params.nb ** 3
    return TSDFState(
        dir_map=jnp.full((nb3,), -1, jnp.int32),
        tsdf=jnp.full((cap, V), -1.0, jnp.float32),
        weight=jnp.zeros((cap, V), jnp.float32),
        rgb=jnp.zeros((cap, V, 3), jnp.float32),
        brick_coords=jnp.zeros((cap, 3), jnp.int32),
        n_bricks=jnp.zeros((), jnp.int32),
    )


@functools.lru_cache(maxsize=None)
def _window_offsets(radius: int) -> _np.ndarray:
    r = int(radius)
    g = _np.mgrid[-r:r + 1, -r:r + 1, -r:r + 1]
    return g.reshape(3, -1).T.astype(_np.int32)        # ((2r+1)³, 3)


def _combine(tsdf, weight, rgb, num, den, rgbnum, max_weight,
             use_pallas: bool):
    """Weighted running-average fold of one stop's scatter sums.

    RGB and TSDF divide by the PRE-clamp weight sum (the mathematically
    correct mean); only the stored weight is clamped — the KinectFusion
    recipe, kept identical between the XLA form, the pallas kernel and
    the NumPy oracle."""
    if use_pallas:
        from . import tsdf_pallas

        return tsdf_pallas.combine_pallas(tsdf, weight, rgb, num, den,
                                          rgbnum, max_weight)
    wsum = weight + den
    safe = jnp.maximum(wsum, 1e-12)
    new_tsdf = jnp.where(den > 0.0, (tsdf * weight + num) / safe, tsdf)
    new_rgb = jnp.where((den > 0.0)[..., None],
                        (rgb * weight[..., None] + rgbnum)
                        / safe[..., None], rgb)
    return new_tsdf, jnp.minimum(wsum, max_weight), new_rgb


@functools.lru_cache(maxsize=None)
def _integrate_fn(params: TSDFParams, use_pallas: bool):
    """One stop → volume, ONE launch, volume buffers donated in/out."""
    depth = int(params.grid_depth)
    cap = int(params.max_bricks)
    radius = int(params.splat_radius)
    r_vox = 1 << depth
    nb = r_vox // BS
    nb3 = nb ** 3
    offs = jnp.asarray(_window_offsets(radius), jnp.int32)
    trunc = jnp.float32(params.trunc_voxels)
    wmax = jnp.float32(params.max_weight)
    carve_steps = int(params.carve_steps)
    cw = jnp.float32(params.carve_weight)

    def run(dir_map, tsdf, weight, rgb, coords, n_bricks,
            points, colors, valid, dirs, origin, voxel):
        # -- per-point voxel window + projective TSDF samples ------------
        g = (points - origin[None, :]) / voxel             # (P, 3) grid
        v0 = jnp.floor(g).astype(jnp.int32)
        vox = v0[:, None, :] + offs[None, :, :]            # (P, K, 3)
        inb = jnp.all((vox >= 0) & (vox < r_vox), axis=-1)
        ok = valid[:, None] & inb
        center = vox.astype(jnp.float32) + 0.5
        sdf = jnp.sum((center - g[:, None, :]) * dirs[:, None, :],
                      axis=-1)                             # voxel units
        u = jnp.clip(sdf / trunc, -1.0, 1.0)
        w = jnp.where(ok, jnp.maximum(1.0 - jnp.abs(u), 0.0), 0.0)
        ok = ok & (w > 0.0)

        # -- allocate newly touched bricks (prefix-sum, static shape) ----
        bc = vox >> 3                                      # brick coords
        cell = (bc[..., 0] * nb + bc[..., 1]) * nb + bc[..., 2]
        cell_s = jnp.where(ok, cell, nb3)
        touched = jnp.zeros((nb3 + 1,), jnp.int32).at[
            cell_s.reshape(-1)].max(1, mode="drop")[:nb3]
        new = (touched > 0) & (dir_map < 0)
        rank = jnp.cumsum(new.astype(jnp.int32)) - 1
        slot = n_bricks + rank
        alloc_ok = new & (slot < cap)
        dir_map = jnp.where(alloc_ok, slot, dir_map)
        n_wanted = n_bricks + jnp.sum(new.astype(jnp.int32))
        cid = jnp.arange(nb3, dtype=jnp.int32)
        bxyz = jnp.stack([cid // (nb * nb), (cid // nb) % nb, cid % nb],
                         axis=1)
        dest = jnp.where(alloc_ok, slot, cap)
        coords = coords.at[dest].set(bxyz, mode="drop")

        # -- scatter the stop's weighted sums into the brick pool --------
        slot_pt = dir_map[jnp.where(ok, cell, 0)]          # (P, K)
        intra = ((vox[..., 0] & 7) * BS + (vox[..., 1] & 7)) * BS \
            + (vox[..., 2] & 7)
        flat = jnp.where(ok & (slot_pt >= 0), slot_pt * V + intra,
                         cap * V).reshape(-1)
        num = jnp.zeros((cap * V,), jnp.float32).at[flat].add(
            (w * u).reshape(-1), mode="drop").reshape(cap, V)
        den = jnp.zeros((cap * V,), jnp.float32).at[flat].add(
            w.reshape(-1), mode="drop").reshape(cap, V)
        rgbnum = jnp.zeros((cap * V, 3), jnp.float32).at[flat].add(
            (w[..., None] * colors[:, None, :]).reshape(-1, 3),
            mode="drop").reshape(cap, V, 3)

        tsdf, weight, rgb = _combine(tsdf, weight, rgb, num, den, rgbnum,
                                     wmax, use_pallas)

        if carve_steps:
            # Free-space carving: voxel samples marching from one voxel
            # past the truncation band toward the camera are observed
            # EMPTY — decrement their weight so stale surface a moving
            # sensor no longer sees fades out. Grid coords are voxel
            # units, so stepping t voxels along the (unit, world) inward
            # direction is ``g − d̂·t``. Only already-allocated bricks
            # are touched (absent slots drop), and a fully-carved voxel
            # resets to the unobserved sentinel.
            qs = trunc + jnp.arange(1, carve_steps + 1,
                                    dtype=jnp.float32)
            samp = g[:, None, :] - dirs[:, None, :] * qs[None, :, None]
            cvox = jnp.floor(samp).astype(jnp.int32)
            cinb = jnp.all((cvox >= 0) & (cvox < r_vox), axis=-1)
            cok = valid[:, None] & cinb
            cbc = cvox >> 3
            ccell = (cbc[..., 0] * nb + cbc[..., 1]) * nb + cbc[..., 2]
            cslot = dir_map[jnp.where(cok, ccell, 0)]
            cintra = ((cvox[..., 0] & 7) * BS + (cvox[..., 1] & 7)) * BS \
                + (cvox[..., 2] & 7)
            cflat = jnp.where(cok & (cslot >= 0), cslot * V + cintra,
                              cap * V).reshape(-1)
            hits = jnp.zeros((cap * V,), jnp.float32).at[cflat].add(
                jnp.ones(cflat.shape, jnp.float32),
                mode="drop").reshape(cap, V)
            # Multiplicative decay — scale-free in the accumulated
            # weight, so stale surface fades at the same rate however
            # confidently it was once observed.
            new_w = weight * jnp.exp(-cw * hits)
            erased = (hits > 0.0) & (new_w < 1e-3)
            tsdf = jnp.where(erased, -1.0, tsdf)
            weight = jnp.where(erased, 0.0, new_w)

        return (dir_map, tsdf, weight, rgb, coords,
                jnp.minimum(n_wanted, cap), n_wanted)

    return jax.jit(run, donate_argnums=(0, 1, 2, 3, 4))


def integrate(state: TSDFState, params: TSDFParams, points, colors,
              valid, dirs, origin, voxel_size,
              use_pallas: bool | None = None):
    """Fuse one stop (world-frame arrays) into the volume.

    ``points`` (P, 3) f32, ``colors`` (P, 3) f32 (0–255 scale),
    ``valid`` (P,) bool, ``dirs`` (P, 3) f32 unit INWARD directions
    (:func:`camera_dirs` / ``−normals``). Returns ``(state, n_wanted)``
    — ``n_wanted > params.max_bricks`` means the pool overflowed and the
    excess bricks were dropped (holes, never an error; the caller logs).
    The state buffers are DONATED: the passed-in state must not be
    reused."""
    if use_pallas is None:
        use_pallas = _backend.tpu_backend()
    out = _integrate_fn(params, bool(use_pallas))(
        state.dir_map, state.tsdf, state.weight, state.rgb,
        state.brick_coords, state.n_bricks,
        jnp.asarray(points, jnp.float32), jnp.asarray(colors, jnp.float32),
        jnp.asarray(valid, bool), jnp.asarray(dirs, jnp.float32),
        jnp.asarray(origin, jnp.float32),
        jnp.asarray(voxel_size, jnp.float32))
    return TSDFState(*out[:6]), out[6]


@jax.jit
def camera_dirs(points, cam):
    """Unit inward directions for a streaming stop: along the viewing
    ray, away from the camera center ``cam`` (3,) — behind the observed
    point is inside. Degenerate points at the camera get a safe axis."""
    d = points - cam[None, :]
    n = jnp.linalg.norm(d, axis=-1, keepdims=True)
    return jnp.where(n > 1e-9, d / jnp.maximum(n, 1e-9),
                     jnp.asarray([0.0, 0.0, 1.0], jnp.float32))


@functools.lru_cache(maxsize=None)
def _neighbor_fn(params: TSDFParams):
    """(state) → (nbr (cap, 6), block_valid (cap,)) for the marching
    extractors: face-neighbor slots through the dense directory, absent
    (or out-of-band) = cap — the `poisson_sparse` ``nbr`` contract."""
    cap = int(params.max_bricks)
    nb = params.nb
    nb3 = nb ** 3
    dirs6 = jnp.asarray([[1, 0, 0], [-1, 0, 0], [0, 1, 0],
                         [0, -1, 0], [0, 0, 1], [0, 0, -1]], jnp.int32)

    def run(dir_map, coords, n_bricks):
        row_ok = jnp.arange(cap, dtype=jnp.int32) < n_bricks
        nbc = coords[:, None, :] + dirs6[None, :, :]       # (cap, 6, 3)
        inb = jnp.all((nbc >= 0) & (nbc < nb), axis=-1)
        cell = (nbc[..., 0] * nb + nbc[..., 1]) * nb + nbc[..., 2]
        slot = dir_map[jnp.where(inb, cell, 0)]
        nbr = jnp.where(inb & (slot >= 0) & row_ok[:, None], slot, cap)
        # A neighbor row past n_bricks (stale slot) also reads as absent.
        nbr = jnp.where(nbr < n_bricks, nbr, cap)
        return nbr.astype(jnp.int32), row_ok

    return jax.jit(run)


def neighbor_table(state: TSDFState, params: TSDFParams):
    return _neighbor_fn(params)(state.dir_map, state.brick_coords,
                                state.n_bricks)


# ---------------------------------------------------------------------------
# NumPy oracle (dense grid, same float32 formulas)
# ---------------------------------------------------------------------------


def integrate_oracle(dense, points, colors, valid, dirs, origin,
                     voxel_size, params: TSDFParams):
    """Dense-grid NumPy reference for :func:`integrate`.

    ``dense`` is ``None`` (fresh volume) or the ``(tsdf, weight, rgb)``
    triple a previous call returned — dense ``(R, R, R[, 3])`` float32
    arrays. Same window, same projective distance, same running-average
    fold, all in float32; the only divergence from the device op is
    scatter-add ORDER (parity is allclose, not bitwise)."""
    r_vox = params.resolution
    if dense is None:
        tsdf = _np.full((r_vox,) * 3, -1.0, _np.float32)
        weight = _np.zeros((r_vox,) * 3, _np.float32)
        rgb = _np.zeros((r_vox,) * 3 + (3,), _np.float32)
    else:
        tsdf, weight, rgb = (a.copy() for a in dense)
    pts = _np.asarray(points, _np.float32)
    cols = _np.asarray(colors, _np.float32)
    val = _np.asarray(valid, bool)
    dr = _np.asarray(dirs, _np.float32)
    origin = _np.asarray(origin, _np.float32)
    voxel = _np.float32(voxel_size)
    trunc = _np.float32(params.trunc_voxels)

    g = (pts - origin[None, :]) / voxel
    v0 = _np.floor(g).astype(_np.int64)
    num = _np.zeros_like(tsdf)
    den = _np.zeros_like(weight)
    rgbnum = _np.zeros_like(rgb)
    for off in _window_offsets(params.splat_radius):
        vox = v0 + off[None, :]
        ok = val & _np.all((vox >= 0) & (vox < r_vox), axis=-1)
        center = vox.astype(_np.float32) + _np.float32(0.5)
        sdf = _np.sum((center - g) * dr, axis=-1, dtype=_np.float32)
        u = _np.clip(sdf / trunc, -1.0, 1.0).astype(_np.float32)
        w = _np.where(ok, _np.maximum(1.0 - _np.abs(u), 0.0),
                      0.0).astype(_np.float32)
        ok = ok & (w > 0.0)
        ix, iy, iz = (vox[ok, i] for i in range(3))
        _np.add.at(num, (ix, iy, iz), w[ok] * u[ok])
        _np.add.at(den, (ix, iy, iz), w[ok])
        _np.add.at(rgbnum, (ix, iy, iz), w[ok, None] * cols[ok])

    wsum = weight + den
    safe = _np.maximum(wsum, _np.float32(1e-12))
    tsdf = _np.where(den > 0.0, (tsdf * weight + num) / safe, tsdf)
    rgb = _np.where((den > 0.0)[..., None],
                    (rgb * weight[..., None] + rgbnum) / safe[..., None],
                    rgb)
    weight = _np.minimum(wsum, _np.float32(params.max_weight))

    if params.carve_steps:
        hits = _np.zeros_like(weight)
        cw = _np.float32(params.carve_weight)
        for q in range(1, int(params.carve_steps) + 1):
            samp = g - dr * _np.float32(trunc + q)
            cvox = _np.floor(samp).astype(_np.int64)
            cok = val & _np.all((cvox >= 0) & (cvox < r_vox), axis=-1)
            ix, iy, iz = (cvox[cok, i] for i in range(3))
            _np.add.at(hits, (ix, iy, iz), _np.float32(1.0))
        new_w = weight * _np.exp(-cw * hits, dtype=_np.float32)
        erased = (hits > 0.0) & (new_w < 1e-3)
        tsdf = _np.where(erased, _np.float32(-1.0), tsdf)
        weight = _np.where(erased, _np.float32(0.0),
                           new_w).astype(_np.float32)

    return tsdf.astype(_np.float32), weight.astype(_np.float32), \
        rgb.astype(_np.float32)


def state_to_dense(state: TSDFState, params: TSDFParams):
    """Brick-pool state → dense ``(tsdf, weight, rgb)`` host arrays (the
    oracle's layout), for parity comparison and debugging."""
    r_vox = params.resolution
    tsdf = _np.full((r_vox,) * 3, -1.0, _np.float32)
    weight = _np.zeros((r_vox,) * 3, _np.float32)
    rgb = _np.zeros((r_vox,) * 3 + (3,), _np.float32)
    n = int(state.n_bricks)
    coords = _np.asarray(state.brick_coords)[:n]
    t = _np.asarray(state.tsdf)[:n].reshape(n, BS, BS, BS)
    w = _np.asarray(state.weight)[:n].reshape(n, BS, BS, BS)
    c = _np.asarray(state.rgb)[:n].reshape(n, BS, BS, BS, 3)
    for i, (bx, by, bz) in enumerate(coords):
        sl = (slice(bx * BS, bx * BS + BS), slice(by * BS, by * BS + BS),
              slice(bz * BS, bz * BS + BS))
        tsdf[sl] = t[i]
        weight[sl] = w[i]
        rgb[sl] = c[i]
    return tsdf, weight, rgb
