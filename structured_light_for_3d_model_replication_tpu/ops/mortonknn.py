"""Morton-blocked KNN — the gather-free large-N neighborhood engine.

The spatial-grid KNN (ops/gridknn.py) is algorithmically right but
bandwidth-wrong on TPU: per-query candidate collection is a huge RANDOM
gather ((N, 27, C) indices), and random gathers are the one memory pattern
a TPU does poorly. This module restructures the same idea so that ALL bulk
data movement is contiguous:

1. sort points once by 30-bit Morton code (10 bits/axis, interleaved) —
   the space-filling curve puts spatial neighbors next to each other in
   memory;
2. reshape the sorted cloud into blocks of B points; the candidate set of
   every query in block b is blocks b−1, b, b+1 — THREE CONTIGUOUS SLICES,
   materialized with two rolls and a concat, no gather;
3. distances are one batched (B × 3B) matmul per block; top-k via the
   TPU's PartialReduce (`approx_min_k`) + a tiny exact sort of k.

Approximate by construction: a true neighbor further than one block away
along the curve is missed. Measured on surface-scan data at k=20:
recall ≈ 0.89 / 0.93 / 0.95 for B = 128 / 256 / 512 — but the MISSED
neighbors are replaced by near-equidistant ones (median k-th-distance
error ≈ 0), so the consumers this engine serves — SOR statistics, PCA
normals, FPFH histograms — agree with the exact engine to >99% (see
tests/test_spatial_knn.py). Block size is the recall lever; exactness,
when needed, lives in ops/knn.py.

O(N·3B) FLOPs, fully dense, one sort. The reference's KDTree
(`server/processing.py:64,87`) does fewer FLOPs and loses by orders of
magnitude on a vector machine.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BITS = 10
_GRID_MAX = (1 << _BITS) - 1


def _spread_bits(v: jnp.ndarray) -> jnp.ndarray:
    """10-bit int → bits spread to every 3rd position (Morton interleave)."""
    v = (v | (v << 16)) & 0x030000FF
    v = (v | (v << 8)) & 0x0300F00F
    v = (v | (v << 4)) & 0x030C30C3
    v = (v | (v << 2)) & 0x09249249
    return v


def morton_code(cell: jnp.ndarray) -> jnp.ndarray:
    """(N, 3) int32 grid coords in [0, 1023] → (N,) 30-bit Morton code."""
    return (_spread_bits(cell[:, 0])
            | (_spread_bits(cell[:, 1]) << 1)
            | (_spread_bits(cell[:, 2]) << 2))


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5),
                   static_argnames=("axis_rot",))
def _morton_knn_impl(points, valid, k, block, chunk_blocks, exclude_self,
                     axis_rot: int = 0):
    n = points.shape[0]

    # Quantize to the Morton grid: finest cells that keep 10 bits/axis.
    # ``axis_rot`` rotates which axis owns which interleave position —
    # multi-pass callers use it to build a STRUCTURALLY different curve
    # whose long jumps land elsewhere, so a second pass recovers neighbors
    # the first curve split apart.
    mins = jnp.min(jnp.where(valid[:, None], points, jnp.inf), axis=0)
    maxs = jnp.max(jnp.where(valid[:, None], points, -jnp.inf), axis=0)
    h = jnp.maximum(jnp.max(maxs - mins) / _GRID_MAX, 1e-12)
    cell = jnp.clip(((points - mins) / h).astype(jnp.int32), 0, _GRID_MAX)
    if axis_rot:
        cell = jnp.roll(cell, axis_rot, axis=1)
    code = morton_code(cell)
    # Invalid points sort to the end (and never match as neighbors).
    sort_key = jnp.where(valid, code, jnp.int32(2**31 - 1))

    order = jnp.argsort(sort_key)
    pts_s = points[order]
    val_s = valid[order]
    orig_s = order.astype(jnp.int32)

    # Pad to a whole number of blocks.
    pad = (-n) % block
    if pad:
        pts_s = jnp.concatenate(
            [pts_s, jnp.zeros((pad, 3), pts_s.dtype)])
        val_s = jnp.concatenate([val_s, jnp.zeros(pad, bool)])
        orig_s = jnp.concatenate(
            [orig_s, jnp.zeros(pad, jnp.int32)])
    nb = pts_s.shape[0] // block
    bp = pts_s.reshape(nb, block, 3)
    bv = val_s.reshape(nb, block)
    bi = orig_s.reshape(nb, block)

    # Candidates of block b = blocks b−1, b, b+1 (rolled: the two edge
    # blocks see a wrapped far-away block — eliminated by distance).
    def with_neighbors(x):
        return jnp.concatenate(
            [jnp.roll(x, 1, axis=0), x, jnp.roll(x, -1, axis=0)], axis=1)

    cp = with_neighbors(bp)   # (nb, 3B, 3)
    cv = with_neighbors(bv)   # (nb, 3B)
    ci = with_neighbors(bi)   # (nb, 3B)

    hi = jax.lax.Precision.HIGHEST

    def per_chunk(args):
        q, qv, qi, kp, kv, ki = args
        # (C, B, 3B) squared distances via the matmul expansion.
        q2 = jnp.sum(q * q, axis=-1)                      # (C, B)
        p2 = jnp.sum(kp * kp, axis=-1)                    # (C, 3B)
        cross = jnp.einsum("cbd,cnd->cbn", q, kp, precision=hi)
        d2 = q2[..., :, None] + p2[..., None, :] - 2.0 * cross
        bad = ~kv[..., None, :]
        if exclude_self:
            bad = bad | (qi[..., :, None] == ki[..., None, :])
        d2 = jnp.where(bad, jnp.inf, d2)
        flat = d2.reshape(-1, d2.shape[-1])               # (C*B, 3B)
        cd, carg = jax.lax.approx_min_k(flat, k, recall_target=0.99)
        cidx = jnp.take_along_axis(
            jnp.repeat(ki, block, axis=0).reshape(flat.shape[0], -1),
            carg, axis=1)
        neg, arg = jax.lax.top_k(-cd, k)                  # ascending order
        idx = jnp.take_along_axis(cidx, arg, axis=1)
        # Clamp epsilon-negative fp32 matmul-expansion distances: a NaN out
        # of a later sqrt would poison SOR's global statistics.
        dd = jnp.maximum(-neg, 0.0)
        okq = qv.reshape(-1)[:, None]
        nb_ok = jnp.isfinite(dd) & okq
        return jnp.where(jnp.isfinite(dd), dd, 0.0), idx, nb_ok

    cb = chunk_blocks
    nb_pad = (-nb) % cb
    if nb_pad:
        def padb(x):
            return jnp.concatenate(
                [x, jnp.zeros((nb_pad,) + x.shape[1:], x.dtype)])
        bp, bv, bi, cp, cv, ci = map(padb, (bp, bv, bi, cp, cv, ci))
    groups = bp.shape[0] // cb

    def g(x):
        return x.reshape((groups, cb) + x.shape[1:])

    d, i, v = jax.lax.map(per_chunk, (g(bp), g(bv), g(bi),
                                      g(cp), g(cv), g(ci)))
    d = d.reshape(-1, k)[: nb * block]
    i = i.reshape(-1, k)[: nb * block]
    v = v.reshape(-1, k)[: nb * block]

    # Un-sort: sorted row r belongs to original index orig_s[r]; sorted
    # rows ≥ n are block padding and scatter to a dump row. (Invalid INPUT
    # points occupy genuine sorted rows < n; their nb_ok is already False.)
    pos = jnp.where(jnp.arange(nb * block) < n, orig_s, n)
    out_d = jnp.zeros((n + 1, k), jnp.float32).at[pos].set(d)[:n]
    out_i = jnp.zeros((n + 1, k), jnp.int32).at[pos].set(i)[:n]
    out_v = jnp.zeros((n + 1, k), bool).at[pos].set(v)[:n]
    return out_d, out_i, out_v


@functools.partial(jax.jit, static_argnums=(3,))
def _merge_passes(ds, is_, vs, k):
    """Merge per-pass (N, k) results: dedup by neighbor index, keep the
    k nearest. Small per-row work (2k-wide sorts)."""
    d = jnp.concatenate(ds, axis=1)
    i = jnp.concatenate(is_, axis=1)
    v = jnp.concatenate(vs, axis=1)
    d = jnp.where(v, d, jnp.inf)
    # Sort by index so duplicates are adjacent, then invalidate repeats.
    order = jnp.argsort(i, axis=1)
    d2 = jnp.take_along_axis(d, order, axis=1)
    i2 = jnp.take_along_axis(i, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((i2.shape[0], 1), bool), i2[:, 1:] == i2[:, :-1]], axis=1)
    d2 = jnp.where(dup, jnp.inf, d2)
    neg, arg = jax.lax.top_k(-d2, k)
    out_i = jnp.take_along_axis(i2, arg, axis=1)
    out_d = jnp.maximum(-neg, 0.0)
    ok = jnp.isfinite(out_d)
    return jnp.where(ok, out_d, 0.0), out_i, ok


def morton_knn(
    points: jnp.ndarray,
    k: int,
    points_valid: jnp.ndarray | None = None,
    exclude_self: bool = False,
    block: int = 256,
    chunk_blocks: int = 64,
    passes: int = 1,
):
    """Self-query approximate KNN over the Morton curve (module docstring).

    Same contract as ``knn``: (sq_dists (N,k), indices (N,k),
    neighbor_valid (N,k)), distances ascending. ``passes`` > 1 (≤ 3)
    repeats the search over axis-rotated Morton curves and merges the
    deduplicated candidates; measured misses are largely window-limited
    and correlated across curves, so extra passes buy little recall
    (~+0.5 pt each) — prefer a larger ``block`` when recall matters.
    """
    points = jnp.asarray(points, jnp.float32)
    n = points.shape[0]
    if points_valid is None:
        points_valid = jnp.ones(n, dtype=bool)
    if 3 * block < k + (1 if exclude_self else 0):
        raise ValueError(f"block {block} too small for k={k}")
    outs = [
        _morton_knn_impl(points, points_valid, k, block, chunk_blocks,
                         exclude_self, axis_rot=p % 3)
        for p in range(passes)
    ]
    if passes == 1:
        return outs[0]
    return _merge_passes(tuple(o[0] for o in outs),
                         tuple(o[1] for o in outs),
                         tuple(o[2] for o in outs), k)
