"""DBSCAN clustering — keep-largest-cluster cleanup.

Replaces Open3D ``cluster_dbscan`` as used by the reference's outlier lab
(`Old/StatisticalOutlierRemoval.py:5-32`: eps=5, min_points=200, then keep
the biggest cluster and call everything else noise).

DBSCAN's textbook formulation is a frontier BFS — hostile to a vector
machine. The TPU formulation here is iterative min-label propagation on the
ε-neighborhood graph:

1. ε-neighborhoods from the tiled-matmul KNN (capped at ``max_nn`` edges per
   point — exact for clouds whose local density stays under the cap; the cap
   only ever SPLITS a cluster, never merges two);
2. core points = ≥ min_points neighbors (self included, DBSCAN convention);
3. every core point starts labeled with its own index; each sweep takes the
   min label over {self} ∪ core neighbors — labels flow only THROUGH core
   points, exactly DBSCAN's density-connectivity. Edges are propagated both
   directions (scatter-min over the directed KNN edge list and its reverse),
   so the truncated KNN lists still behave as an undirected graph;
4. border points adopt the min label among their core neighbors at the end;
   everything else is noise (−1). ``lax.while_loop`` runs sweeps until the
   labels reach a fixed point (≤ graph diameter iterations).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .knn import knn


@functools.partial(jax.jit, static_argnames=("min_points", "max_nn"))
def dbscan(
    points: jnp.ndarray,
    eps: float,
    min_points: int = 200,
    valid: jnp.ndarray | None = None,
    max_nn: int = 64,
):
    """Returns (labels (N,) int32, n_clusters). Noise/invalid → −1.

    Labels are compacted to 0..n_clusters−1 in first-seen (min-index) order.
    """
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    pts = jnp.asarray(points, jnp.float32)

    d2, idx, nbv = knn(pts, max_nn, points_valid=valid)
    in_eps = nbv & (d2 <= eps * eps)            # (N, K), self included
    n_nbrs = jnp.sum(in_eps, axis=1)
    core = valid & (n_nbrs >= min_points)

    big = jnp.int32(n)  # "no label yet" sentinel (> any real index)
    init = jnp.where(core, jnp.arange(n, dtype=jnp.int32), big)

    rows = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None],
                            idx.shape)
    edge_ok = in_eps & core[rows] & core[idx]   # core–core edges only

    def sweep(labels):
        # forward: row takes min of its listed core neighbors' labels
        nb_lab = jnp.where(edge_ok, labels[idx], big)
        fwd = jnp.minimum(labels, jnp.min(nb_lab, axis=1))
        # reverse: scatter each row's label to its listed neighbors
        src_lab = jnp.where(edge_ok, fwd[rows], big)
        rev = jnp.full(n, big, jnp.int32).at[idx.reshape(-1)].min(
            src_lab.reshape(-1))
        return jnp.where(core, jnp.minimum(fwd, rev), big)

    def cond(state):
        labels, prev_changed = state
        return prev_changed

    def body(state):
        labels, _ = state
        new = sweep(labels)
        return new, jnp.any(new != labels)

    labels, _ = jax.lax.while_loop(cond, body, (init, jnp.bool_(True)))

    # Border points: min label among core ε-neighbors.
    nb_core_lab = jnp.where(in_eps & core[idx], labels[idx], big)
    border_lab = jnp.min(nb_core_lab, axis=1)
    full = jnp.where(core, labels,
                     jnp.where(valid & (border_lab < big), border_lab, big))

    # Compact root indices to 0..C-1 (roots are label==own-index core pts).
    is_root = core & (labels == jnp.arange(n, dtype=jnp.int32))
    compact = jnp.cumsum(is_root.astype(jnp.int32)) - 1  # root rank at root
    out = jnp.where(full < big, compact[jnp.clip(full, 0, n - 1)], -1)
    return out.astype(jnp.int32), jnp.sum(is_root.astype(jnp.int32))


def keep_largest_cluster(points, eps, min_points=200, valid=None,
                         max_nn: int = 64):
    """The reference's cleanup recipe (`Old/StatisticalOutlierRemoval.py:
    5-32`): cluster, then keep only the most populous cluster. Returns the
    surviving mask (all-noise clouds keep everything, like the reference's
    early-return)."""
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    labels, n_clusters = dbscan(points, eps, min_points, valid, max_nn)
    counts = jax.ops.segment_sum(
        (labels >= 0).astype(jnp.int32), jnp.clip(labels, 0, n - 1),
        num_segments=n,
    )
    biggest = jnp.argmax(counts)
    keep = valid & (labels == biggest)
    return jnp.where(n_clusters > 0, keep, valid)
