"""Pallas TPU kernel for the brick-grid rescue KNN (`ops/brickknn.py`).

Round-2 measured the XLA brick engine at ~4.7 s vs Morton's ~0.95 s at
1M/k=20 — 4.9× for the window-exact candidate set where the VERDICT asked
≤ 1.5×. XProf showed the gap is NOT the distance math (42 ms) or even the
27-brick gathers (80 ms): it is TPU-hostile index bookkeeping —
``take_along_axis`` chains (2.1 s), ``approx_top_k`` over (rows, 864)
(0.73 s), 27-way ``searchsorted`` (0.46 s) and scattering the 3.3×-padded
brick rows back to point order (0.65 s).

This kernel eliminates the bookkeeping instead of accelerating it:

* the candidate "gather" is DMA addressing — per query cell the kernel
  walks its (compacted, present-first) neighbor-brick list and DMAs each
  brick's packed ``x|y|z|id`` 128-lane row straight into VMEM,
  double-buffered in stages of 4;
* distances accumulate into a (CP·32, 896) VMEM tile packed with the
  candidate's lane id in the LOW 10 MANTISSA BITS (896 < 1024 lanes, so
  the packing is a total order: ties cannot produce duplicate picks) —
  the same trick as `ops/nn_pallas.py`/`ops/knn.py` packed top-k;
* selection is THRESHOLD extraction: the k-th pick is "min of packed
  values strictly above the (k-1)-th" — one fused where+min pass per k,
  no masking writes, no sort, no approx_top_k, no position gathers. The
  global point id of each pick is selected in the same pass from a
  parallel id tile, so the output needs NO local→global translation;
* 4 cells share a grid step (CP=4): extraction reductions run on all 128
  VPU sublanes instead of 32 (measured 0.69 → 0.52 s kernel time);
* outputs land in brick order; the caller maps them to point order with
  ONE (N, k) row gather instead of scattering every padded brick row.

Packing cost: returned d² has its low 10 mantissa bits cleared (≤ 2⁻¹³
relative underestimate) and near-exact ties at the k-th distance may
resolve differently than exact f32 — measured recall vs brute force stays
≥ 0.99 (`tests/test_spatial_knn.py`). The XLA path in `ops/brickknn.py`
remains the exact oracle and the CPU fallback.

Replaces the Open3D KDTree exactness role of the reference
(`server/processing.py:64,87`) at TPU speed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _backend
from .brickknn import _grid_cells, _sorted_segments
from ..utils.log import get_logger

log = get_logger(__name__)

S = 32            # brick slots (queries AND candidates per cell)
NB = 27           # 3³ neighbor window
G = 7             # DMA stages of 4 bricks (7·4 = 28 ≥ 27)
CP = 4            # cells per grid step (128 query sublanes)
W = G * 4 * S     # 896 candidate lanes per cell
MAX_K = 32        # output block width
BIGID = 3.0e7     # id sentinel (exact in f32; > any real point id)
_BITS = 10
_GRID_MAX = (1 << _BITS) - 1
_BIG = 1 << 30


def available() -> bool:
    return _backend.tpu_backend()


def _kernel(nbr_ref, nnb_ref, q_ref, bpc_hbm, d_ref, i_ref,
            cand, work, ridq, sem, *, k: int, exclude_self: bool):
    cbase = pl.program_id(0) * CP
    inf = float("inf")

    # Ghost steps (cells past the occupied count — the static budget is
    # generous) have nnb == 0 for every sub: skip the whole body. Their
    # output rows are never gathered (gatherpos can't point at them).
    any_live = sum(nnb_ref[0, sub, 0] for sub in range(CP)) > 0

    @pl.when(any_live)
    def _body():
        _kernel_body(nbr_ref, nnb_ref, q_ref, bpc_hbm, d_ref, i_ref,
                     cand, work, ridq, sem, k=k, exclude_self=exclude_self,
                     cbase=cbase)


def _kernel_body(nbr_ref, nnb_ref, q_ref, bpc_hbm, d_ref, i_ref,
                 cand, work, ridq, sem, *, k: int, exclude_self: bool,
                 cbase):
    inf = float("inf")

    def dma(slot, sub, u, jj):
        return pltpu.make_async_copy(
            bpc_hbm.at[nbr_ref[0, sub, jj]], cand.at[slot, sub, u],
            sem.at[slot, sub, u])

    def start_stage(slot, g):
        for sub in range(CP):
            for u in range(4):
                dma(slot, sub, u, jnp.minimum(g * 4 + u, NB - 1)).start()

    # Dynamic stage count: surface cells average ~14 live neighbors, so
    # half the 7 stages would DMA dead bricks (the kernel is DMA-bound:
    # 112 copies/step at the static count). Stages never entered leave
    # stale lanes -> one upfront inf-fill masks them.
    nnmax = nnb_ref[0, 0, 0]
    for sub in range(1, CP):
        nnmax = jnp.maximum(nnmax, nnb_ref[0, sub, 0])
    gmax = (nnmax + 3) // 4
    work[...] = jnp.full_like(work, inf)

    start_stage(0, 0)
    q = q_ref[0]                           # (CP·S, 3)
    qx = q[:, 0:1]
    qy = q[:, 1:2]
    qz = q[:, 2:3]

    def stage(g, _):
        slot = jax.lax.rem(g, 2)
        nxt = jax.lax.rem(g + 1, 2)

        @pl.when(g + 1 < gmax)
        def _():
            start_stage(nxt, g + 1)

        uparts = []
        idparts = []
        eye = (jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
               == jax.lax.broadcasted_iota(jnp.int32, (S, S), 1))
        for u in range(4):
            jj = g * 4 + u
            jc = jnp.minimum(jj, NB - 1)
            subparts = []
            subids = []
            for sub in range(CP):
                dma(slot, sub, u, jc).wait()
                kp = cand[slot, sub, u]               # (1, 128)
                sq = slice(sub * S, (sub + 1) * S)
                dx = qx[sq] - kp[:, 0:S]
                dy = qy[sq] - kp[:, S:2 * S]
                dz = qz[sq] - kp[:, 2 * S:3 * S]
                d2 = dx * dx + dy * dy + dz * dz      # (S, S)
                if exclude_self:
                    own = nbr_ref[0, sub, jc] == cbase + sub
                    d2 = jnp.where(own & eye, inf, d2)
                d2 = jnp.where(jj < nnb_ref[0, sub, 0], d2, inf)
                subparts.append(d2)
                subids.append(jnp.broadcast_to(kp[:, 3 * S:], (S, S)))
            uparts.append(jnp.concatenate(subparts, axis=0))
            idparts.append(jnp.concatenate(subids, axis=0))
        slab = jnp.concatenate(uparts, axis=1)        # (CP·S, 128)
        idslab = jnp.concatenate(idparts, axis=1)
        # Lane id in the low mantissa (denormal-floored first so FTZ can't
        # erase it; NaN/inf from empty-slot sentinels -> +inf, id dropped).
        slab = jnp.maximum(slab, 1e-30)
        bits = jax.lax.bitcast_convert_type(slab, jnp.int32)
        lane = (jax.lax.broadcasted_iota(jnp.int32, (CP * S, 128), 1)
                + g * 128)
        pk = (bits & ~jnp.int32(_GRID_MAX)) | lane
        pk = jnp.where(jnp.isfinite(slab),
                       jax.lax.bitcast_convert_type(pk, jnp.float32),
                       jnp.float32(jnp.inf))
        work[:, pl.ds(g * 128, 128)] = pk
        ridq[:, pl.ds(g * 128, 128)] = idslab
        return 0

    jax.lax.fori_loop(0, gmax, stage, 0)

    w = work[...]                          # (CP·S, W) packed
    ridb = ridq[...]
    t = jnp.full((CP * S, 1), -jnp.inf, jnp.float32)
    for kk in range(k):
        m = jnp.min(jnp.where(w > t, w, inf), axis=1, keepdims=True)
        sel = jnp.min(jnp.where(w == m, ridb, BIGID), axis=1, keepdims=True)
        mb = (jax.lax.bitcast_convert_type(m, jnp.int32)
              & ~jnp.int32(_GRID_MAX))
        d_ref[0, :, kk] = jax.lax.bitcast_convert_type(mb, jnp.float32)[:, 0]
        i_ref[0, :, kk] = sel[:, 0].astype(jnp.int32)
        t = m
    for kk in range(k, MAX_K):             # unused output lanes
        d_ref[0, :, kk] = jnp.full((CP * S,), inf, jnp.float32)
        i_ref[0, :, kk] = jnp.zeros((CP * S,), jnp.int32)


@functools.partial(
    jax.jit,
    static_argnums=(2, 3, 4, 5, 6))
def _brick_knn_pallas_impl(points, valid, k, exclude_self, cell_scale_x100,
                           max_cells, interpret):
    n = points.shape[0]
    m_cells = max_cells

    # --- cell assignment: shared with the XLA engine ---
    h, quantize = _grid_cells(points, valid, k, cell_scale_x100)

    # Occupancy retarget: the r_k-derived cell size packs surface clouds
    # at ~5 points/cell — 1M points occupy ~220k cells, blowing the cell
    # budget AND paying the kernel's fixed per-cell cost on mostly-empty
    # bricks. Growing h only widens the exact window (recall cannot
    # drop), but fixed 32-slot bricks overflow where the cloud is
    # DENSEST, so the safe growth is set by the tail of the occupancy
    # distribution, not its mean. Probe the p99.5 PER-POINT occupancy at
    # h and 2h (sort + histogram, no percentile sort): their ratio gives
    # the local packing exponent at the dense cells (≈2² for surfaces,
    # ≈2³ for volumetric cores), then grow h until that tail occupancy
    # reaches ~28 of the 32 slots. A cell-budget floor keeps giant
    # uniform clouds inside max_cells. Overflow stays counted and warned.
    def occ_probe(hh):
        cs = jnp.sort(quantize(hh))
        vs = cs < _BIG
        firstp = jnp.concatenate(
            [cs[:1] < _BIG, (cs[1:] != cs[:-1]) & vs[1:]])
        rankp = jnp.cumsum(firstp.astype(jnp.int32)) - 1
        counts = jnp.zeros((n + 1,), jnp.int32).at[
            jnp.where(vs, rankp, n)].add(1)
        cpp = jnp.where(vs, counts[jnp.minimum(rankp, n - 1)], 0)
        # Invalid points land in bin 257, OUTSIDE the scanned range —
        # dumping them into bin 0 would satisfy the cumulative threshold
        # immediately on masked clouds (occ_hi = 0 → maximum growth →
        # mass slot overflow).
        hist = jnp.zeros((258,), jnp.int32).at[
            jnp.where(vs, jnp.minimum(cpp, 256), 257)].add(1)
        nv = jnp.maximum(jnp.sum(vs), 1)
        cum = jnp.cumsum(hist[:257])
        occ_hi = jnp.argmax(cum >= (0.995 * nv).astype(jnp.int32))
        return (jnp.maximum(occ_hi, 1).astype(jnp.float32),
                jnp.sum(firstp).astype(jnp.float32))

    occ0, cells0 = occ_probe(h)
    occ2, _ = occ_probe(2.0 * h)
    beta_p = jnp.clip(jnp.log2(jnp.maximum(occ2, occ0 * 1.1) / occ0),
                      1.5, 3.0)
    s_pack = jnp.maximum(28.0 / occ0, 1.0) ** (1.0 / beta_p)
    # cells(h·s) ≤ cells0/s² for any geometry with β ≥ 2.
    s_budget = jnp.sqrt(cells0 / (0.95 * m_cells))
    h = h * jnp.clip(jnp.maximum(s_pack, s_budget), 1.0, 4.0)
    cid = quantize(h)
    (cid_s, pts_s, val_s, orig_s, first, cell_rank, ok, dest,
     ucid) = _sorted_segments(points, valid, cid, S, m_cells)

    # --- brick arrays ---
    # Candidate side (M, 1, 128): x|y|z|gid lanes; empty slots carry +inf
    # coords (d² -> inf in-kernel) and the BIGID gid sentinel.
    row4 = jnp.concatenate(
        [pts_s, orig_s.astype(jnp.float32)[:, None]], axis=1)
    fill4 = jnp.asarray([jnp.inf, jnp.inf, jnp.inf, BIGID], jnp.float32)
    b4 = jnp.broadcast_to(fill4, (m_cells * S + 1, 4)).at[dest].set(row4)
    bpc = (b4[:-1].reshape(m_cells, S, 4).transpose(0, 2, 1)
           .reshape(m_cells, 1, 4 * S))
    # Query side (M, S, 3); empty query slots at 0 (their rows are never
    # gathered — gatherpos has no source pointing at them).
    bq = jnp.zeros((m_cells * S + 1, 3), jnp.float32).at[dest].set(
        pts_s)[:-1].reshape(m_cells, S, 3)
    # Point-order -> brick-order map for the final row gather; dropped
    # points land on the dump row (all-inf -> neighbor_valid False).
    gatherpos = jnp.full((n + 1,), m_cells * S, jnp.int32).at[
        jnp.where(ok, orig_s, n)].set(dest)[:n]

    # --- neighbor table: 13 directed deltas + mirror (the 27-delta
    # searchsorted was 0.46 s of the XLA engine; symmetry halves it) ---
    ux = ucid >> (2 * _BITS)
    uy = (ucid >> _BITS) & _GRID_MAX
    uz = ucid & _GRID_MAX
    all_deltas = [(dx, dy, dz) for dx in (-1, 0, 1) for dy in (-1, 0, 1)
                  for dz in (-1, 0, 1)]
    pos_deltas = jnp.asarray(all_deltas[14:], jnp.int32)      # 13 directed
    nxyz = (jnp.stack([ux, uy, uz], -1)[:, None, :]
            + pos_deltas[None])                               # (M, 13, 3)
    in_grid = jnp.all((nxyz >= 0) & (nxyz <= _GRID_MAX), axis=-1) \
        & (ucid < _BIG)[:, None]
    ncid = (nxyz[..., 0] << (2 * _BITS)) | (nxyz[..., 1] << _BITS) \
        | nxyz[..., 2]
    # Lookup by SORT-MERGE, not searchsorted: with in-range coordinates
    # the packed neighbor id is exactly ucid + const offset, so each
    # delta's query list is itself ascending — rank queries against the
    # table with one stable concat-argsort per delta (vmapped; ~13 small
    # sorts) instead of 1.7M binary searches (0.22 s of vmapped while).
    ncid_q = jnp.where(in_grid, ncid, _BIG)                   # (M, 13)

    def rank_in_table(queries):
        keys = jnp.concatenate([ucid, queries])
        order3 = jnp.argsort(keys, stable=True)   # ties: table first
        cum = jnp.cumsum((order3 < m_cells).astype(jnp.int32))
        inv = jnp.zeros((2 * m_cells,), jnp.int32).at[order3].set(
            jnp.arange(2 * m_cells, dtype=jnp.int32))
        c = cum[inv[m_cells:]]          # table entries ≤ query (stable)
        return c                        # rank+1 when present

    c13 = jax.vmap(rank_in_table, in_axes=1, out_axes=1)(ncid_q)
    pos_c = jnp.clip(c13 - 1, 0, m_cells - 1)
    found = in_grid & (c13 > 0) & (ucid[pos_c] == ncid)
    fwd = jnp.where(found, pos_c, m_cells)                    # (M, 13)

    nbr27 = jnp.full((m_cells, NB), m_cells, jnp.int32)
    # Self (slot 13) only for OCCUPIED ranks — a ghost cell (rank past
    # the occupied count) must end with nnb == 0 or the kernel's
    # whole-body skip never fires and every ghost step pays a full DMA
    # stage + extraction.
    nbr27 = nbr27.at[:, 13].set(jnp.where(
        ucid < _BIG, jnp.arange(m_cells, dtype=jnp.int32), m_cells))
    nbr27 = nbr27.at[:, 14:].set(fwd)
    # Mirror: if B is A's neighbor at directed delta d (slot 14+d), then A
    # is B's neighbor at the mirrored slot 12-d.
    mslot = jnp.arange(12, -1, -1, dtype=jnp.int32)           # (13,)
    mdest = jnp.where(found, pos_c * NB + mslot[None, :], m_cells * NB)
    msrc = jnp.broadcast_to(
        jnp.arange(m_cells, dtype=jnp.int32)[:, None], (m_cells, 13))
    nbr27 = nbr27.reshape(-1)
    nbr27 = jnp.concatenate([nbr27, jnp.zeros((1,), jnp.int32)]).at[
        mdest.reshape(-1)].set(msrc.reshape(-1))[:-1].reshape(m_cells, NB)

    # Present-first compaction; absent -> own rank (elided DMA revisits).
    present = nbr27 < m_cells
    key = jnp.where(present, jnp.arange(NB, dtype=jnp.int32)[None, :], 64)
    order2 = jnp.argsort(key, axis=1)
    nbr_c = jnp.take_along_axis(nbr27, order2, axis=1)
    nnb = jnp.sum(present, axis=1).astype(jnp.int32)
    own = jnp.arange(m_cells, dtype=jnp.int32)[:, None]
    nbr_c = jnp.where(nbr_c < m_cells, nbr_c, own)

    # --- kernel ---
    mg = m_cells // CP   # max_cells is CP-aligned (caller rounds)
    d, i = pl.pallas_call(
        functools.partial(_kernel, k=k, exclude_self=exclude_self),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(mg,),
            in_specs=[
                pl.BlockSpec((1, CP, NB), lambda c: (c, 0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, CP, 1), lambda c: (c, 0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, CP * S, 3), lambda c: (c, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec((1, CP * S, MAX_K), lambda c: (c, 0, 0)),
                pl.BlockSpec((1, CP * S, MAX_K), lambda c: (c, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, CP, 4, 1, 128), jnp.float32),
                pltpu.VMEM((CP * S, W), jnp.float32),
                pltpu.VMEM((CP * S, W), jnp.float32),
                pltpu.SemaphoreType.DMA((2, CP, 4)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((mg, CP * S, MAX_K), jnp.float32),
            jax.ShapeDtypeStruct((mg, CP * S, MAX_K), jnp.int32),
        ],
        interpret=interpret,
    )(nbr_c.reshape(mg, CP, NB), nnb.reshape(mg, CP, 1), bq.reshape(
        mg, CP * S, 3), bpc)

    # --- back to point order: ONE row gather (vs scattering every padded
    # brick row — 0.65 s of the XLA engine at 1M). No dump-row concat: a
    # concatenate here copies the whole 540 MB result before the gather
    # (measured 1.2 s of dynamic-update-slices); clamp + mask instead. ---
    d = d.reshape(m_cells * S, MAX_K)
    i = i.reshape(m_cells * S, MAX_K)
    in_brick = gatherpos < m_cells * S
    gp = jnp.minimum(gatherpos, m_cells * S - 1)
    # d[gp][:, :k], NOT d[gp, :k]: the fused gather-with-slice lowers to
    # a sequential dynamic-slice loop on TPU (measured 2.86 s vs 0.15 s
    # for gather-then-slice at 1M rows).
    out_d = d[gp][:, :k]
    out_i = i[gp][:, :k]
    out_v = (jnp.isfinite(out_d) & valid[:, None] & in_brick[:, None])
    out_d = jnp.where(out_v, out_d, 0.0)
    out_i = jnp.clip(jnp.where(out_v, out_i, 0), 0, n - 1)
    n_dropped = jnp.sum(val_s & ~ok)
    return out_d, out_i, out_v, n_dropped


MAX_N = 1 << 24  # point ids travel as exact f32 lanes


def brick_knn_pallas(points, valid, k: int, exclude_self: bool,
                     cell_scale_x100: int, max_cells: int,
                     interpret: bool = False):
    """Kernel-path entry used by :func:`..brickknn.brick_knn` dispatch.
    ``max_cells`` is rounded up to the CP grid multiple here."""
    if k > MAX_K:
        raise ValueError(f"pallas brick engine caps k at {MAX_K}, got {k}")
    if points.shape[0] > MAX_N:
        raise ValueError(
            f"pallas brick engine caps n at {MAX_N} (ids are exact-f32 "
            f"lanes), got {points.shape[0]}; use the XLA path")
    mc = ((max_cells + CP - 1) // CP) * CP
    return _brick_knn_pallas_impl(points, valid, k, exclude_self,
                                  cell_scale_x100, mc, interpret)
