"""Dense-grid screened-Poisson surface reconstruction (TPU-native).

The reference meshes with Open3D's octree screened-Poisson solver
(`create_from_point_cloud_poisson`, `server/processing.py:212,293`). An octree
is a pointer-chasing structure that maps poorly to a vector machine, so this
module trades the octree's adaptivity for a **regular dense voxel grid**, which
XLA tiles perfectly:

1. trilinear **splat** of the oriented normal field into a (R,R,R,3) vector
   grid V (plus a scalar sample-density grid) — one scatter-add;
2. **divergence** of V by central differences — shifts + adds, fully fused;
3. solve the screened Poisson equation ``(∇² − α·W)χ = ∇·V`` with **conjugate
   gradients** (`jax.lax` loop, 7-point Laplacian stencil as clamped shifts;
   W is the splat-density screen that pins χ near the samples);
4. pick the iso level as the density-weighted mean of χ at the sample points
   (trilinear gather), exactly the convention Kazhdan's solver uses.

Everything here is jitted and shape-static: ``depth`` (grid = 2^depth per
axis, reference guards depth ≤ 16 at `server/processing.py:207-208`; we guard
≤ 8 since dense 512³ exceeds sane HBM) and CG iteration count are compile-time
constants. Iso-surface extraction from the resulting grid lives in
:mod:`.marching` (host-side compaction of a device-computed field).

The splat-density grid doubles as the Open3D "density" output used for
quantile trimming (`server/processing.py:214-218,297-302`).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class PoissonGrid(NamedTuple):
    """Result of the Poisson solve, everything needed for extraction."""

    chi: jnp.ndarray      # (R, R, R) float32 implicit function
    density: jnp.ndarray  # (R, R, R) float32 splat density (trim support)
    iso: jnp.ndarray      # () float32 iso level at the surface
    origin: jnp.ndarray   # (3,) float32 world position of voxel (0,0,0) center
    scale: jnp.ndarray    # () float32 world size of one voxel


def normalize_points(points: jnp.ndarray, valid: jnp.ndarray, resolution: int,
                     pad_frac: float = 0.10):
    """Map points into grid coordinates [0, R-1] with a padded bounding cube.

    Returns (grid_pts (N,3), origin (3,), voxel_scale ()). The cube is
    isotropic (same scale on all axes) so normals keep their direction.
    """
    big = jnp.float32(1e30)
    v = valid[:, None]
    lo = jnp.min(jnp.where(v, points, big), axis=0)
    hi = jnp.max(jnp.where(v, points, -big), axis=0)
    extent = jnp.max(hi - lo)
    extent = jnp.where(extent > 1e-12, extent, 1.0)
    pad = extent * pad_frac
    scale = (extent + 2 * pad) / (resolution - 1)  # world units per voxel
    center = 0.5 * (lo + hi)
    origin = center - 0.5 * (extent + 2 * pad)
    grid_pts = (points - origin) / scale
    return grid_pts, origin, scale


def _corner_weights(grid_pts: jnp.ndarray, resolution: int):
    """Trilinear corner indices + weights for splat/gather.

    Returns (flat_idx (N,8) int32 into R³, w (N,8) float32).
    """
    g = jnp.clip(grid_pts, 0.0, resolution - 1 - 1e-4)
    i0 = jnp.floor(g).astype(jnp.int32)            # (N, 3)
    f = g - i0                                      # (N, 3)
    R = resolution
    corners = jnp.array(
        [[dx, dy, dz] for dx in (0, 1) for dy in (0, 1) for dz in (0, 1)],
        dtype=jnp.int32,
    )                                               # (8, 3)
    idx = i0[:, None, :] + corners[None, :, :]      # (N, 8, 3)
    idx = jnp.clip(idx, 0, R - 1)
    flat = (idx[..., 0] * R + idx[..., 1]) * R + idx[..., 2]  # (N, 8)
    cf = corners[None].astype(jnp.float32)          # (1, 8, 3)
    w = jnp.prod(cf * f[:, None, :] + (1 - cf) * (1 - f[:, None, :]), axis=-1)
    return flat, w


def splat(grid_pts: jnp.ndarray, values: jnp.ndarray, valid: jnp.ndarray,
          resolution: int) -> jnp.ndarray:
    """Trilinear scatter-add of per-point values (N,C) → (R,R,R,C)."""
    R = resolution
    flat, w = _corner_weights(grid_pts, R)
    w = w * valid.astype(jnp.float32)[:, None]
    contrib = w[..., None] * values[:, None, :]     # (N, 8, C)
    out = jnp.zeros((R * R * R, values.shape[-1]), jnp.float32)
    out = out.at[flat.reshape(-1)].add(contrib.reshape(-1, values.shape[-1]))
    return out.reshape(R, R, R, values.shape[-1])


def gather(grid: jnp.ndarray, grid_pts: jnp.ndarray) -> jnp.ndarray:
    """Trilinear interpolation of a (R,R,R) field at (N,3) grid coords."""
    R = grid.shape[0]
    flat, w = _corner_weights(grid_pts, R)
    vals = grid.reshape(-1)[flat]                   # (N, 8)
    return jnp.sum(vals * w, axis=-1)


def _shift(x: jnp.ndarray, axis: int, delta: int) -> jnp.ndarray:
    """Shift with edge-clamp (Neumann boundary): x[i] ← x[i+delta]."""
    n = x.shape[axis]
    if delta == 1:
        body = jax.lax.slice_in_dim(x, 1, n, axis=axis)
        edge = jax.lax.slice_in_dim(x, n - 1, n, axis=axis)
        return jnp.concatenate([body, edge], axis=axis)
    body = jax.lax.slice_in_dim(x, 0, n - 1, axis=axis)
    edge = jax.lax.slice_in_dim(x, 0, 1, axis=axis)
    return jnp.concatenate([edge, body], axis=axis)


def laplacian(x: jnp.ndarray) -> jnp.ndarray:
    """7-point Laplacian with Neumann (zero-flux) boundaries."""
    acc = -6.0 * x
    for axis in range(3):
        acc = acc + _shift(x, axis, 1) + _shift(x, axis, -1)
    return acc


def divergence(V: jnp.ndarray) -> jnp.ndarray:
    """Central-difference divergence of a (R,R,R,3) vector grid."""
    out = jnp.zeros(V.shape[:3], jnp.float32)
    for axis in range(3):
        c = V[..., axis]
        out = out + 0.5 * (_shift(c, axis, 1) - _shift(c, axis, -1))
    return out


def screen_weights(density, screen):
    """Normalized-density screen ``screen · density / mean(nonzero)`` —
    resolution-agnostic. THE recipe for every screened-Poisson operator
    in the package: the dense solve below, the band solve's fine screen,
    and the two-level preconditioner's coarse operator
    (`poisson_sparse._pcg_sparse`) must all normalize identically or the
    preconditioner silently stops matching the operator it corrects."""
    wmean = jnp.sum(density) / jnp.maximum(
        jnp.sum((density > 0).astype(jnp.float32)), 1.0)
    return screen * density / jnp.maximum(wmean, 1e-12)


@functools.partial(jax.jit,
                   static_argnames=("resolution", "cg_iters", "warm"))
def _solve(points, normals, valid, x0, resolution: int, cg_iters: int,
           screen: float, rtol=3e-4, *, warm: bool = True):
    R = resolution
    if not warm:
        # Cold start: the zeros grid is a workspace ALLOCATED INSIDE the
        # program — hoisting it to the caller would pin an extra
        # non-donated 2^3d operand (67 MB at depth 8) for the whole
        # solve. ``x0`` is a 0-d placeholder here.
        x0 = jnp.zeros((R, R, R), jnp.float32)
    grid_pts, origin, scale = normalize_points(points, valid, R)
    vw = splat(grid_pts, jnp.concatenate(
        [normals, jnp.ones((points.shape[0], 1), jnp.float32)], axis=-1),
        valid, R)
    V, density = vw[..., :3], vw[..., 3]
    rhs = divergence(V)

    W = screen_weights(density, screen)

    def A(x):
        return laplacian(x) - W * x

    # Jacobi-preconditioned CG on -A (symmetric positive-definite with the
    # screen term); the diagonal 6 + W removes the screening term's
    # density variation, the same preconditioner as the band-sparse
    # solver's fine CG (`ops/poisson_sparse.py:_cg_sparse` — measured
    # ~2.5× fewer iterations to tolerance there). ``cg_iters`` caps the
    # loop; the residual stop usually ends it sooner.
    b = -rhs

    def matvec(x):
        return -A(x)

    dinv = 1.0 / (6.0 + W)
    r0 = b - matvec(x0)
    z0 = dinv * r0
    rz0 = jnp.vdot(r0, z0)
    rtolf = jnp.float32(rtol)
    tol2 = rtolf * rtolf * jnp.vdot(b, b)

    def cond(state):
        _, _, _, _, rs, it = state
        return (it < cg_iters) & (rs > tol2)

    def body(state):
        x, r, p, rz, _, it = state
        Ap = matvec(p)
        alpha = rz / jnp.maximum(jnp.vdot(p, Ap), 1e-30)
        x = x + alpha * p
        r = r - alpha * Ap
        z = dinv * r
        rz_new = jnp.vdot(r, z)
        beta = rz_new / jnp.maximum(rz, 1e-30)
        p = z + beta * p
        return x, r, p, rz_new, jnp.vdot(r, r), it + 1

    chi, _, _, _, _, iters = jax.lax.while_loop(
        cond, body, (x0, r0, z0, rz0, jnp.vdot(r0, r0), jnp.int32(0)))

    # Iso level: density-weighted mean of chi at the samples.
    chi_at_pts = gather(chi, grid_pts)
    wpts = valid.astype(jnp.float32) * gather(density, grid_pts)
    iso = jnp.sum(chi_at_pts * wpts) / jnp.maximum(jnp.sum(wpts), 1.0)
    return PoissonGrid(chi, density, iso, origin, scale), iters


def reconstruct(points, normals, valid=None, depth: int = 6,
                cg_iters: int = 300, screen: float = 4.0,
                rtol: float = 3e-4, x0=None,
                return_iters: bool = False) -> PoissonGrid:
    """Screened-Poisson solve on a 2^depth dense grid.

    Drop-in for the solve half of `create_from_point_cloud_poisson`
    (`server/processing.py:212,293`); extraction is :func:`.marching.extract`.
    ``depth`` > 8 is rejected like the reference rejects > 16
    (`server/processing.py:207-208`) — dense 512³ does not fit sanely.
    ``cg_iters`` caps the PCG; the residual stop (``rtol``, same knob and
    measured-equal-quality 3e-4 default as
    :func:`..poisson_sparse.reconstruct_sparse`) usually ends it sooner.

    ``x0`` WARM-STARTS the CG from a previous solve's χ grid (same
    resolution; the streaming previewer threads its last preview grid
    through — the solution barely moves between stops, so the residual
    stop fires after far fewer iterations). ``return_iters`` additionally
    returns the iteration count the residual stop settled at — the
    measurable half of the warm-start contract (tests/test_stream.py).
    """
    if depth > 8:
        raise ValueError(
            f"depth={depth} > 8: dense-grid Poisson is capped at 256³ "
            "(the reference similarly guards depth > 16)")
    R = 2 ** depth
    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], dtype=bool)
    warm = x0 is not None
    if warm and x0.shape != (R, R, R):
        raise ValueError(f"x0 shape {x0.shape} does not match the "
                         f"depth-{depth} grid ({R}³)")
    grid, iters = _solve(
        points, normals, valid,
        x0 if warm else jnp.zeros((), jnp.float32),
        R, cg_iters, screen, rtol, warm=warm)
    return (grid, int(iters)) if return_iters else grid
