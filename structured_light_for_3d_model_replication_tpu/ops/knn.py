"""K-nearest-neighbor search on TPU (D-dimensional: 3-D geometry and 33-D
FPFH feature matching share this kernel).

The reference delegates every neighborhood query to Open3D's C++ KDTree
(`server/processing.py:64,87,154` — SOR, normal estimation, ICP
correspondences). KD-trees are pointer-chasing structures that map terribly to
a vector machine, so this module instead computes KNN as dense tiled linear
algebra, which is exactly what the MXU is for:

* pairwise squared distances per (query-tile × key-tile) block via the
  ``|q|² + |p|² − 2 q·pᵀ`` expansion — the ``q·pᵀ`` term is a matmul;
* a running top-k merge over key tiles carried through ``lax.scan``, so HBM
  never holds more than one (Tq × Tk) distance block per step;
* static shapes throughout: inputs are padded, padding is masked with +inf
  distance, k is a compile-time constant.

Exact (not approximate) — same neighbor sets as a KDTree up to distance ties.
O(M·N) FLOPs, but at TPU matmul rates that beats a host KDTree for the point
counts this pipeline sees (≤ a few million after voxel downsampling).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def pad_points(points: jnp.ndarray, valid: jnp.ndarray | None, multiple: int):
    """Pad (N,D) points (+ valid mask) to a multiple; padding is invalid."""
    n, dim = points.shape
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    pad = (-n) % multiple
    if pad:
        points = jnp.concatenate(
            [points, jnp.zeros((pad, dim), points.dtype)], axis=0
        )
        valid = jnp.concatenate([valid, jnp.zeros(pad, dtype=bool)], axis=0)
    return points, valid


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _knn_padded(
    queries: jnp.ndarray,   # (M, D) float32, M % q_tile == 0
    q_valid: jnp.ndarray,   # (M,) bool
    points: jnp.ndarray,    # (N, D) float32, N % k_tile == 0
    p_valid: jnp.ndarray,   # (N,) bool
    k: int,
    q_tile: int,
    k_tile: int,
):
    M, dim = queries.shape
    N = points.shape[0]
    n_k_blocks = N // k_tile
    key_blocks = points.reshape(n_k_blocks, k_tile, dim)
    key_valid = p_valid.reshape(n_k_blocks, k_tile)
    base_idx = jnp.arange(n_k_blocks, dtype=jnp.int32) * k_tile

    p2_blocks = jnp.sum(key_blocks * key_blocks, axis=-1)  # (B, Tk)

    def per_query_tile(args):
        q, qv = args  # (Tq, D), (Tq,)
        q2 = jnp.sum(q * q, axis=-1, keepdims=True)  # (Tq, 1)

        def step(carry, blk):
            best_d, best_i = carry  # (Tq, k)
            kp, kv, p2, base = blk
            # HIGHEST: fp32 dot products — bf16 would misorder close
            # neighbors, changing neighbor SETS, not just distances.
            cross = jax.lax.dot_general(
                q, kp.T, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
            )  # (Tq, Tk)
            d = q2 + p2[None, :] - 2.0 * cross
            d = jnp.where(kv[None, :], d, jnp.inf)
            idx = base + jnp.arange(k_tile, dtype=jnp.int32)
            cat_d = jnp.concatenate([best_d, d], axis=1)
            cat_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(idx[None, :], d.shape)], axis=1
            )
            neg_top, arg = jax.lax.top_k(-cat_d, k)
            return (-neg_top, jnp.take_along_axis(cat_i, arg, axis=1)), None

        init = (
            jnp.full((q.shape[0], k), jnp.inf, jnp.float32),
            jnp.zeros((q.shape[0], k), jnp.int32),
        )
        (best_d, best_i), _ = jax.lax.scan(
            step, init, (key_blocks, key_valid, p2_blocks, base_idx)
        )
        return best_d, best_i

    q_tiles = queries.reshape(M // q_tile, q_tile, dim)
    qv_tiles = q_valid.reshape(M // q_tile, q_tile)
    # lax.map over query tiles: one (Tq, Tk) block resident at a time.
    best_d, best_i = jax.lax.map(per_query_tile, (q_tiles, qv_tiles))
    best_d = best_d.reshape(M, k)
    best_i = best_i.reshape(M, k)
    # Squared distances can go epsilon-negative in fp32; clamp for sqrt users.
    return jnp.maximum(best_d, 0.0), best_i


def knn(
    points: jnp.ndarray,
    k: int,
    queries: jnp.ndarray | None = None,
    points_valid: jnp.ndarray | None = None,
    queries_valid: jnp.ndarray | None = None,
    exclude_self: bool = False,
    q_tile: int = 1024,
    k_tile: int = 2048,
):
    """k nearest points for each query (defaults: queries = points).

    Returns (sq_dists (M, k), indices (M, k), neighbor_valid (M, k)).
    Invalid/padded points never appear as neighbors; when fewer than k valid
    points exist, surplus slots have neighbor_valid False (dist inf capped to
    0 — check the mask). With ``exclude_self`` the query's own index is
    dropped (the Open3D SOR convention of "k neighbors other than me").
    """
    self_query = queries is None
    if self_query:
        queries, queries_valid = points, points_valid

    kk = k + 1 if (exclude_self and self_query) else k
    n_q = queries.shape[0]

    points = jnp.asarray(points, jnp.float32)
    queries = jnp.asarray(queries, jnp.float32)
    p_pad, pv_pad = pad_points(points, points_valid, k_tile)
    q_pad, qv_pad = pad_points(queries, queries_valid, q_tile)

    d, i = _knn_padded(q_pad, qv_pad, p_pad, pv_pad, kk, q_tile, k_tile)
    d, i = d[:n_q], i[:n_q]

    if exclude_self and self_query:
        # Drop the first column where it is the query itself (it is, whenever
        # the query point is valid — distance 0 sorts first up to ties).
        own = jnp.arange(n_q, dtype=jnp.int32)[:, None]
        is_self = i == own  # (n_q, kk)
        # Shift each row left past the self entry: stable mask-then-top_k.
        keep = ~is_self
        # rank candidates: keep original order among kept entries
        order = jnp.argsort(~keep, axis=1, stable=True)  # kept first
        d = jnp.take_along_axis(d, order, axis=1)[:, :k]
        i = jnp.take_along_axis(i, order, axis=1)[:, :k]

    nb_valid = jnp.isfinite(d) if d.size else jnp.zeros_like(d, bool)
    # A padded/invalid QUERY row is all-invalid too.
    if queries_valid is not None:
        nb_valid = nb_valid & queries_valid[:n_q, None]
    return jnp.where(jnp.isfinite(d), d, 0.0), i, nb_valid
