"""K-nearest-neighbor search on TPU (D-dimensional: 3-D geometry and 33-D
FPFH feature matching share this kernel).

The reference delegates every neighborhood query to Open3D's C++ KDTree
(`server/processing.py:64,87,154` — SOR, normal estimation, ICP
correspondences). KD-trees are pointer-chasing structures that map terribly to
a vector machine, so this module instead computes KNN as dense tiled linear
algebra, which is exactly what the MXU is for:

* pairwise squared distances per (query-tile × key-tile) block via the
  ``|q|² + |p|² − 2 q·pᵀ`` expansion — the ``q·pᵀ`` term is a matmul;
* static shapes throughout: inputs are padded, padding is masked with +inf
  distance, k is a compile-time constant.

The top-k reduction is where TPUs need care — the sort unit is the weak one,
so three paths exist:

* ``k == 1`` — a running argmin carried through the key-block scan. No sort
  at all; ICP correspondences and mutual feature matching live here.
* ``method="approx"`` (default on TPU for k > 1) — per-block
  ``lax.approx_min_k`` (the TPU's PartialReduce hardware op, ~1000× faster
  than ``lax.top_k`` at these shapes), candidates merged across blocks with
  a second ``approx_min_k`` and ordered with one tiny exact ``top_k`` over
  the final k. Recall ≈ 0.95² per query; the downstream consumers (SOR
  statistics, PCA normals, FPFH histograms) are insensitive to a missed
  ~5% of neighbors.
* ``method="exact"`` (default off-TPU, and the oracle for tests) — the
  classic carried exact ``top_k`` merge.

O(M·N) FLOPs either way; at TPU matmul rates this beats a host KDTree for
the point counts this pipeline sees (≤ a few million after downsampling).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.log import get_logger

log = get_logger(__name__)

# Packed single-block approx top-k (see `_knn_padded`): key index bits
# embedded in the distance mantissa — bounds the key count it applies to.
_PACK_BITS = 13


def check_neighbors(neighbors, n: int, width: int) -> None:
    """Validate a precomputed ``(d2, idx, nb_valid)`` sweep against its
    consumer's cloud length and required column count. Undersized or
    mismatched sweeps would silently truncate neighborhoods — fail loudly
    at trace time instead."""
    for a in neighbors:
        shape = tuple(a.shape)
        if len(shape) != 2 or shape[0] != n or shape[1] < width:
            raise ValueError(
                f"precomputed neighbors shape {shape} incompatible with "
                f"cloud n={n}, required width={width}")


def pad_points(points: jnp.ndarray, valid: jnp.ndarray | None, multiple: int):
    """Pad (N,D) points (+ valid mask) to a multiple; padding is invalid."""
    n, dim = points.shape
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    pad = (-n) % multiple
    if pad:
        points = jnp.concatenate(
            [points, jnp.zeros((pad, dim), points.dtype)], axis=0
        )
        valid = jnp.concatenate([valid, jnp.zeros(pad, dtype=bool)], axis=0)
    return points, valid


def _block_dists(q, q2, kp, kv, p2, precision=None):
    """(Tq, Tk) squared distances, invalid keys masked to +inf."""
    cross = jax.lax.dot_general(
        q, kp.T, (((1,), (0,)), ((), ())),
        # HIGHEST default: fp32 dot products — bf16 would misorder close
        # neighbors, changing neighbor SETS, not just distances. Callers
        # that only consume a tolerant k=1 correspondence (ICP) can pass
        # the 3-pass bf16 algorithm: ~fp32 accuracy at half the TPU
        # matmul passes of HIGHEST (which lowers to 6-pass bf16).
        precision=jax.lax.Precision.HIGHEST if precision is None
        else precision,
    )
    d = q2 + p2[None, :] - 2.0 * cross
    return jnp.where(kv[None, :], d, jnp.inf)


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7, 8))
def _knn_padded(
    queries: jnp.ndarray,   # (M, D) float32, M % q_tile == 0
    q_valid: jnp.ndarray,   # (M,) bool
    points: jnp.ndarray,    # (N, D) float32, N % k_tile == 0
    p_valid: jnp.ndarray,   # (N,) bool
    k: int,
    q_tile: int,
    k_tile: int,
    approx: bool,
    fast_dots: bool = False,
):
    # 3-pass bf16 only where the hardware has the fast path; CPU executes
    # plain fp32 anyway (and rejects some presets). getattr fallback (on
    # the class too — it shipped together with the preset): an older
    # jaxlib degrades to HIGHEST instead of raising at trace time in
    # every ICP call.
    _preset = getattr(getattr(jax.lax, "DotAlgorithmPreset", None),
                      "BF16_BF16_F32_X3", None)
    prec = (_preset
            if fast_dots and _preset is not None
            and jax.default_backend() in ("tpu", "axon")
            else None)
    M, dim = queries.shape
    N = points.shape[0]
    n_k_blocks = N // k_tile
    key_blocks = points.reshape(n_k_blocks, k_tile, dim)
    key_valid = p_valid.reshape(n_k_blocks, k_tile)
    base_idx = jnp.arange(n_k_blocks, dtype=jnp.int32) * k_tile

    p2_blocks = jnp.sum(key_blocks * key_blocks, axis=-1)  # (B, Tk)

    def per_query_tile(args):
        q, qv = args  # (Tq, D), (Tq,)
        q2 = jnp.sum(q * q, axis=-1, keepdims=True)  # (Tq, 1)

        if k == 1:
            # Sort-free running argmin.
            def step(carry, blk):
                best_d, best_i = carry  # (Tq,), (Tq,)
                kp, kv, p2, base = blk
                d = _block_dists(q, q2, kp, kv, p2, prec)
                j = jnp.argmin(d, axis=1)
                dmin = jnp.take_along_axis(d, j[:, None], 1)[:, 0]
                better = dmin < best_d
                return (jnp.where(better, dmin, best_d),
                        jnp.where(better, base + j.astype(jnp.int32),
                                  best_i)), None

            init = (jnp.full((q.shape[0],), jnp.inf, jnp.float32),
                    jnp.zeros((q.shape[0],), jnp.int32))
            (bd, bi), _ = jax.lax.scan(
                step, init, (key_blocks, key_valid, p2_blocks, base_idx))
            return bd[:, None], bi[:, None]

        if approx and n_k_blocks == 1 and N <= (1 << _PACK_BITS):
            # Single-block packed path: embed the key index in the low
            # mantissa bits of the (nonnegative) squared distance, so the
            # ENTIRE top-k — PartialReduce candidates + final ordering —
            # runs on ONE operand. The generic path's aggregation sorts
            # (value, index) pairs and reorders carried indices with
            # take_along_axis gathers that XProf measured at ~400 ms per
            # ring sweep (k=100); packing removes every index operand.
            # Cost: distances quantized to ~2⁻¹⁰ relative (the low 13
            # mantissa bits), irrelevant to the approx path's consumers
            # (neighbor sets at recall ≈ 0.95, radius masks).
            kp, kv, p2 = key_blocks[0], key_valid[0], p2_blocks[0]
            # Floor at a small NORMAL float: a denormal packed value (a
            # zero self-distance carrying only index bits) could be
            # flushed to zero by the TPU, dropping the embedded index
            # (same guard as ops/nn_pallas.py).
            d = jnp.maximum(_block_dists(q, q2, kp, kv, p2, prec), 1e-30)
            bits = jax.lax.bitcast_convert_type(d, jnp.int32)
            mask = jnp.int32((1 << _PACK_BITS) - 1)
            iota = jnp.arange(N, dtype=jnp.int32)
            packed = jnp.where(jnp.isfinite(d),
                               (bits & ~mask) | iota[None, :],
                               bits)  # +inf keeps its exact bit pattern
            fd = jax.lax.bitcast_convert_type(packed, jnp.float32)
            # aggregate_to_topk=True: the PartialReduce output is
            # aggregated to exactly k in-op, so the ascending sort runs
            # over k lanes instead of the full candidate width — same
            # result (packed single-operand, so aggregation needs no
            # index plumbing), measured 311 → 227 ms per 24-ring burst
            # at the FPFH shape (N=8192, k=100), indices identical.
            cand, _ = jax.lax.approx_min_k(fd, k, aggregate_to_topk=True)
            top = jnp.sort(cand, axis=-1)  # single-operand sort over k
            tb = jax.lax.bitcast_convert_type(top, jnp.int32)
            return (jax.lax.bitcast_convert_type(tb & ~mask, jnp.float32),
                    tb & mask)

        if approx:
            # Per-block PartialReduce candidates, merged with a second
            # approx pass, ordered with one tiny exact sort over k.
            def step(_, blk):
                kp, kv, p2, base = blk
                d = _block_dists(q, q2, kp, kv, p2, prec)
                nd, nloc = jax.lax.approx_min_k(d, k)
                return None, (nd, base + nloc.astype(jnp.int32))

            _, (cd, ci) = jax.lax.scan(
                step, None, (key_blocks, key_valid, p2_blocks, base_idx))
            # (B, Tq, k) -> (Tq, B*k)
            cd = jnp.moveaxis(cd, 0, 1).reshape(q.shape[0], -1)
            ci = jnp.moveaxis(ci, 0, 1).reshape(q.shape[0], -1)
            md, marg = jax.lax.approx_min_k(cd, k)
            mi = jnp.take_along_axis(ci, marg, axis=1)
            neg, order = jax.lax.top_k(-md, k)  # ascending exact order
            return -neg, jnp.take_along_axis(mi, order, axis=1)

        # Exact: carried top-k merge.
        def step(carry, blk):
            best_d, best_i = carry  # (Tq, k)
            kp, kv, p2, base = blk
            d = _block_dists(q, q2, kp, kv, p2, prec)
            idx = base + jnp.arange(k_tile, dtype=jnp.int32)
            cat_d = jnp.concatenate([best_d, d], axis=1)
            cat_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(idx[None, :], d.shape)], axis=1
            )
            neg_top, arg = jax.lax.top_k(-cat_d, k)
            return (-neg_top, jnp.take_along_axis(cat_i, arg, axis=1)), None

        init = (
            jnp.full((q.shape[0], k), jnp.inf, jnp.float32),
            jnp.zeros((q.shape[0], k), jnp.int32),
        )
        (best_d, best_i), _ = jax.lax.scan(
            step, init, (key_blocks, key_valid, p2_blocks, base_idx)
        )
        return best_d, best_i

    def refined_tile(args):
        # The blocked |q|²+|p|²−2q·p expansion loses ~|q|·|p|·eps to fp32
        # cancellation — a true-zero self-distance comes back as ~1e-2 at
        # coordinate scale 10 (the seed test_knn_matches_kdtree failure).
        # Selection over full tiles must keep the matmul form, but the k
        # SELECTED distances are O(Tq·k·D): recompute them by direct
        # difference (exact to fp32 rounding) and re-sort, so callers see
        # KDTree-grade distances at negligible cost.
        q, _ = args
        best_d, best_i = per_query_tile(args)
        diff = q[:, None, :] - points[best_i]          # (Tq, k, D)
        exact = jnp.sum(diff * diff, axis=-1)
        keep = jnp.isfinite(best_d)                    # inf = no neighbor
        best_d = jnp.where(keep, exact, best_d)
        if best_d.shape[1] > 1:
            order = jnp.argsort(best_d, axis=1, stable=True)
            best_d = jnp.take_along_axis(best_d, order, axis=1)
            best_i = jnp.take_along_axis(best_i, order, axis=1)
        return best_d, best_i

    q_tiles = queries.reshape(M // q_tile, q_tile, dim)
    qv_tiles = q_valid.reshape(M // q_tile, q_tile)
    # lax.map over query tiles: one (Tq, Tk) block resident at a time.
    best_d, best_i = jax.lax.map(refined_tile, (q_tiles, qv_tiles))
    best_d = best_d.reshape(M, -1)
    best_i = best_i.reshape(M, -1)
    # Squared distances can go epsilon-negative in fp32; clamp for sqrt users.
    return jnp.maximum(best_d, 0.0), best_i


@functools.lru_cache(maxsize=1)
def _log_default_method(method: str, backend: str) -> None:
    # Once per process: the auto default silently diverges across platforms
    # (approx recall ≈ 0.9 on accelerators vs exact KDTree semantics on
    # CPU), so record which one every ``method="auto"`` consumer got.
    log.info("knn method='auto' resolves to %r on backend %r "
             "(pass method='exact' at precision-sensitive call sites)",
             method, backend)


def _default_method() -> str:
    # Accelerators (incl. the tunneled-TPU "axon" platform) take the
    # PartialReduce path; CPU keeps the exact oracle default.
    backend = jax.default_backend()
    method = "approx" if backend != "cpu" else "exact"
    _log_default_method(method, backend)
    return method


def knn(
    points: jnp.ndarray,
    k: int,
    queries: jnp.ndarray | None = None,
    points_valid: jnp.ndarray | None = None,
    queries_valid: jnp.ndarray | None = None,
    exclude_self: bool = False,
    q_tile: int = 1024,
    k_tile: int | None = None,
    method: str = "auto",
    fast_dots: bool = False,
):
    """k nearest points for each query (defaults: queries = points).

    Returns (sq_dists (M, k), indices (M, k), neighbor_valid (M, k)),
    distances ascending. Invalid/padded points never appear as neighbors;
    when fewer than k valid points exist, surplus slots have neighbor_valid
    False (dist inf capped to 0 — check the mask). With ``exclude_self`` the
    query's own index is dropped (the Open3D SOR convention of "k neighbors
    other than me"). ``method``: "exact", "approx" (recall ≈ 0.9, TPU
    PartialReduce), or "auto" (approx on accelerators, exact on CPU; k=1 is
    always exact via running argmin).
    """
    if method == "auto":
        method = _default_method()
    if method not in ("exact", "approx"):
        raise ValueError(f"unknown knn method {method!r}")
    self_query = queries is None
    if self_query:
        queries, queries_valid = points, points_valid

    kk = k + 1 if (exclude_self and self_query) else k
    n_q = queries.shape[0]

    points = jnp.asarray(points, jnp.float32)
    queries = jnp.asarray(queries, jnp.float32)
    if k_tile is None:
        # Bigger blocks amortize the per-block reduction; the approx path's
        # PartialReduce handles wide rows cheaply, the exact path's sort
        # does not.
        k_tile = 8192 if (method == "approx" or kk == 1) else 2048
    p_pad, pv_pad = pad_points(points, points_valid, k_tile)
    q_pad, qv_pad = pad_points(queries, queries_valid, q_tile)

    d, i = _knn_padded(q_pad, qv_pad, p_pad, pv_pad, kk, q_tile, k_tile,
                       method == "approx", fast_dots)
    d, i = d[:n_q], i[:n_q]

    if exclude_self and self_query:
        # Drop the first column where it is the query itself (it is, whenever
        # the query point is valid — distance 0 sorts first up to ties).
        own = jnp.arange(n_q, dtype=jnp.int32)[:, None]
        is_self = i == own  # (n_q, kk)
        # Shift each row left past the self entry: stable mask-then-top_k.
        keep = ~is_self
        # rank candidates: keep original order among kept entries
        order = jnp.argsort(~keep, axis=1, stable=True)  # kept first
        d = jnp.take_along_axis(d, order, axis=1)[:, :k]
        i = jnp.take_along_axis(i, order, axis=1)[:, :k]

    nb_valid = jnp.isfinite(d) if d.size else jnp.zeros_like(d, bool)
    # A padded/invalid QUERY row is all-invalid too.
    if queries_valid is not None:
        nb_valid = nb_valid & queries_valid[:n_q, None]
    return jnp.where(jnp.isfinite(d), d, 0.0), i, nb_valid
