"""Device-side sparse iso-surface extraction (vectorized marching tets).

The host extractor (:func:`.marching.extract_sparse`) pulls the full chi +
density brick tensors to host (two (M, 8³) float fields — ~750 MB at the
1M-point depth-10 band over this dev environment's ~20 MB/s tunnel) and
then runs NumPy over the active cells. This module keeps classification,
compaction, edge interpolation AND the whole post-soup tail — global
winding vote, density trim, vertex weld — ON DEVICE, so the only data
that crosses the link is the welded result: unique vertices + faces
(plus four count scalars). Readback is tallied per call in
:data:`LAST_READBACK` and pinned by tests to exactly that set.

Same algorithm as the host path — 6-tet decomposition, identical per-case
edge logic — expressed as three shape-static jitted programs with host
syncs only at the two data-dependent counts:

1. **corner field + classification**: assemble the (M, 9³) per-block
   corner frame from the flat bricks and the face-neighbor table (diagonal
   neighbors by chaining face hops; absent neighbors clamp to the own-brick
   face exactly like the host's ``nb_vals``), then mark cells whose 8
   corners straddle the iso level. The inside/any/all pass optionally runs
   as a fused Pallas kernel (:mod:`.marching_pallas`) on TPU backends.
2. **cell compaction** (static capacity ``K``): prefix-sum compact the
   active cell ids and count the triangles their tet cases will emit.
3. **triangle emission** (static capacity ``T``): prefix-sum compact the
   (cell, tet, slot) triangle slots, interpolate each triangle's three
   edge crossings, and orient every triangle so its normal points from the
   inside (χ > iso) to the outside — a per-(tet, case) static flip table,
   so the soup is globally field-consistent and the outward vote reduces
   to one all-or-nothing flip.
4. **tail** (same static ``T``): the all-or-nothing winding flip (sign
   vote over triangle normals against the soup centroid), the optional
   density-quantile trim, and the vertex weld. The weld keys on the raw
   float32 BIT PATTERNS of the vertex coordinates — valid because the
   edge-ascending canonicalization below makes every shared crossing
   bit-identical, so "same vertex" is exact equality, no rounding grid
   needed: bitcast → lexsort → first-occurrence group ids → scattered
   unique vertices + inverse-mapped faces, degenerate faces dropped,
   exactly the host :func:`.marching.weld` contract.

Capacities are data-dependent, so they are bucketed to powers of two
(bounded recompiles) and sliced to the true counts on device before the
readback (a bucket can hold ~2× the real soup).

Everything stays FLAT per the solver's layout rule (a materialized
(…, 8, 8) trailing shape pads 16× under the TPU (8, 128) tile): the corner
frame is (M, 729), cells are flat 0..511, and all cube geometry moves
through precomputed static index tables.

Parity with the host extractor is pinned by tests/test_marching_jax.py:
identical triangle COUNT (same cells, same cases, same table logic) and
vertex agreement to float32 interpolation precision — i.e. within the
vertex-weld tolerance. One documented divergence: the density used by
``quantile_trim`` is sampled at the triangle's OWN cell voxel (known
without any lookup) where the host rounds the centroid, which can land in
an adjacent voxel; trims within a band of the quantile threshold may
differ by a few triangles.
"""

from __future__ import annotations

import functools

import numpy as _np

import jax
import jax.numpy as jnp

from . import _backend
from .marching import _CORNERS, _TETS
from .poisson_sparse import BS
from ..io.stl import TriangleMesh
from ..utils.log import get_logger

log = get_logger(__name__)

_C9 = BS + 1          # corner frame edge: 8 voxels + the +face plane
_NC = _C9 ** 3        # 729 corner positions per block
_V = BS ** 3          # 512 cells per block

# --- static index tables -------------------------------------------------
#
# Corner frame source maps: corner position (x, y, z) ∈ [0, 8]³ reads from
# the neighbor selected by which coordinates hit 8 (the +face), at the
# wrapped voxel, falling back to the own-brick clamp voxel when that
# neighbor is absent — the exact contract of the host extractor's
# ``nb_vals`` (clamped equal values produce no crossings).

_NB_ORDER = {(0, 0, 0): 0, (1, 0, 0): 1, (0, 1, 0): 2, (0, 0, 1): 3,
             (1, 1, 0): 4, (1, 0, 1): 5, (0, 1, 1): 6, (1, 1, 1): 7}


def _corner_maps():
    x, y, z = _np.meshgrid(_np.arange(_C9), _np.arange(_C9),
                           _np.arange(_C9), indexing="ij")
    x, y, z = x.reshape(-1), y.reshape(-1), z.reshape(-1)
    case = _np.array([_NB_ORDER[(int(a == BS), int(b == BS),
                                 int(c == BS))]
                      for a, b, c in zip(x, y, z)], _np.int32)
    src = ((x % BS) * BS + (y % BS)) * BS + (z % BS)
    clamp = ((_np.minimum(x, BS - 1) * BS + _np.minimum(y, BS - 1)) * BS
             + _np.minimum(z, BS - 1))
    return case, src.astype(_np.int32), clamp.astype(_np.int32)


_CASE9, _SRC9, _CLAMP9 = _corner_maps()

# Cell corner gather: cell c ∈ [0, 512) at (cx, cy, cz), corner j ∈ [0, 8)
# reads frame position ((cx+dx)·9 + (cy+dy))·9 + (cz+dz).
_CIDX = _np.zeros((_V, 8), _np.int32)
for _c in range(_V):
    _cx, _cy, _cz = _c // (BS * BS), (_c // BS) % BS, _c % BS
    for _j, (_dx, _dy, _dz) in enumerate(_CORNERS):
        _CIDX[_c, _j] = ((_cx + _dx) * _C9 + (_cy + _dy)) * _C9 \
            + (_cz + _dz)
# Cell → its own (x, y, z) voxel coords, for world positioning.
_CELL_XYZ = _np.stack([_np.arange(_V) // (BS * BS),
                       (_np.arange(_V) // BS) % BS,
                       _np.arange(_V) % BS], axis=1).astype(_np.int32)


def _tet_tables():
    """Replicate the host's per-case logic (``marching._tet_triangles``)
    as static tables: for each 4-bit inside mask, up to two triangles,
    each vertex an ORDERED (src, dst) tet-corner pair for the edge
    interpolation ``p_src + t·(p_dst − p_src)`` — the same operand order
    as the host, so the arithmetic matches term for term."""
    ntri = _np.zeros(16, _np.int32)
    ep = _np.zeros((16, 2, 3, 2), _np.int32)
    for case in range(16):
        ins = [(case >> i) & 1 for i in range(4)]
        k = sum(ins)
        tris = []
        if k in (1, 3):
            want = 1 if k == 1 else 0
            lone = next(i for i in range(4) if ins[i] == want)
            others = [b for b in range(4) if b != lone]
            tris.append([(lone, others[0]), (lone, others[1]),
                         (lone, others[2])])
        elif k == 2:
            a, b = [i for i in range(4) if ins[i]]
            c, d = [i for i in range(4) if not ins[i]]
            tris.append([(a, c), (a, d), (b, d)])
            tris.append([(a, c), (b, d), (b, c)])
        ntri[case] = len(tris)
        for j, t in enumerate(tris):
            ep[case, j] = t
    return ntri, ep


_NTRI, _EP = _tet_tables()

# Per-(tet, case, slot) data in CUBE-corner ids plus the winding flip that
# makes every triangle's normal point from inside (χ > iso) to outside —
# i.e. along −∇χ, the same field-side consistency the host's per-triangle
# gradient vote enforces; only the global outward/inward decision remains
# for the host.
_EP_CUBE = _np.zeros((6, 16, 2, 3, 2), _np.int32)
_FLIP = _np.zeros((6, 16, 2), bool)
for _t in range(6):
    _P4 = _CORNERS[_TETS[_t]].astype(_np.float64)
    for _case in range(16):
        _ins = _np.array([(_case >> _i) & 1 for _i in range(4)], bool)
        if not (0 < _ins.sum() < 4):
            continue
        _V4 = _np.where(_ins, 1.0, 0.0)
        _in_cen = _P4[_ins].mean(axis=0)
        _out_cen = _P4[~_ins].mean(axis=0)
        for _j in range(_NTRI[_case]):
            _verts = []
            for _a, _b in _EP[_case, _j]:
                _tt = (0.5 - _V4[_a]) / (_V4[_b] - _V4[_a])
                _verts.append(_P4[_a] + _tt * (_P4[_b] - _P4[_a]))
            _n = _np.cross(_verts[1] - _verts[0], _verts[2] - _verts[0])
            _FLIP[_t, _case, _j] = float(
                _np.dot(_n, _out_cen - _in_cen)) < 0.0
            for _v in range(3):
                _EP_CUBE[_t, _case, _j, _v] = _TETS[_t][_EP[_case, _j, _v]]
# Canonicalize every edge to ascending CUBE-corner order. The crossing
# ``p_a + t·(p_b − p_a)``, t = (iso − v_a)/(v_b − v_a) is the same point
# from either end mathematically but NOT bit-identically in float32 (ulp
# ~6e-5 at depth-10 grid coords ≫ the weld's 1e-6 rounding grid), and
# the per-case tables above inherit the host's mixed operand orders
# (k==3 interpolates outside→inside where k==1 does inside→outside) —
# without this, tets meeting at a shared cube edge emit bit-different
# copies of the same vertex and the weld leaves seam duplicates. One
# consistent end per edge makes shared crossings bit-identical; triangle
# vertex ORDER (winding) is untouched — only how each position is
# computed. The host oracle keeps its f64 mixed-order form, where the
# ~1e-13 discrepancy vanishes under the weld grid.
_EP_CUBE = _np.where((_EP_CUBE[..., 0] > _EP_CUBE[..., 1])[..., None],
                     _EP_CUBE[..., ::-1], _EP_CUBE)


def _bucket(n: int, floor: int = 4096) -> int:
    """Static-capacity bucket: next power of two ≥ max(n, floor), so the
    data-dependent counts reuse a handful of compiled programs."""
    return max(floor, 1 << (max(n, 1) - 1).bit_length())


#: Per-call device→host transfer tally: cleared at the top of
#: :func:`extract_sparse_jax`, one entry per named readback with its
#: byte count. tests/test_marching_jax.py asserts the keys are exactly
#: {"counts", "vertices", "faces"} and that vertices/faces carry
#: ``nv·12`` / ``nf·12`` bytes — i.e. the welded result and nothing
#: field- or soup-sized ever crosses the link.
LAST_READBACK: dict[str, int] = {}


def _pull(name: str, arr) -> _np.ndarray:
    """Materialize ``arr`` on host and tally the bytes under ``name``."""
    out = _np.asarray(arr)
    LAST_READBACK[name] = LAST_READBACK.get(name, 0) + out.nbytes
    return out


def _nb8_table(nbr):
    """(M, 6) face-neighbor slots → (M, 8) [own, +x, +y, +z, +xy, +xz,
    +yz, +xyz]. Diagonals chain two/three face hops and take the min over
    the hop orders (absent = M sorts last, so any reachable path wins).

    A diagonal that is IN the band but unreachable by face hops falls
    back to the own-face clamp, which the host extractor (a direct
    diagonal lookup) would not. That divergence cannot reach a REAL
    crossing cell: a sign change within one voxel of a block corner
    implies a sample within the interpolation+screen support of that
    corner, i.e. in one of the corner-adjacent blocks — and that
    block's 27-dilation puts every block of the corner neighborhood,
    including both two-hop intermediates, in the band. Only
    sample-free phantom crossings (band-edge specks at starvation
    density, e.g. the depth-16 envelope smoke) can see the clamp, and
    those carry no parity contract."""
    m = nbr.shape[0]
    nbp = jnp.concatenate(
        [nbr, jnp.full((1, 6), m, nbr.dtype)]).astype(jnp.int32)
    own = jnp.arange(m, dtype=jnp.int32)
    px, py, pz = nbr[:, 0], nbr[:, 2], nbr[:, 4]
    pxy = jnp.minimum(nbp[px, 2], nbp[py, 0])
    pxz = jnp.minimum(nbp[px, 4], nbp[pz, 0])
    pyz = jnp.minimum(nbp[py, 4], nbp[pz, 2])
    pxyz = jnp.minimum(jnp.minimum(nbp[pxy, 4], nbp[pxz, 2]),
                       nbp[pyz, 0])
    return jnp.stack([own, px, py, pz, pxy, pxz, pyz, pxyz], axis=1)


@functools.partial(jax.jit, static_argnames=("use_pallas",))
def _phase_corners(chi, nbr, block_valid, iso, use_pallas: bool = False):
    """Corner frame (M, 729) + active-cell mask (M, 512) + count."""
    m = chi.shape[0]
    nb8 = _nb8_table(nbr)
    rows = nb8[:, jnp.asarray(_CASE9, jnp.int32)]          # (M, 729)
    chi_pad = jnp.concatenate([chi, jnp.zeros((1, _V), chi.dtype)])
    vals = chi_pad[rows, jnp.asarray(_SRC9, jnp.int32)[None, :]]
    clamp = chi[:, jnp.asarray(_CLAMP9, jnp.int32)]
    c9 = jnp.where(rows < m, vals, clamp)

    if use_pallas:
        from . import marching_pallas

        any_f, all_f = marching_pallas.classify_pallas(c9 - iso)
        cid = jnp.asarray(_CIDX[:, 0], jnp.int32)
        active = ((any_f[:, cid] > 0.5) & (all_f[:, cid] < 0.5)
                  & block_valid[:, None])
    else:
        inside = c9 > iso
        any_in = all_in = None
        for j in range(8):
            blk = inside[:, jnp.asarray(_CIDX[:, j], jnp.int32)]
            any_in = blk if any_in is None else (any_in | blk)
            all_in = blk if all_in is None else (all_in & blk)
        active = any_in & ~all_in & block_valid[:, None]
    return c9, active, jnp.sum(active.astype(jnp.int32))


def _cell_cases(c9, cell_ids, iso):
    """Compacted cell ids → (corner values (K, 8), case (K, 6))."""
    ok = cell_ids >= 0
    bk = jnp.where(ok, cell_ids // _V, 0)
    ck = jnp.where(ok, cell_ids % _V, 0)
    v8 = c9[bk[:, None], jnp.asarray(_CIDX, jnp.int32)[ck]]   # (K, 8)
    vt = v8[:, jnp.asarray(_TETS, jnp.int32)]                 # (K, 6, 4)
    inside = (vt > iso) & ok[:, None, None]
    bits = jnp.asarray([1, 2, 4, 8], jnp.int32)
    case = jnp.sum(inside.astype(jnp.int32) * bits, axis=-1)  # (K, 6)
    return bk, ck, v8, case


@functools.partial(jax.jit, static_argnames=("K",))
def _phase_cells(active, K: int):
    """Prefix-sum compact active cells into ``K`` static slots (-1 pad)."""
    af = active.reshape(-1)
    rank = jnp.cumsum(af.astype(jnp.int32)) - 1
    dest = jnp.where(af, jnp.minimum(rank, K), K)
    return jnp.full((K + 1,), -1, jnp.int32).at[dest].set(
        jnp.arange(af.shape[0], dtype=jnp.int32),
        mode="drop")[:K]


@functools.partial(jax.jit, static_argnames=("K",))
def _phase_count(c9, cell_ids, iso, K: int):
    """(triangle count, (bk, ck, v8, case)) — the classified cells stay
    on device so _phase_triangles reuses them instead of re-running the
    (K, 8) corner gather and tet classification."""
    bk, ck, v8, case = _cell_cases(c9, cell_ids, iso)
    return (jnp.sum(jnp.asarray(_NTRI, jnp.int32)[case]),
            (bk, ck, v8, case))


@functools.partial(jax.jit, static_argnames=("T",))
def _phase_triangles(cells, density, block_coords, iso, T: int):
    """Compact the triangle slots and emit the oriented soup.

    ``cells`` is _phase_count's device-resident (bk, ck, v8, case).
    Returns (tris (T, 3, 3) float32 grid coords, density (T,)). Slots
    past the true count hold garbage and are sliced off on device
    before readback.
    """
    bk, ck, v8, case = cells
    nt = jnp.asarray(_NTRI, jnp.int32)[case]                  # (K, 6)
    tv = (jnp.arange(2, dtype=jnp.int32)[None, None, :]
          < nt[:, :, None]).reshape(-1)                       # (K·12,)
    rank = jnp.cumsum(tv.astype(jnp.int32)) - 1
    dest = jnp.where(tv, jnp.minimum(rank, T), T)
    src = jnp.zeros((T + 1,), jnp.int32).at[dest].set(
        jnp.arange(tv.shape[0], dtype=jnp.int32), mode="drop")[:T]

    k = src // 12
    t = (src % 12) // 2
    j = src % 2
    caseT = case[k, t]                                        # (T,)
    epc = jnp.asarray(_EP_CUBE, jnp.int32)[t, caseT, j]       # (T, 3, 2)
    v8k = v8[k]                                               # (T, 8)
    va = jnp.take_along_axis(v8k, epc[:, :, 0], axis=1)       # (T, 3)
    vb = jnp.take_along_axis(v8k, epc[:, :, 1], axis=1)
    base = (block_coords[bk[k]] * BS
            + jnp.asarray(_CELL_XYZ, jnp.int32)[ck[k]])       # (T, 3)
    corners = jnp.asarray(_CORNERS, jnp.int32)
    pa = (base[:, None, :] + corners[epc[:, :, 0]]).astype(jnp.float32)
    pb = (base[:, None, :] + corners[epc[:, :, 1]]).astype(jnp.float32)
    denom = vb - va
    safe = jnp.abs(denom) > 1e-12
    tt = jnp.where(safe, (iso - va) / jnp.where(safe, denom, 1.0), 0.5)
    tt = jnp.clip(tt, 0.0, 1.0).astype(jnp.float32)
    tris = pa + tt[..., None] * (pb - pa)                     # (T, 3, 3)
    flip = jnp.asarray(_FLIP, jnp.bool_)[t, caseT, j]
    tris = jnp.where(flip[:, None, None], tris[:, ::-1, :], tris)
    dens = density[bk[k], ck[k]]
    return tris, dens


@functools.partial(jax.jit, static_argnames=("do_trim",),
                   donate_argnums=(0, 1),
                   in_shardings=None, out_shardings=None)
def _phase_tail(tris, dens, n, trim, do_trim: bool):
    """Winding vote + optional quantile trim + vertex weld, on device.

    ``tris`` is _phase_triangles' bucketed (T, 3, 3) soup with ``n`` real
    rows (slots ≥ n hold garbage and are masked throughout). Returns
    ``(uverts (3T, 3) float32, faces (T, 3) int32, counts (2,) int32)``
    with ``counts = [nv, nf]``; the caller slices to the true counts on
    device so the readback is the welded result only.

    The weld keys on float32 bit patterns: the ``_EP_CUBE`` ascending-edge
    canonicalization makes every shared crossing bit-identical, so exact
    bit equality IS vertex identity (−0.0 is normalized to +0.0 first).
    Host parity: same vote rule (``Σ sign(vote) ≤ 0`` flips), same
    ``np.quantile`` linear interpolation for the trim threshold, same
    degenerate-face drop as :func:`.marching.weld`.
    """
    T = tris.shape[0]
    valid = jnp.arange(T, dtype=jnp.int32) < n

    # Global outward decision: device winding is already field-consistent
    # (normals along −∇χ), so one sign vote against the soup centroid
    # settles outward-vs-inward for every triangle at once.
    cen = tris.mean(axis=1)
    vf = valid.astype(jnp.float32)
    gcen = (jnp.sum(cen * vf[:, None], axis=0)
            / jnp.maximum(jnp.sum(vf), 1.0))
    nrm = jnp.cross(tris[:, 1] - tris[:, 0], tris[:, 2] - tris[:, 0])
    vote = jnp.sum(nrm * (cen - gcen), axis=-1)
    outward_flip = jnp.sum(jnp.where(valid, jnp.sign(vote), 0.0)) <= 0.0
    tris = jnp.where(outward_flip, tris[:, ::-1, :], tris)

    keep = valid
    if do_trim:
        sd = jnp.sort(jnp.where(valid, dens, jnp.inf))
        pos = trim * (n - 1).astype(jnp.float32)
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, n - 1)
        hi = jnp.minimum(lo + 1, n - 1)
        frac = pos - lo.astype(jnp.float32)
        thresh = sd[lo] * (1.0 - frac) + sd[hi] * frac
        keep = keep & (dens > thresh)

    # Weld: bitcast → lexsort (invalid rows last) → first-occurrence
    # group ids → scatter unique vertices / inverse-map faces.
    vflat = tris.reshape(T * 3, 3) + 0.0           # −0.0 → +0.0
    vkeep = jnp.repeat(keep, 3)
    key = jax.lax.bitcast_convert_type(vflat, jnp.int32)
    order = jnp.lexsort((key[:, 2], key[:, 1], key[:, 0],
                         (~vkeep).astype(jnp.int32)))
    ks = key[order]
    valid_s = vkeep[order]
    newg = jnp.concatenate([jnp.ones((1,), bool),
                            jnp.any(ks[1:] != ks[:-1], axis=1)]) & valid_s
    gid = jnp.cumsum(newg.astype(jnp.int32)) - 1
    nv = jnp.sum(newg.astype(jnp.int32))
    big = T * 3
    uverts = jnp.zeros((big, 3), jnp.float32).at[
        jnp.where(newg, gid, big)].set(vflat[order], mode="drop")
    inv = jnp.zeros((big,), jnp.int32).at[order].set(
        jnp.where(valid_s, gid, 0))
    faces = inv.reshape(T, 3)
    good = (keep & (faces[:, 0] != faces[:, 1])
            & (faces[:, 1] != faces[:, 2])
            & (faces[:, 0] != faces[:, 2]))
    rank = jnp.cumsum(good.astype(jnp.int32)) - 1
    dest = jnp.where(good, jnp.minimum(rank, T), T)
    faces_c = jnp.zeros((T + 1, 3), jnp.int32).at[dest].set(
        faces, mode="drop")[:T]
    nf = jnp.sum(good.astype(jnp.int32))
    return uverts, faces_c, jnp.stack([nv, nf])


def extract_sparse_jax(grid, quantile_trim: float = 0.0,
                       use_pallas: bool | None = None) -> TriangleMesh:
    """SparsePoissonGrid → welded TriangleMesh, extraction on device.

    Drop-in for the host :func:`.marching.extract_sparse` (the NumPy path
    stays the oracle); requires the grid's ``nbr`` table (always present
    on grids from :func:`..ops.poisson_sparse.reconstruct_sparse`).
    ``use_pallas``: None = the fused classify kernel on TPU backends,
    the XLA gather form elsewhere.
    """
    if grid.nbr is None:
        raise ValueError("extract_sparse_jax needs grid.nbr (grids built "
                         "by reconstruct_sparse carry it); use the host "
                         "extractor for hand-assembled grids")
    if use_pallas is None:
        use_pallas = _backend.tpu_backend()
    LAST_READBACK.clear()
    iso = jnp.float32(grid.iso)
    c9, active, count = _phase_corners(grid.chi, grid.nbr,
                                       grid.block_valid, iso,
                                       use_pallas=bool(use_pallas))
    n_cells = int(_pull("counts", count))
    if n_cells == 0:
        return TriangleMesh(_np.zeros((0, 3), _np.float32),
                            _np.zeros((0, 3), _np.int32))
    K = _bucket(n_cells)
    cell_ids = _phase_cells(active, K)
    count_d, cells = _phase_count(c9, cell_ids, iso, K)
    nt = int(_pull("counts", count_d))
    if nt == 0:
        return TriangleMesh(_np.zeros((0, 3), _np.float32),
                            _np.zeros((0, 3), _np.int32))
    tris_d, dens_d = _phase_triangles(
        cells, grid.density, grid.block_coords, iso, _bucket(nt))
    # Winding vote, trim and weld all run on device, so the only arrays
    # that cross the link are the welded vertices and faces (sliced to
    # their true counts ON DEVICE first — the bucketed capacities can
    # hold ~2× the real mesh, and the per-count slice program is a
    # trivially cheap compile next to shipping the slack).
    uverts_d, faces_d, counts_d = _phase_tail(
        tris_d, dens_d, jnp.int32(nt), jnp.float32(quantile_trim),
        do_trim=quantile_trim > 0.0)
    nv, nf = (int(c) for c in _pull("counts", counts_d))
    verts = _pull("vertices", uverts_d[:nv])
    faces = _pull("faces", faces_d[:nf])
    world = verts * float(grid.scale) + _np.asarray(grid.origin,
                                                    _np.float32)
    mesh = TriangleMesh(world.astype(_np.float32), faces)
    if len(mesh.faces):
        mesh.compute_vertex_normals()
    return mesh
