"""Ray-plane triangulation and calibration precompute.

Replaces the reference's triangulation stack (`server/sl_system.py:584-653`)
and its calibration precompute (`:353-403`):

* the reference fits the 1920+1080 projector light planes in a Python loop
  ("hot loop: 3000 plane fits", `sl_system.py:379-403`); here each is one
  vmapped closed-form cross-product — a single kernel.
* the reference gathers valid pixels with `np.where` then triangulates a ragged
  array; here triangulation is dense over all H*W pixels with a validity mask,
  so it jits with static shapes and vectorizes onto the VPU/MXU.
* the reference only ever intersects camera rays with COLUMN planes — row_map
  is decoded then dropped (`sl_system.py:624-629`). That behavior is preserved
  as plane_axis="col", with "row" and "both" (inverse-variance fusion of the
  two independent ray-plane depths) offered as strictly-better options since
  wPlaneRow is already in the calibration container (`sl_system.py:403,410`).

Frames: everything lives in the CAMERA frame. `stereoCalibrate`-convention
extrinsics map camera→projector: X_p = R @ X_c + T. Hence the projector center
in camera coordinates is -Rᵀ T and a projector-pixel ray direction is
Rᵀ K_p⁻¹ [u, v, 1].
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import TriangulationConfig


class Calibration(NamedTuple):
    """Device-resident calibration, mirroring the reference .mat container keys
    {Nc, Oc, wPlaneCol, wPlaneRow, cam_K, proj_K, R, T}
    (`server/sl_system.py:406-415`)."""

    cam_K: jnp.ndarray      # (3,3)
    proj_K: jnp.ndarray     # (3,3)
    R: jnp.ndarray          # (3,3) camera->projector rotation
    T: jnp.ndarray          # (3,)  camera->projector translation
    Nc: jnp.ndarray         # (H, W, 3) unit ray per camera pixel
    Oc: jnp.ndarray         # (3,) camera center (zeros in camera frame)
    plane_cols: jnp.ndarray  # (proj_w, 4) [nx, ny, nz, d], n·X + d = 0
    plane_rows: jnp.ndarray  # (proj_h, 4)


def camera_rays(cam_K: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """Unit viewing ray per camera pixel, (H, W, 3).

    Reference precomputes this grid with meshgrid + K⁻¹ + normalize
    (`server/sl_system.py:353-365`).
    """
    u = jnp.arange(width, dtype=jnp.float32)
    v = jnp.arange(height, dtype=jnp.float32)
    uu, vv = jnp.meshgrid(u, v)  # (H, W)
    pix = jnp.stack([uu, vv, jnp.ones_like(uu)], axis=-1)  # (H, W, 3)
    Kinv = jnp.linalg.inv(cam_K.astype(jnp.float32))
    # HIGHEST: calibration geometry must stay true fp32 even on TPU, where
    # default matmul precision is bf16.
    rays = jnp.einsum("hwj,kj->hwk", pix, Kinv, precision=jax.lax.Precision.HIGHEST)
    return rays / jnp.linalg.norm(rays, axis=-1, keepdims=True)


def projector_center(R: jnp.ndarray, T: jnp.ndarray) -> jnp.ndarray:
    """Projector optical center in camera coordinates: -Rᵀ T."""
    return -(R.T @ T)


@functools.partial(jax.jit, static_argnums=(3, 4))
def projector_planes(
    proj_K: jnp.ndarray,
    R: jnp.ndarray,
    T: jnp.ndarray,
    n: int,
    axis: str,
) -> jnp.ndarray:
    """Light-plane equations for every projector column (axis="col") or row
    (axis="row"), shape (n, 4) with plane n·X + d = 0 in camera coordinates.

    Each projector column u sweeps a plane through the projector center and
    the back-projections of (u, 0) and (u, 1); vmapped closed form replacing
    the reference's per-plane Python loop (`server/sl_system.py:379-403`).
    """
    proj_K = proj_K.astype(jnp.float32)
    R = R.astype(jnp.float32)
    T = T.astype(jnp.float32)
    Kinv = jnp.linalg.inv(proj_K)
    center = -(R.T @ T)  # (3,)

    idx = jnp.arange(n, dtype=jnp.float32)
    if axis == "col":
        p0 = jnp.stack([idx, jnp.zeros_like(idx), jnp.ones_like(idx)], axis=-1)
        edge = Kinv[:, 1]  # exact direction along a column: K⁻¹ e_v
    elif axis == "row":
        p0 = jnp.stack([jnp.zeros_like(idx), idx, jnp.ones_like(idx)], axis=-1)
        edge = Kinv[:, 0]  # exact direction along a row: K⁻¹ e_u
    else:
        raise ValueError(f"axis must be 'col' or 'row', got {axis!r}")

    # Projector-pixel ray directions in camera coords: Rᵀ K⁻¹ p.
    # normal = cross(ray(p0), ray(p0+edge)) = cross(ray(p0), edge_cam): forming
    # the cross with the exact edge vector avoids the fp32 cancellation of
    # crossing two nearly-parallel rays one pixel apart.
    hi = jax.lax.Precision.HIGHEST  # keep true fp32 on TPU (default is bf16)
    d0 = jnp.einsum(
        "nj,kj,km->nm", p0, Kinv, R, precision=hi
    )  # (n,3): Rᵀ K⁻¹ p0 per column
    edge_cam = jnp.einsum("km,k->m", R, edge, precision=hi)
    normal = jnp.cross(d0, edge_cam[None, :])
    normal = normal / jnp.linalg.norm(normal, axis=-1, keepdims=True)
    d = -jnp.sum(normal * center[None, :], axis=-1)  # plane through proj center
    return jnp.concatenate([normal, d[:, None]], axis=-1)


def make_calibration(
    cam_K,
    proj_K,
    R,
    T,
    cam_height: int,
    cam_width: int,
    proj_width: int = 1920,
    proj_height: int = 1080,
) -> Calibration:
    """Precompute the full device-resident calibration container."""
    cam_K = jnp.asarray(cam_K, jnp.float32)
    proj_K = jnp.asarray(proj_K, jnp.float32)
    R = jnp.asarray(R, jnp.float32)
    T = jnp.asarray(T, jnp.float32).reshape(3)
    return Calibration(
        cam_K=cam_K,
        proj_K=proj_K,
        R=R,
        T=T,
        Nc=camera_rays(cam_K, cam_height, cam_width),
        Oc=jnp.zeros(3, jnp.float32),
        plane_cols=projector_planes(proj_K, R, T, proj_width, "col"),
        plane_rows=projector_planes(proj_K, R, T, proj_height, "row"),
    )


def _ray_plane_t(planes_t, rays_t, origin, eps):
    """t for origin + t*ray hitting plane n·X + d = 0; invalid → nan-safe 0.

    SoA layouts — ``planes_t`` (4, N), ``rays_t`` (3, N) — keep the pixel
    axis on the TPU's 128-lane dimension. The AoS (N, 4) form tiles 4 of
    128 lanes (32× padded traffic), and its gathered table + component
    slices were ~170 ms of the fused 360 decode (XProf fusion.1189 /
    slice.2515)."""
    n = planes_t[:3]
    denom = jnp.sum(n * rays_t, axis=0)
    num = -(jnp.sum(n * origin[:, None], axis=0) + planes_t[3])
    safe = jnp.abs(denom) > eps
    t = jnp.where(safe, num / jnp.where(safe, denom, 1.0), 0.0)
    return t, safe


@functools.partial(jax.jit, static_argnames=("cfg",))
def triangulate(
    col_map: jnp.ndarray,
    row_map: jnp.ndarray,
    mask: jnp.ndarray,
    calib: Calibration,
    cfg: TriangulationConfig = TriangulationConfig(),
):
    """Dense masked triangulation.

    Inputs are (H, W) decode maps + mask; output is ((H*W, 3) float32 points,
    (H*W,) bool valid). Every pixel is computed; `valid` marks real points.
    Reproduces `t = -(N·Oc + d)/(N·ray)` with the |denom|>1e-6 guard
    (`server/sl_system.py:638-648`).
    """
    H, W = col_map.shape
    rays = calib.Nc.reshape(-1, 3)
    rays_t = rays.T                                  # (3, N) SoA
    origin = calib.Oc
    flat_mask = mask.reshape(-1)

    n_cols = calib.plane_cols.shape[0]
    n_rows = calib.plane_rows.shape[0]
    col_idx = jnp.clip(col_map.reshape(-1), 0, n_cols - 1)
    row_idx = jnp.clip(row_map.reshape(-1), 0, n_rows - 1)

    if cfg.plane_axis == "col":
        planes_t = jnp.take(calib.plane_cols.T, col_idx, axis=1)
        t, safe = _ray_plane_t(planes_t, rays_t, origin, cfg.denom_eps)
    elif cfg.plane_axis == "row":
        planes_t = jnp.take(calib.plane_rows.T, row_idx, axis=1)
        t, safe = _ray_plane_t(planes_t, rays_t, origin, cfg.denom_eps)
    elif cfg.plane_axis == "both":
        # Inverse-variance fusion of the two independent depth estimates. The
        # decode error is ~uniform in plane INDEX (±half a projector pixel),
        # so each axis's variance is its depth sensitivity to a one-index
        # step, measured by finite difference against the adjacent plane.
        # With a horizontal baseline the row planes are nearly depth-blind
        # (huge dt/dindex) and automatically get ~zero weight.
        def est(planes_all, idx, n_planes):
            pt = planes_all.T
            p = jnp.take(pt, idx, axis=1)
            # Forward difference, falling back to backward at the last plane
            # (a clipped forward diff would measure zero sensitivity there and
            # grab near-infinite fusion weight).
            nbr = jnp.where(idx + 1 < n_planes, idx + 1, idx - 1)
            p_nbr = jnp.take(pt, nbr, axis=1)
            t0, s0 = _ray_plane_t(p, rays_t, origin, cfg.denom_eps)
            t1, _ = _ray_plane_t(p_nbr, rays_t, origin, cfg.denom_eps)
            sens = jnp.abs(t1 - t0) + 1e-12
            return t0, s0, 1.0 / (sens * sens)

        tc, sc, wc = est(calib.plane_cols, col_idx, n_cols)
        tr, sr, wr = est(calib.plane_rows, row_idx, n_rows)
        wc = wc * sc
        wr = wr * sr
        wsum = wc + wr
        safe = (sc | sr) & (wsum > 0.0)
        t = jnp.where(safe, (wc * tc + wr * tr) / jnp.where(safe, wsum, 1.0), 0.0)
    else:
        raise ValueError(f"unknown plane_axis {cfg.plane_axis!r}")

    valid = flat_mask & safe & (t > cfg.min_t) & (t < cfg.max_t)
    points = origin[None, :] + t[:, None] * rays
    points = jnp.where(valid[:, None], points, 0.0).astype(jnp.float32)
    return points, valid


def colors_from_white(white: jnp.ndarray) -> jnp.ndarray:
    """Per-point colors from the white reference frame, (H*W, 3) uint8.

    The reference samples the white texture and swizzles BGR→RGB at PLY-write
    time (`server/sl_system.py:646-651,689-691`); here images are RGB already.
    Grayscale input is broadcast to 3 channels.
    """
    if white.ndim == 2:
        white = jnp.repeat(white[..., None], 3, axis=-1)
    return white.reshape(-1, 3).astype(jnp.uint8)
