"""Point-cloud cleanup kernels: the Open3D C++ replacements, TPU-first.

Covers the reference call sites (`server/processing.py`):
* ``voxel_down_sample`` (`:83,171`)           → :func:`voxel_downsample`
* ``remove_statistical_outlier`` (`:64,174`)  → :func:`statistical_outlier_removal`
* ``remove_radius_outlier``
  (`Old/StatisticalOutlierRemoval.py:86`)     → :func:`radius_outlier_removal`
* ``estimate_normals`` (`:87,178,199,265`)    → :func:`estimate_normals`
* ``orient_normals_towards_camera_location`` / radial-outward negate
  (`:273-276,287-289`)                        → :func:`orient_normals`

Design rules (everything jit/vmap/shard-friendly):
* **Static shapes.** Clouds are dense (N, 3) arrays + a validity mask; ops
  never gather to ragged arrays. "Removing" a point means clearing its mask
  bit. Voxel downsampling emits N output slots with a mask instead of a
  data-dependent count.
* **Neighborhoods are tiled matmuls** (ops/knn.py), not KD-trees.
* **Eigenvectors are closed-form.** Per-point normals need the smallest
  eigenvector of a 3×3 covariance; that is an analytic trigonometric solve
  (vmapped, branch-free), not a LAPACK call.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import _backend
from .brickknn import brick_knn
from .gridknn import grid_knn
from .knn import check_neighbors, knn
from .mortonknn import morton_knn

# Above this many points, self-query neighborhoods route to the
# Morton-blocked engine (O(N·3B), gather-free) instead of the dense tiled
# matmul (O(N²)) — at 1M points that is ~0.8 s vs tens of seconds.
APPROX_KNN_THRESHOLD = 131_072


def _self_knn(points, k, valid, exclude_self, method="auto"):
    """Self-query KNN dispatch.

    ``dense``  — exact tiled matmul (ops/knn.py), O(N²);
    ``morton`` — Morton-blocked approximate (ops/mortonknn.py), the
                 large-N default: gather-free, ~0.97+ kth-distance accuracy;
    ``rescue`` — brick-grid engine (ops/brickknn.py): recall ≥ 0.99 at
                 morton-like cost (dense per-cell bricks, no random
                 gathers) for precision-sensitive large-N consumers;
    ``grid``   — 27-cell spatial grid (ops/gridknn.py), higher recall than
                 morton but random-gather-bound on TPU.
    """
    n = points.shape[0]
    if method == "auto":
        if n < APPROX_KNN_THRESHOLD:
            method = "dense"
        else:
            # With the Mosaic brick kernel (ops/brickknn_pallas.py) the
            # high-recall engine costs ~1.2× Morton at 1M/k=20 (was
            # 4.9× in XLA), so recall ≥ 0.99 is the large-N default on
            # TPU when the kernel's k/n caps hold; elsewhere Morton
            # (~0.93) remains the cheap default.  The kernel module is
            # imported only behind the backend gate — off-TPU this path
            # must not depend on pallas importability.
            if _backend.tpu_backend():
                from . import brickknn_pallas as _bkp

                method = ("rescue" if k <= _bkp.MAX_K and n <= _bkp.MAX_N
                          else "morton")
            else:
                method = "morton"
    if method == "morton":
        return morton_knn(points, k, points_valid=valid,
                          exclude_self=exclude_self)
    if method == "rescue":
        return brick_knn(points, k, points_valid=valid,
                         exclude_self=exclude_self)
    if method == "grid":
        return grid_knn(points, k, points_valid=valid,
                        exclude_self=exclude_self)
    return knn(points, k, points_valid=valid, exclude_self=exclude_self)


# ---------------------------------------------------------------------------
# Voxel downsample
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("with_attrs",))
def voxel_downsample(
    points: jnp.ndarray,
    voxel_size,
    valid: jnp.ndarray | None = None,
    attrs: jnp.ndarray | None = None,
    with_attrs: bool = False,
):
    """Average points (and optional per-point attributes) per voxel cell.

    Returns ``(out_points (N,3), out_attrs, out_valid (N,), n_cells)`` — one
    output slot per input point, the first ``n_cells`` slots holding one cell
    centroid each (cells in lexicographic cell order), the rest masked off.
    Matches Open3D ``voxel_down_sample`` semantics (mean of members).
    """
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    pts = jnp.asarray(points, jnp.float32)

    cell = jnp.floor(pts / voxel_size).astype(jnp.int32)
    # Invalid points get an out-of-band cell so they sort last, together.
    big = jnp.int32(2**30)
    cell = jnp.where(valid[:, None], cell, big)

    order = jnp.lexsort((cell[:, 2], cell[:, 1], cell[:, 0]))
    cs = cell[order]
    vs = valid[order]
    ps = pts[order]

    new_cell = jnp.any(cs != jnp.roll(cs, 1, axis=0), axis=1)
    new_cell = new_cell.at[0].set(True)
    group = jnp.cumsum(new_cell.astype(jnp.int32)) - 1  # (N,) in [0, n_groups)

    ones = vs.astype(jnp.float32)
    counts = jax.ops.segment_sum(ones, group, num_segments=n)
    sums = jax.ops.segment_sum(ps * ones[:, None], group, num_segments=n)
    denom = jnp.maximum(counts, 1.0)[:, None]
    out_points = sums / denom

    # A group is valid iff it contains valid points (the out-of-band group
    # contributes zero count).
    out_valid = counts > 0
    n_cells = jnp.sum(out_valid.astype(jnp.int32))

    out_attrs = None
    if with_attrs:
        a = jnp.asarray(attrs, jnp.float32)
        asums = jax.ops.segment_sum(a[order] * ones[:, None], group,
                                    num_segments=n)
        out_attrs = asums / denom
    return out_points, out_attrs, out_valid, n_cells


# ---------------------------------------------------------------------------
# Outlier removal
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("nb_neighbors", "neighbor_method"))
def statistical_outlier_removal(
    points: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    nb_neighbors: int = 20,
    std_ratio: float = 2.0,
    neighbor_method: str = "auto",
):
    """Open3D ``remove_statistical_outlier`` semantics
    (`server/processing.py:64`: nb=20, ratio=2.0): per point, mean distance
    to its nb nearest OTHER points; drop points whose mean exceeds
    global_mean + std_ratio · global_std. Returns the surviving mask.

    Points with NO valid neighbors are undecidable and fail conservative:
    they are excluded from the μ/σ statistics and removed. The approximate
    large-N engines can produce such rows (brick slot/budget overflow,
    `ops/brickknn.py`); giving them mean_d = 0 would instead make dropped
    points unconditionally survive outlier removal. Exception: when EVERY
    valid point is undecidable (e.g. a single-point cloud, where Open3D
    keeps the point) there are no statistics at all to fail against, so
    the whole valid set is kept rather than wiped."""
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    d2, _, nbv = _self_knn(points, nb_neighbors, valid, True,
                           neighbor_method)
    d = jnp.sqrt(d2)
    cnt = jnp.sum(nbv, axis=1)
    decidable = valid & (cnt > 0)
    mean_d = jnp.sum(jnp.where(nbv, d, 0.0), axis=1) / jnp.maximum(cnt, 1)

    vf = decidable.astype(jnp.float32)
    nv = jnp.maximum(jnp.sum(vf), 1.0)
    mu = jnp.sum(mean_d * vf) / nv
    var = jnp.sum((mean_d - mu) ** 2 * vf) / nv
    thresh = mu + std_ratio * jnp.sqrt(var)
    return jnp.where(jnp.any(decidable),
                     decidable & (mean_d <= thresh), valid)


@functools.partial(jax.jit, static_argnames=("min_neighbors",
                                             "neighbor_method"))
def radius_outlier_removal(
    points: jnp.ndarray,
    radius: float,
    min_neighbors: int = 5,
    valid: jnp.ndarray | None = None,
    neighbor_method: str = "auto",
):
    """Open3D ``remove_radius_outlier`` semantics
    (`Old/StatisticalOutlierRemoval.py:86`: nb=5, r=15): keep points with at
    least min_neighbors OTHER points within radius. Returns surviving mask.
    """
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    # Having ≥ m neighbors within r  ⇔  the m-th nearest (excl. self) is ≤ r.
    d2, _, nbv = _self_knn(points, min_neighbors, valid, True,
                           neighbor_method)
    kth_ok = nbv[:, -1] & (d2[:, -1] <= radius * radius)
    return valid & kth_ok


# ---------------------------------------------------------------------------
# Fixed-size subsampling (static-shape compaction)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("m",))
def random_subsample(
    points: jnp.ndarray,
    m: int,
    valid: jnp.ndarray | None = None,
    attrs: jnp.ndarray | None = None,
    key=None,
):
    """Uniform random subset of the VALID points, compacted to a static size.

    Returns ``(out_points (m,3), out_attrs (m,...) or None, out_valid (m,))``.
    When fewer than ``m`` valid points exist, every valid point is kept and
    the surplus slots are masked off. This is the static-shape bridge between
    the dense per-pixel pipeline (H·W slots, most invalid) and the cloud ops
    (registration wants a few thousand well-spread points): Open3D gets the
    same effect from ``voxel_down_sample`` before ICP
    (`server/processing.py:83`); a random subset is the shape-static analogue.
    """
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    if key is None:
        key = jax.random.PRNGKey(0)
    # Valid points get a random positive score, invalid -inf: top_k picks a
    # uniform random m-subset of the valid set, never a padded slot (unless
    # fewer than m valid points exist — then out_valid masks the surplus).
    score = jnp.where(valid, jax.random.uniform(key, (n,)), -jnp.inf)
    _, idx = jax.lax.top_k(score, m)
    out_valid = valid[idx]
    out_points = jnp.where(out_valid[:, None], points[idx], 0.0)
    out_attrs = None
    if attrs is not None:
        taken = attrs[idx]
        mask = out_valid.reshape((m,) + (1,) * (taken.ndim - 1))
        out_attrs = jnp.where(mask, taken, 0)
    return out_points, out_attrs, out_valid


def _tiered_rank_search(rank: jnp.ndarray, targets: jnp.ndarray):
    """``searchsorted(rank, targets, side='left')`` for a NONDECREASING
    int table, as three blocked compare-count levels.

    The plain binary search does log₂(n) ≈ 21 rounds of element gathers
    per query — this backend's pathological access class — and measured
    165 ms per 24-view ring at the subsample shape (393k queries over
    2M-row cumsums). Here each level counts ``block_max < t`` over a
    ≤B-wide row (vectorized compare-sum), and the two lower levels fetch
    their row by WHOLE-ROW gather (the fast class). Because the table is
    nondecreasing, "block max < t" ⟺ "entire block < t", so the three
    counts add up to exactly #(rank < t) — the 'left' insertion point."""
    n = rank.shape[0]
    # Smallest multiple-of-8 block edge with b³ ≥ n. An explicit guard
    # rather than a float cube root: `int(round(n ** (1/3) + 0.5))` sits
    # one float-rounding away from undershooting on large exact cubes,
    # and an undershot b makes `pad` negative → silent truncation of the
    # rank table. Compile-time only (n is a static shape).
    b = 8
    while b ** 3 < n:
        b += 8
    big = jnp.iinfo(rank.dtype).max
    pad = b ** 3 - n
    rp = jnp.concatenate([rank, jnp.full((pad,), big, rank.dtype)]) \
        if pad else rank
    t = targets[:, None]
    m1 = rp.reshape(b, b * b)[:, -1]                     # (B,)
    b1 = jnp.sum((m1[None, :] < t), axis=1).astype(jnp.int32)
    m2 = rp.reshape(b * b, b)[:, -1].reshape(b, b)       # (B, B)
    b2 = jnp.sum(m2[jnp.minimum(b1, b - 1)] < t, axis=1).astype(jnp.int32)
    mid = jnp.minimum(b1 * b + b2, b * b - 1)
    w3 = rp.reshape(b * b, b)[mid]                       # (q, B) row gather
    b3 = jnp.sum(w3 < t, axis=1).astype(jnp.int32)
    return mid * b + b3


@functools.partial(jax.jit, static_argnames=("m",))
def stratified_indices(valid: jnp.ndarray, m: int):
    """Row indices + validity of the stratified subsample — the selection
    half of :func:`stratified_subsample`, exposed so pipelines with
    several consumers of the SAME subsample (registration view, merge
    reduce) pay for the cumsum + binary search once and gather many
    times."""
    n = valid.shape[0]
    rank = jnp.cumsum(valid.astype(jnp.int32))  # 1-based rank of each valid
    n_valid = rank[-1]
    j = jnp.arange(m, dtype=jnp.int32)
    # Target ranks: stratified when n_valid > m, identity (+mask) otherwise.
    # Computed as j·(n_valid/m) — NOT (j·n_valid)/m, whose product overflows
    # fp32 grid at 4K-camera sizes — then repaired to be strictly
    # increasing: in exact math t_j − j is nondecreasing, so a running max
    # over it undoes any ±1 fp32 floor misround that would duplicate a rank.
    stride = n_valid.astype(jnp.float32) / float(m)
    t = jnp.floor(j.astype(jnp.float32) * stride).astype(jnp.int32) + 1
    u = jax.lax.associative_scan(jnp.maximum, t - j)
    t = jnp.minimum(u + j, jnp.maximum(n_valid, 1))
    targets = jnp.where(n_valid > m, t, j + 1)
    # Lookup geometry (m ≪ n: 16k queries over a 2M-row cumsum): an
    # n-row rank→index table lost in r4 (371 vs 221 ms — scatter-bound),
    # and plain searchsorted's log₂(n) element-gather rounds were still
    # 165 ms of the r5 ring profile; the tiered blocked search replaces
    # them with three compare-counts + two whole-row gathers (measured
    # 165 → ~25 ms per ring, bit-identical indices). Sort-merge remains
    # the answer only for queries ≫ table (ops/poisson_sparse.py).
    if n >= (1 << 18):
        idx = _tiered_rank_search(rank, targets)
    else:
        idx = jnp.searchsorted(rank, targets, side="left").astype(
            jnp.int32)
    idx = jnp.minimum(idx, n - 1)
    out_valid = j < jnp.minimum(n_valid, m)
    return idx, out_valid


@functools.partial(jax.jit, static_argnames=("m",))
def stratified_subsample(
    points: jnp.ndarray,
    m: int,
    valid: jnp.ndarray | None = None,
    attrs: jnp.ndarray | None = None,
):
    """Every ⌈n_valid/m⌉-th valid point, compacted to ``m`` static slots.

    The deterministic sibling of :func:`random_subsample`: instead of a
    top_k over random scores (whose sorting-network cost explodes for large
    ``m`` on TPU), ranks come from a cumsum over the valid mask and the j-th
    output is the ⌊j·n_valid/m⌋-th valid point, found by binary search —
    O(n + m·log n). Selection is stratified along the input order, which for
    voxel-downsample outputs (cells emitted in lexicographic order) means
    spatially spread, and for image-order pixel clouds means spread over
    rows. When fewer than ``m`` valid points exist every valid point is
    kept once (surplus slots masked), like random_subsample.
    """
    if valid is None:
        valid = jnp.ones(points.shape[0], dtype=bool)
    idx, out_valid = stratified_indices(valid, m)
    out_points = jnp.where(out_valid[:, None], points[idx], 0.0)
    out_attrs = None
    if attrs is not None:
        taken = attrs[idx]
        mask = out_valid.reshape((m,) + (1,) * (taken.ndim - 1))
        out_attrs = jnp.where(mask, taken, 0)
    return out_points, out_attrs, out_valid


# ---------------------------------------------------------------------------
# Normals: analytic 3×3 symmetric eigensolver (branch-free, vmapped)
# ---------------------------------------------------------------------------


def smallest_eigenvector_sym3(A: jnp.ndarray):
    """Unit eigenvector of the smallest eigenvalue of symmetric (..., 3, 3).

    Trigonometric eigenvalue solve (no iteration, no LAPACK), then the
    eigenvector as the strongest column of (A − λ₁I)(A − λ₂I), whose columns
    all lie in the λ₃ (smallest) eigenspace by Cayley–Hamilton. Degenerate
    (isotropic) inputs fall back to ẑ.
    """
    A = A.astype(jnp.float32)
    q = jnp.trace(A, axis1=-2, axis2=-1) / 3.0
    I = jnp.eye(3, dtype=A.dtype)
    B = A - q[..., None, None] * I
    p2 = jnp.sum(B * B, axis=(-2, -1)) / 6.0
    p = jnp.sqrt(jnp.maximum(p2, 0.0))
    safe_p = jnp.where(p > 1e-20, p, 1.0)
    r = jnp.linalg.det(B / safe_p[..., None, None]) / 2.0
    r = jnp.clip(r, -1.0, 1.0)
    phi = jnp.arccos(r) / 3.0
    lam1 = q + 2.0 * p * jnp.cos(phi)                       # largest
    lam3 = q + 2.0 * p * jnp.cos(phi + 2.0 * jnp.pi / 3.0)  # smallest
    lam2 = 3.0 * q - lam1 - lam3

    M = (A - lam1[..., None, None] * I) @ (A - lam2[..., None, None] * I)
    norms = jnp.linalg.norm(M, axis=-2)  # column norms (..., 3)
    best = jnp.argmax(norms, axis=-1)
    v = jnp.take_along_axis(
        M, best[..., None, None].repeat(3, axis=-2), axis=-1
    )[..., 0]
    vn = jnp.linalg.norm(v, axis=-1, keepdims=True)
    degenerate = (vn[..., 0] < 1e-20) | (p < 1e-20)
    fallback = jnp.broadcast_to(jnp.array([0.0, 0.0, 1.0], A.dtype), v.shape)
    v = jnp.where(degenerate[..., None], fallback,
                  v / jnp.where(vn > 1e-20, vn, 1.0))
    return v


@functools.partial(jax.jit, static_argnames=("k", "neighbor_method",))
def estimate_normals(
    points: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    k: int = 30,
    neighbor_method: str = "auto",
    neighbors=None,
):
    """Per-point unit normals from the k-NN covariance (PCA), the standard
    Open3D ``estimate_normals`` method (`server/processing.py:87,178`) —
    here one batched gather + einsum + analytic eigensolve.

    Returns (normals (N,3), normal_valid (N,)). Sign is arbitrary; use
    :func:`orient_normals`. ``neighbors`` optionally supplies a
    precomputed ``(d2, idx, nb_valid)`` self-query KNN (ascending, ≥ k
    columns, self included) so pipelines that need several neighborhood
    ops on the same cloud (see `models/merge._preprocess`) pay for ONE
    KNN sweep.
    """
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    pts = jnp.asarray(points, jnp.float32)
    if neighbors is not None:
        check_neighbors(neighbors, n, k)
        _, idx, nbv = (a[:, :k] for a in neighbors)
        # The sweep may have been built under a wider validity mask (the
        # shared-KNN pattern in merge._preprocess) — re-mask so invalid
        # points never skew the covariance.
        nbv = nbv & valid[idx]
    else:
        _, idx, nbv = _self_knn(pts, k, valid, False, neighbor_method)
    nbr = pts[idx]  # (N, k, 3)
    w = nbv.astype(jnp.float32)[..., None]  # (N, k, 1)
    cnt = jnp.maximum(jnp.sum(w, axis=1), 1.0)  # (N, 1)
    mu = jnp.sum(nbr * w, axis=1) / cnt
    xc = (nbr - mu[:, None, :]) * w
    # Batched 3×3 covariances: one einsum, MXU-friendly. (A 6-unique-
    # entry elementwise variant — the sor_normals trick — measured SLOWER
    # here, 233 vs 180 ms per 24-ring: the (N,k,6) gather-expand costs
    # more than the tiny-matmul einsum.)
    C = jnp.einsum("nki,nkj->nij", xc, xc,
                   precision=jax.lax.Precision.HIGHEST) / cnt[..., None]
    normals = smallest_eigenvector_sym3(C)
    # Need ≥3 neighbors for a plane fit.
    nvalid = valid & (jnp.sum(nbv, axis=1) >= 3)
    return normals, nvalid


@jax.jit
def orient_normals(
    points: jnp.ndarray,
    normals: jnp.ndarray,
    location: jnp.ndarray,
    outward: bool = False,
):
    """Flip normals to point toward ``location`` (camera convention,
    `server/processing.py:273`) or away from it (``outward=True`` — the
    reference's radial trick of orienting at the cloud center then negating,
    `server/processing.py:274-276`)."""
    to_loc = location[None, :] - points
    dots = jnp.sum(normals * to_loc, axis=-1, keepdims=True)
    flip = jnp.logical_xor(dots < 0.0, outward)
    return jnp.where(flip, -normals, normals)
