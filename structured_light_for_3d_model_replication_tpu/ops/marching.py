"""Iso-surface extraction: vectorized marching tetrahedra (host oracle).

Companion to :mod:`.poisson` — turns the device-computed implicit grid into a
triangle mesh. Extraction output size is data-dependent (anathema to XLA's
static shapes), so this stage historically ran on host as **vectorized NumPy
over the active cells only**: the device hands back a dense (R,R,R) field,
the host finds sign-change cells with one comparison pass, and all triangle
math is batched array ops — no Python per-cell loop. The band-sparse
variant now also has a DEVICE path (:mod:`.marching_jax`, prefix-sum
compaction to bounded static capacities) selected via
``extract_sparse(engine=...)``; this module's NumPy form stays the oracle
every device result is pinned against (tests/test_marching_jax.py).

Marching *tetrahedra* (6 tets per cube) instead of classic marching cubes:
no 256-case tables to get wrong, no ambiguous cases, and the per-tet logic
(16 cases collapse to "1 inside → 1 triangle, 2 inside → 2 triangles")
vectorizes cleanly. Winding is made globally consistent afterwards by voting
triangle normals against the field gradient, so the STL is printable.

Replaces the extraction half of Open3D's `create_from_point_cloud_poisson`
(`server/processing.py:212,293`); the density-quantile trim mirrors
`server/processing.py:214-218,297-302`.
"""

from __future__ import annotations

import numpy as np

from ..io.stl import TriangleMesh

# Cube corner offsets, index = bit order used below.
_CORNERS = np.array(
    [[0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0],
     [0, 0, 1], [1, 0, 1], [1, 1, 1], [0, 1, 1]], dtype=np.int64)

# Standard 6-tetrahedron decomposition of the cube around diagonal 0-6.
_TETS = np.array(
    [[0, 5, 1, 6], [0, 1, 2, 6], [0, 2, 3, 6],
     [0, 3, 7, 6], [0, 7, 4, 6], [0, 4, 5, 6]], dtype=np.int64)


def _interp(p_a, v_a, p_b, v_b, iso):
    """Linear iso crossing on edge a→b. Inputs (M,3)/(M,) arrays."""
    denom = v_b - v_a
    t = np.where(np.abs(denom) > 1e-12, (iso - v_a) / np.where(
        np.abs(denom) > 1e-12, denom, 1.0), 0.5)
    t = np.clip(t, 0.0, 1.0)[:, None]
    return p_a + t * (p_b - p_a)


def _tet_triangles(P, V, iso):
    """Triangles from a batch of tets. P: (M,4,3) corner positions,
    V: (M,4) values. Returns (T,3,3) triangle soup (grid coords)."""
    inside = V > iso                      # (M, 4)
    k = inside.sum(axis=1)
    tris = []

    # --- one vertex on its own side (k==1 lone inside, k==3 lone outside) ---
    for lone_inside in (True, False):
        sel = (k == 1) if lone_inside else (k == 3)
        if not sel.any():
            continue
        Ps, Vs, ins = P[sel], V[sel], inside[sel]
        lone = np.argmax(ins if lone_inside else ~ins, axis=1)     # (m,)
        m = Ps.shape[0]
        rows = np.arange(m)
        others = np.array([[b for b in range(4) if b != a] for a in range(4)],
                          dtype=np.int64)[lone]                    # (m, 3)
        pa, va = Ps[rows, lone], Vs[rows, lone]
        q = [_interp(pa, va, Ps[rows, others[:, j]],
                     Vs[rows, others[:, j]], iso) for j in range(3)]
        tris.append(np.stack([q[0], q[1], q[2]], axis=1))

    # --- two/two split: quad → two triangles ---
    sel = k == 2
    if sel.any():
        Ps, Vs, ins = P[sel], V[sel], inside[sel]
        m = Ps.shape[0]
        rows = np.arange(m)
        order = np.argsort(~ins, axis=1, kind="stable")  # inside first
        a, b = order[:, 0], order[:, 1]   # inside pair
        c, d = order[:, 2], order[:, 3]   # outside pair
        pac = _interp(Ps[rows, a], Vs[rows, a], Ps[rows, c], Vs[rows, c], iso)
        pad = _interp(Ps[rows, a], Vs[rows, a], Ps[rows, d], Vs[rows, d], iso)
        pbc = _interp(Ps[rows, b], Vs[rows, b], Ps[rows, c], Vs[rows, c], iso)
        pbd = _interp(Ps[rows, b], Vs[rows, b], Ps[rows, d], Vs[rows, d], iso)
        tris.append(np.stack([pac, pad, pbd], axis=1))
        tris.append(np.stack([pac, pbd, pbc], axis=1))

    if not tris:
        return np.zeros((0, 3, 3), np.float64)
    return np.concatenate(tris, axis=0)


def extract_triangles(chi: np.ndarray, iso: float):
    """Marching tetrahedra over the active cells of a (R,R,R) field.

    Returns a (T,3,3) float64 triangle soup in grid coordinates.
    """
    chi = np.asarray(chi, np.float64)
    R = chi.shape[0]
    inside = chi > iso
    # A cell is active iff its 8 corners are not all on one side.
    c = inside[:-1, :-1, :-1]
    all_in = c.copy()
    any_in = c.copy()
    for dx, dy, dz in _CORNERS[1:]:
        blk = inside[dx:R - 1 + dx, dy:R - 1 + dy, dz:R - 1 + dz]
        all_in &= blk
        any_in |= blk
    active = np.argwhere(any_in & ~all_in)                # (A, 3)
    if active.shape[0] == 0:
        return np.zeros((0, 3, 3), np.float64)

    corner_idx = active[:, None, :] + _CORNERS[None]      # (A, 8, 3)
    vals = chi[corner_idx[..., 0], corner_idx[..., 1], corner_idx[..., 2]]
    pos = corner_idx.astype(np.float64)                   # grid coords

    P = pos[:, _TETS, :].reshape(-1, 4, 3)                # (A*6, 4, 3)
    V = vals[:, _TETS].reshape(-1, 4)
    return _tet_triangles(P, V, iso)


def orient_triangles(tris: np.ndarray, chi: np.ndarray,
                     outward_high: bool | None = None) -> np.ndarray:
    """Make winding globally consistent (and outward) by checking each
    triangle's normal against the field gradient at its centroid."""
    if tris.shape[0] == 0:
        return tris
    cen = tris.mean(axis=1)
    R = chi.shape[0]
    ic = np.clip(np.round(cen).astype(np.int64), 1, R - 2)
    grad = np.stack([
        chi[ic[:, 0] + 1, ic[:, 1], ic[:, 2]] - chi[ic[:, 0] - 1, ic[:, 1], ic[:, 2]],
        chi[ic[:, 0], ic[:, 1] + 1, ic[:, 2]] - chi[ic[:, 0], ic[:, 1] - 1, ic[:, 2]],
        chi[ic[:, 0], ic[:, 1], ic[:, 2] + 1] - chi[ic[:, 0], ic[:, 1], ic[:, 2] - 1],
    ], axis=1)
    n = np.cross(tris[:, 1] - tris[:, 0], tris[:, 2] - tris[:, 0])
    agree = np.einsum("ij,ij->i", n, grad)
    if outward_high is None:
        # Global vote: scanned objects are star-ish around their centroid, so
        # outward ≈ away from the soup centroid. Decide which gradient sign
        # that corresponds to by majority.
        out_dir = cen - cen.mean(axis=0)
        vote = np.einsum("ij,ij->i", n, out_dir)
        flip_field = np.sum(np.sign(agree) * np.sign(vote)) < 0
    else:
        flip_field = not outward_high
    want_positive = not flip_field
    flip = (agree < 0) if want_positive else (agree > 0)
    tris = tris.copy()
    tris[flip] = tris[flip][:, ::-1, :]
    return tris


def weld(tris: np.ndarray, decimals: int = 6):
    """Triangle soup → indexed (vertices, faces) by exact-rounded merging."""
    flat = tris.reshape(-1, 3)
    key = np.round(flat, decimals)
    uniq, inv = np.unique(key, axis=0, return_inverse=True)
    faces = inv.reshape(-1, 3).astype(np.int32)
    # Drop degenerate faces produced by welding.
    good = ((faces[:, 0] != faces[:, 1]) & (faces[:, 1] != faces[:, 2])
            & (faces[:, 0] != faces[:, 2]))
    return uniq.astype(np.float32), faces[good]


class _SparseSampler:
    """Vectorized global-voxel → value lookup over (M,8,8,8) bricks."""

    def __init__(self, bricks: np.ndarray, coords: np.ndarray,
                 fill: float):
        bs = bricks.shape[1]
        self.bs = bs
        self.bricks = bricks
        self.fill = fill
        key = (coords[:, 0].astype(np.int64) << 42) \
            | (coords[:, 1].astype(np.int64) << 21) | coords[:, 2]
        self.order = np.argsort(key)
        self.sorted_keys = key[self.order]

    def block_index(self, bc: np.ndarray) -> np.ndarray:
        """(..., 3) block coords → brick row (−1 when absent)."""
        key = (bc[..., 0].astype(np.int64) << 42) \
            | (bc[..., 1].astype(np.int64) << 21) | bc[..., 2]
        pos = np.searchsorted(self.sorted_keys, key)
        pos_c = np.minimum(pos, len(self.sorted_keys) - 1)
        found = self.sorted_keys[pos_c] == key
        return np.where(found, self.order[pos_c], -1)

    def __call__(self, vox: np.ndarray) -> np.ndarray:
        """(..., 3) int global voxel coords → field values (fill outside)."""
        bc = vox >> 3 if self.bs == 8 else vox // self.bs
        intra = vox - bc * self.bs
        idx = self.block_index(bc)
        safe = np.maximum(idx, 0)
        vals = self.bricks[safe, intra[..., 0], intra[..., 1],
                           intra[..., 2]]
        return np.where(idx >= 0, vals, self.fill)


def extract_sparse(grid, quantile_trim: float = 0.0,
                   engine: str = "auto") -> TriangleMesh:
    """SparsePoissonGrid → welded TriangleMesh in world coordinates.

    The band-sparse sibling of :func:`extract`: marches only the active
    blocks of :func:`..ops.poisson_sparse.reconstruct_sparse` (at depth 10
    that is ~1% of the virtual 1024³ grid). Cross-block cells read their
    +1 corner values from the neighboring brick; at the outer band edge
    corners clamp to the block face (equal-value cells produce no
    crossings — the band is dilated a full block past the samples, so the
    surface cannot reach it).

    ``engine`` selects the extractor: ``"host"`` — this module's NumPy
    path (the oracle); ``"device"`` — the jitted on-device path
    (:func:`..ops.marching_jax.extract_sparse_jax`, needs ``grid.nbr``);
    ``"auto"`` — device on TPU backends when the grid carries its
    neighbor table, host otherwise (CPU stays on the oracle: the XLA
    gather form has no advantage there and NumPy is the reference).
    """
    if engine not in ("auto", "host", "device"):
        raise ValueError(f"unknown extraction engine {engine!r}")
    if engine != "host":
        from . import _backend
        if engine == "device" or (grid.nbr is not None
                                  and _backend.tpu_backend()):
            from . import marching_jax

            return marching_jax.extract_sparse_jax(
                grid, quantile_trim=quantile_trim)
    valid = np.asarray(grid.block_valid)
    # Brick fields arrive FLAT (M, BS³) — the TPU-tiling-friendly layout
    # (see SparsePoissonGrid) — and get their 3-D shape back on host.
    bs_side = round(grid.chi.shape[-1] ** (1.0 / 3.0))
    chi = np.asarray(grid.chi, np.float64)[valid].reshape(
        -1, bs_side, bs_side, bs_side)
    density = np.asarray(grid.density, np.float64)[valid].reshape(
        -1, bs_side, bs_side, bs_side)
    coords = np.asarray(grid.block_coords)[valid]
    iso = float(grid.iso)
    mv = chi.shape[0]
    if mv == 0:
        return TriangleMesh(np.zeros((0, 3), np.float32),
                            np.zeros((0, 3), np.int32))
    bs = chi.shape[1]

    samp_chi = _SparseSampler(chi, coords, fill=iso)
    samp_den = _SparseSampler(density, coords, fill=0.0)

    # (Mv, 9, 9, 9) corner field: brick + 7 neighbor fills.
    C = np.empty((mv, bs + 1, bs + 1, bs + 1), np.float64)
    C[:, :bs, :bs, :bs] = chi

    def nb_vals(offset, face):
        """Values of the neighbor brick at ``offset`` on our ``face``
        slice, clamp-filled when absent."""
        idx = samp_chi.block_index(coords + np.asarray(offset))
        safe = np.maximum(idx, 0)
        vals = chi[safe][tuple([slice(None)] + face)]
        here_face = [slice(None)] + [
            (bs - 1 if o == 1 else slice(None)) for o in offset]
        clamp = chi[tuple(here_face)]
        m = (idx >= 0).reshape((-1,) + (1,) * (vals.ndim - 1))
        return np.where(m, vals, clamp)

    C[:, bs, :bs, :bs] = nb_vals((1, 0, 0), [0, slice(None), slice(None)])
    C[:, :bs, bs, :bs] = nb_vals((0, 1, 0), [slice(None), 0, slice(None)])
    C[:, :bs, :bs, bs] = nb_vals((0, 0, 1), [slice(None), slice(None), 0])
    C[:, bs, bs, :bs] = nb_vals((1, 1, 0), [0, 0, slice(None)])
    C[:, bs, :bs, bs] = nb_vals((1, 0, 1), [0, slice(None), 0])
    C[:, :bs, bs, bs] = nb_vals((0, 1, 1), [slice(None), 0, 0])
    C[:, bs, bs, bs] = nb_vals((1, 1, 1), [0, 0, 0])

    inside = C > iso
    cell0 = inside[:, :bs, :bs, :bs]
    all_in = cell0.copy()
    any_in = cell0.copy()
    for dx, dy, dz in _CORNERS[1:]:
        blk = inside[:, dx:bs + dx, dy:bs + dy, dz:bs + dz]
        all_in &= blk
        any_in |= blk
    active = np.argwhere(any_in & ~all_in)               # (A, 4) b,x,y,z
    if active.shape[0] == 0:
        return TriangleMesh(np.zeros((0, 3), np.float32),
                            np.zeros((0, 3), np.int32))

    b = active[:, 0]
    cell = active[:, 1:]
    corner_local = cell[:, None, :] + _CORNERS[None]     # (A, 8, 3)
    vals = C[b[:, None], corner_local[..., 0], corner_local[..., 1],
             corner_local[..., 2]]
    pos = (coords[b][:, None, :] * bs + corner_local).astype(np.float64)

    P = pos[:, _TETS, :].reshape(-1, 4, 3)
    V = vals[:, _TETS].reshape(-1, 4)
    tris = _tet_triangles(P, V, iso)
    if tris.shape[0] == 0:
        return TriangleMesh(np.zeros((0, 3), np.float32),
                            np.zeros((0, 3), np.int32))

    # Orientation: field gradient at each centroid via the sparse sampler.
    cen = tris.mean(axis=1)
    ic = np.round(cen).astype(np.int64)
    R = grid.resolution
    ic = np.clip(ic, 1, R - 2)
    ex = np.array([1, 0, 0])
    ey = np.array([0, 1, 0])
    ez = np.array([0, 0, 1])
    grad = np.stack([samp_chi(ic + ex) - samp_chi(ic - ex),
                     samp_chi(ic + ey) - samp_chi(ic - ey),
                     samp_chi(ic + ez) - samp_chi(ic - ez)], axis=1)
    nrm = np.cross(tris[:, 1] - tris[:, 0], tris[:, 2] - tris[:, 0])
    agree = np.einsum("ij,ij->i", nrm, grad)
    out_dir = cen - cen.mean(axis=0)
    vote = np.einsum("ij,ij->i", nrm, out_dir)
    want_positive = np.sum(np.sign(agree) * np.sign(vote)) >= 0
    flip = (agree < 0) if want_positive else (agree > 0)
    tris[flip] = tris[flip][:, ::-1, :]

    if quantile_trim > 0.0 and tris.shape[0]:
        d = samp_den(np.clip(np.round(tris.mean(axis=1)).astype(np.int64),
                             0, R - 1))
        keep = d > np.quantile(d, quantile_trim)
        tris = tris[keep]

    verts, faces = weld(tris)
    world = verts * float(grid.scale) + np.asarray(grid.origin, np.float32)
    mesh = TriangleMesh(world.astype(np.float32), faces)
    if len(mesh.faces):
        mesh.compute_vertex_normals()
    return mesh


def extract(grid, quantile_trim: float = 0.0) -> TriangleMesh:
    """PoissonGrid → welded TriangleMesh in world coordinates.

    ``quantile_trim`` q drops triangles whose splat density falls in the
    bottom q quantile — the reference's density trim
    (`server/processing.py:214-218,297-302`); q=0 keeps the mesh watertight
    (the GUI default, `server/gui.py:65`).
    """
    chi = np.asarray(grid.chi, np.float64)
    density = np.asarray(grid.density, np.float64)
    iso = float(grid.iso)
    tris = extract_triangles(chi, iso)
    tris = orient_triangles(tris, chi)
    if quantile_trim > 0.0 and tris.shape[0]:
        cen = np.clip(np.round(tris.mean(axis=1)).astype(np.int64), 0,
                      chi.shape[0] - 1)
        d = density[cen[:, 0], cen[:, 1], cen[:, 2]]
        keep = d > np.quantile(d, quantile_trim)
        tris = tris[keep]
    verts, faces = weld(tris)
    world = verts * float(grid.scale) + np.asarray(grid.origin, np.float32)
    mesh = TriangleMesh(world.astype(np.float32), faces)
    if len(mesh.faces):
        mesh.compute_vertex_normals()
    return mesh
