"""Block-sparse screened-Poisson solve — depth 9-16, band-bounded memory.

The dense solver (:mod:`.poisson`) is the right shape for TPU up to 256³
(depth 8), but the reference's octree path runs at depth 10 by default and
accepts up to 16 (`server/processing.py:207-208,293`); a dense 1024³ grid
is 4 GB per field and CG needs ~7 fields. This module recovers the
octree's adaptivity with a TPU-idiomatic structure: a **two-level scheme**
over a dense coarse grid plus a **block-sparse fine band**.

1. **Coarse solve**: the existing dense screened-Poisson at
   ``min(depth, coarse_depth)`` — gives the global interior/exterior
   field far from the surface (exactly the role of an octree's shallow
   nodes).
2. **Active band**: the set of 8³ voxel blocks within one block of any
   sample, found with one sort-unique over 27-dilated block keys — static
   capacity ``max_blocks``, padded, shape-stable.
3. **Fine solve**: splat, divergence and screened-Laplacian CG run ONLY
   on the band, stored as ``(M, 8, 8, 8)`` brick tensors. Cross-block
   stencil halos come from a precomputed (M, 6) neighbor table; at the
   band boundary the halo is a **Dirichlet condition prolonged from the
   coarse solution** (folded into the RHS once, so the CG operator is
   halo-free). The coarse solution also seeds ``x0``, so the fine CG only
   refines the band.
4. Iso level and density trimming gather from the sparse bricks; marching
   extraction (:func:`.marching.extract_sparse`) walks only active
   blocks.

Memory at depth 10 on a 1M-point surface scan: ~10⁵ active blocks →
~50M voxels → ~200 MB per field, an order of magnitude under the dense
grid, with identical numerics inside the band.

Everything is jit-compiled with static ``(resolution, max_blocks,
cg_iters)``; block discovery, splat and halo exchange are sorts, segment
ops and gathers — no pointer chasing.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from . import _backend
from . import poisson as dense_poisson
from ..utils.log import get_logger

log = get_logger(__name__)

BS = 8                       # voxels per block edge
_KEY_BITS = 10               # per-axis bits of the single-int32 pack
_KEY_MAX = (1 << _KEY_BITS) - 1
# Depth 14-16 block coordinates need up to 13 bits per axis — more than a
# single int32 triple-pack holds — so those depths run a (hi, lo) int32
# KEY-PAIR path: hi = x, lo = y<<_WB | z, ordered with lexsort and looked
# up by stable sort-merge rank (searchsorted has no composite-key form).
_WB = 13                     # per-axis bits of the wide pair pack
# Plain Python int (a module-level jnp value would initialize the XLA
# backend at import, breaking jax.distributed for multi-host users).
_BIG = 1 << 30               # sentinel key: sorts after every real block


class SparsePoissonGrid(NamedTuple):
    """Band-sparse solve result; extraction input for ``extract_sparse``.

    Brick fields are stored FLAT as (M, BS³): a materialized (M,8,8,8)
    tensor pads 16× under the TPU's (8,128) tile (the last dim 8 rounds to
    128) — flat bricks tile exactly. 3-D views exist only transiently
    inside the stencil computations."""

    chi: jnp.ndarray           # (M, BS³) float32
    density: jnp.ndarray       # (M, BS³) float32 splat density
    block_coords: jnp.ndarray  # (M, 3) int32 block coords (padded rows big)
    block_valid: jnp.ndarray   # (M,) bool
    iso: jnp.ndarray           # () float32
    origin: jnp.ndarray        # (3,) world position of voxel (0,0,0) center
    scale: jnp.ndarray         # () world size of one fine voxel
    resolution: int            # static: fine voxels per axis
    # Face-neighbor slot table (M, 6), columns +x,-x,+y,-y,+z,-z, value M
    # for "absent" — produced by setup anyway, carried so the DEVICE
    # marching extractor (`ops/marching_jax.py`) can assemble cross-block
    # corner values without re-deriving the block index. Optional (None)
    # so hand-built grids in tests stay constructible.
    nbr: jnp.ndarray | None = None


class PoissonParams(NamedTuple):
    """Hashable knob set for :func:`reconstruct_sparse`.

    ``preconditioner`` selects the fine-band CG preconditioner:

    * ``"additive"`` (default) — additive two-level: scaled Jacobi on the
      band PLUS a band-masked coarse correction on the SAME dense coarse
      grid the solve already uses for its Dirichlet seed, moved through
      the separable restriction/prolongation machinery of
      :func:`_prolong_band`. ZERO fine matvecs per application — the only
      band matvec per outer iteration is CG's own ``A·p`` — so the total
      fine-band traffic is ~iteration-count matvecs: measured 26 vs 65
      Jacobi iterations at the 37.9k-block depth-9 probe shape, ~2.5×
      less band traffic.
    * ``"vcycle"`` — multiplicative two-level V-cycle: damped-Jacobi
      pre/post smoothing wrapped around the same masked coarse
      correction. Few iterations (28 vs 65 at the probe shape) but 2
      extra band matvecs per application (~3 total per iteration) — the
      right choice when outer-loop reductions, not matvecs, dominate.
    * ``"chebyshev"`` — degree-``cheby_degree`` Chebyshev polynomial of
      the Jacobi-scaled band operator; no coarse traffic, linear and
      symmetric. Fewer iterations than Jacobi at the same matvec count —
      useful when the coarse grid is unavailable or mistrusted.
    * ``"jacobi"`` — the original diagonal preconditioner, kept verbatim
      (:func:`_cg_sparse`) as the oracle/fallback path.
    """

    depth: int = 10
    cg_iters: int = 200
    screen: float = 4.0
    max_blocks: int = 131_072
    # None = depth-aware default: 7 (128³), auto-raised so the
    # coarse/fine resolution ratio stays ≤ 128 through depth 15 (capped
    # at 8 = 256³ dense, so depth 16 runs at ratio 256 and WARNS). At
    # ratio 256 (depth 15 over a 128³ coarse grid) the band is ~0.05
    # coarse cells thick and the folded Dirichlet halo inherits the
    # coarse blob's surface error wholesale — the measured p90 =
    # 4.63-voxel error tail of BENCH r5's depth-15 row, gone at ratio
    # 128 (depth 14, p90 0.29, same cloud density).
    coarse_depth: int | None = None
    coarse_iters: int = 300
    rtol: float = 3e-4
    preconditioner: str = "additive"
    # Two-level internals, None = per-scheme measured defaults (resolved
    # in _pcg_sparse). ``smooth_omega`` is scheme-dependent BY ROLE: for
    # "vcycle" it is the damped-Jacobi smoothing weight (must stay < 1;
    # 0.8 measured best), for "additive" it is the diagonal branch's
    # WEIGHT against the coarse correction — the ω/γ balance of the two
    # summed terms, optimum ≥ 2 (37.9k-block sweep: ω=1→35 iters,
    # ω=2→30, plateau 26-28 over ω∈[2,4]). ``precond_coarse_iters`` is
    # the fixed coarse-level PCG count (fixed => deterministic cost; the
    # slight nonlinearity it leaves is absorbed by the flexible CG);
    # additive measured best at 4, vcycle at 8.
    smooth_omega: float | None = None
    precond_coarse_iters: int | None = None
    # Chebyshev internals: polynomial degree and the spectral bounds of
    # the Jacobi-scaled operator (eigenvalues of D⁻¹A lie in (0, 2]).
    cheby_degree: int = 4
    cheby_lmin: float = 0.06
    cheby_lmax: float = 2.0
    # Fine-band RELAXATION dtype under the preconditioned schemes:
    # "bfloat16" runs the preconditioner's band-side elementwise
    # arithmetic (the scaled-Jacobi / smoothing / Chebyshev-recurrence
    # terms) in bf16 while every residual, matvec and dot ACCUMULATES in
    # fp32 — the preconditioner merely becomes a slightly different
    # (still SPD-ish) approximation, which the flexible Polak-Ribière
    # outer loop absorbs, and the fp32 residual stopping rule keeps the
    # converged error envelope (bench [3d]/[3e] gates: median ≤ 0.35
    # vox, p90 < 3 vox vs the fp32 mode). fp32 stays the default; the
    # "jacobi" oracle path has no relaxation stage and rejects the mode.
    fine_dtype: str = "float32"


def _pack(bc: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) block coords → packed int32 key (coords must be in range)."""
    return ((bc[..., 0] << (2 * _KEY_BITS)) | (bc[..., 1] << _KEY_BITS)
            | bc[..., 2])


def _unpack(key: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([key >> (2 * _KEY_BITS),
                      (key >> _KEY_BITS) & _KEY_MAX,
                      key & _KEY_MAX], axis=-1)


def _rank_lookup1(table, q):
    """Single-key (slot, found) lookup by stable sort-merge rank — the
    replacement for per-query ``searchsorted`` binary search, which XProf
    measured at 1.3 s of the 1M-point depth-10 setup (8.4M splat-corner
    queries); the merge is one ~40 ms sort. (This geometry — queries ≫
    table — is where the merge wins; with few queries over a huge sorted
    array searchsorted wins, see ops/pointcloud.py:stratified_indices.)
    Stable argsort orders equal keys by position, so table entries (which
    come first in the concat) precede equal queries and the running
    table-count at a query's sorted position is exactly rank+1 when
    present."""
    m = table.shape[0]
    keys = jnp.concatenate([table, q])
    order = jnp.argsort(keys, stable=True)
    cum = jnp.cumsum((order < m).astype(jnp.int32))
    inv = jnp.zeros((keys.shape[0],), jnp.int32).at[order].set(
        jnp.arange(keys.shape[0], dtype=jnp.int32), unique_indices=True)
    c = cum[inv[m:]]
    slot = jnp.clip(c - 1, 0, m - 1)
    return slot, (c > 0) & (table[slot] == q)


# --- wide (hi, lo) key-pair helpers: the depth-14-16 path ------------------


def _rank_lookup(th, tl, qh, ql):
    """Sorted key-pair table → (slot, found) for flat query pairs, by
    stable sort-merge rank (the composite-key replacement for
    ``searchsorted``; same trick as `ops/brickknn_pallas.py` neighbor
    lookup). Ties order table entries before queries, so the count of
    table entries ≤ query gives rank+1 when present."""
    m = th.shape[0]
    q = qh.shape[0]
    kh = jnp.concatenate([th, qh])
    kl = jnp.concatenate([tl, ql])
    tag = jnp.concatenate([jnp.zeros((m,), jnp.int32),
                           jnp.ones((q,), jnp.int32)])
    order = jnp.lexsort((tag, kl, kh))
    cum = jnp.cumsum((order < m).astype(jnp.int32))
    inv = jnp.zeros((m + q,), jnp.int32).at[order].set(
        jnp.arange(m + q, dtype=jnp.int32), unique_indices=True)
    c = cum[inv[m:]]
    slot = jnp.clip(c - 1, 0, m - 1)
    found = (c > 0) & (th[slot] == qh) & (tl[slot] == ql)
    return slot, found


def _sorted_unique(hi, lo):
    """Ascending sort + first-occurrence mask. ``lo=None`` = narrow
    single-int32 keys (one ``jnp.sort``); otherwise lexicographic (hi, lo)
    pairs. Invalid keys carry hi=_BIG and sort last either way."""
    if lo is None:
        s = jnp.sort(hi)
        return s, None, jnp.concatenate(
            [jnp.ones(1, bool), s[1:] != s[:-1]])
    order = jnp.lexsort((lo, hi))
    h = hi[order]
    l = lo[order]
    first = jnp.concatenate(
        [jnp.ones(1, bool), (h[1:] != h[:-1]) | (l[1:] != l[:-1])])
    return h, l, first


def _scatter_table(hi_s, lo_s, first, max_entries):
    """Compact the sorted-unique keys into a static table of
    ``max_entries`` ascending slots (_BIG-hi padding past the real count).
    Returns (table_hi, table_lo_or_None, n_unique)."""
    new = first & (hi_s < _BIG)
    rank = jnp.cumsum(new.astype(jnp.int32)) - 1
    slot = jnp.where(new & (rank < max_entries), rank, max_entries)
    th = jnp.full((max_entries + 1,), _BIG, jnp.int32).at[slot].set(
        jnp.where(new, hi_s, _BIG))[:max_entries]
    tl = None
    if lo_s is not None:
        tl = jnp.zeros((max_entries + 1,), jnp.int32).at[slot].set(
            jnp.where(new, lo_s, 0))[:max_entries]
    return th, tl, jnp.sum(new.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Flat-space stencils. EVERYTHING stays (M, BS³): on TPU any materialized
# (…, 8, 8) / (…, 10, 10) trailing shape pads to the (8, 128) tile — 13-16×
# memory blowup, the OOM that killed the first three layouts of this solver.
# In flat index space (idx = (ix·8 + iy)·8 + iz) the 7-point stencil is six
# rolls (±1, ±8, ±64) under boundary masks, and cross-brick faces are
# static-index gathers from the neighbor brick's flat row.
# ---------------------------------------------------------------------------

import numpy as _np

_FLAT_IDX = _np.arange(BS ** 3)
_FIZ = _FLAT_IDX % BS
_FIY = (_FLAT_IDX // BS) % BS
_FIX = _FLAT_IDX // (BS * BS)

# Direction order MATCHES the neighbor-table column order (units):
# +x, -x, +y, -y, +z, -z.
_DIRS = []
for _ax, (_coord, _stride) in enumerate(
        ((_FIX, BS * BS), (_FIY, BS), (_FIZ, 1))):
    for _sign in (+1, -1):
        _interior = (_coord < BS - 1) if _sign > 0 else (_coord > 0)
        _at_face = ~_interior
        # Neighbor-brick source index for our face positions: the same
        # (other two coords), opposite wall on the stepped axis.
        _src = _FLAT_IDX - _sign * _stride * (BS - 1)
        # Dirichlet face map: dir_chi stores each face as the (a, b) plane
        # of the two non-stepped axes, flattened a*8+b in vox order.
        _others = [c for c in (_FIX, _FIY, _FIZ)
                   if c is not _coord]
        _face_map = _others[0] * BS + _others[1]
        # Face-compacted forms: the 64 face positions themselves plus the
        # neighbor-source / Dirichlet indices restricted to them — the
        # halo exchange only ever needs these 64 of the 512 brick values
        # (gathering whole neighbor bricks was 6×8 = 48× the necessary
        # halo traffic and dominated the CG matvec at 72 ms/iteration).
        _pos = _np.where(_at_face)[0].astype(_np.int32)
        _DIRS.append((
            _sign * _stride,
            _interior.astype(_np.float32),
            _at_face.astype(_np.float32),
            _np.where(_at_face, _src, 0).astype(_np.int32),
            _np.where(_at_face, _face_map, 0).astype(_np.int32),
            _pos,
            _src[_pos].astype(_np.int32),
            _face_map[_pos].astype(_np.int32),
        ))


def _dir_consts(d):
    delta, interior, at_face, src, fmap, pos, src64, fmap64 = _DIRS[d]
    return (delta,
            jnp.asarray(interior, jnp.float32),
            jnp.asarray(at_face, jnp.float32),
            jnp.asarray(src, jnp.int32), jnp.asarray(fmap, jnp.int32),
            jnp.asarray(pos, jnp.int32), jnp.asarray(src64, jnp.int32),
            jnp.asarray(fmap64, jnp.int32))


# The halo a direction-d neighbor supplies is ITS face on the opposite
# side, in the same (a, b) traversal order — verified here once at import.
_OPP = [1, 0, 3, 2, 5, 4]
for _d in range(6):
    assert _np.array_equal(_DIRS[_d][6], _DIRS[_OPP[_d]][5]), _d
# One-hot placement matrices: face-order (64) → flat brick positions
# (512). Placement-by-matmul instead of scatter-add: MXU-trivial, and a
# one-hot f32 matmul at HIGHEST precision is exact.
_PLACE = []
for _d in range(6):
    _p = _np.zeros((BS * BS, BS ** 3), _np.float32)
    _p[_np.arange(BS * BS), _DIRS[_d][5]] = 1.0
    _PLACE.append(_p)


def _neighbor_sum(x, nbr, dirichlet=None):
    """Σ over the 6 neighbors of each voxel, flat (M, BS³) in and out.
    ``dirichlet`` (M, 6, BS²) supplies values past absent-neighbor faces
    (None → zero).

    Interior terms are rolls. The cross-brick halo is face-compacted:
    one static gather extracts every brick's 6 faces into (M, 6, BS²),
    then each direction's halo is a contiguous ROW gather of (M, BS²)
    from that tensor and a one-hot matmul places it at our face
    positions — the whole exchange moves only the BS² face values
    instead of materializing entire (M, BS³) neighbor bricks per
    direction (8× the necessary halo traffic, and the dominant cost of
    the CG matvec at 1M scale)."""
    m = x.shape[0]
    faces = x[:, _FACES_ALL].reshape(m, 6, BS * BS)
    fpad = jnp.concatenate(
        [faces, jnp.zeros((1, 6, BS * BS), x.dtype)])
    acc = jnp.zeros_like(x)
    hi = jax.lax.Precision.HIGHEST
    for d in range(6):
        delta, interior, _, _, _, _, _, fmap64 = _dir_consts(d)
        acc = acc + jnp.roll(x, -delta, axis=1) * interior
        halo = fpad[:, _OPP[d], :][nbr[:, d]]          # (M, BS²) rows
        if dirichlet is not None:
            have = (nbr[:, d] < m)[:, None]
            dvals = jnp.take(dirichlet[:, d], fmap64, axis=1)
            halo = jnp.where(have, halo, dvals)
        acc = acc + jnp.matmul(halo, jnp.asarray(_PLACE[d], jnp.float32),
                               precision=hi)
    return acc


# Concatenated face positions of all 6 directions (the static extraction
# gather feeding _neighbor_sum's face tensor).
_FACES_ALL = _np.concatenate([_DIRS[_d][5] for _d in range(6)])


def _lap_band_flat(x, nbr, dirichlet=None):
    return _neighbor_sum(x, nbr, dirichlet) - 6.0 * x


def _div_band_flat(Vflat, nbr):
    """Central-difference divergence; ``Vflat`` is (M, BS³, 3) (zero
    Dirichlet — the splat support never reaches the band edge). Halo
    exchange face-compacted like :func:`_neighbor_sum`."""
    m = Vflat.shape[0]
    out = jnp.zeros((m, BS ** 3), jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    for ax in range(3):
        x = Vflat[..., ax]
        faces = x[:, _FACES_ALL].reshape(m, 6, BS * BS)
        fpad = jnp.concatenate(
            [faces, jnp.zeros((1, 6, BS * BS), x.dtype)])
        for sign, d in ((+0.5, 2 * ax), (-0.5, 2 * ax + 1)):  # +ax, −ax
            delta, interior, _, _, _, _, _, _ = _dir_consts(d)
            out = out + sign * (jnp.roll(x, -delta, axis=1) * interior)
            halo = fpad[:, _OPP[d], :][nbr[:, d]]
            out = out + sign * jnp.matmul(halo,
                                          jnp.asarray(_PLACE[d],
                                                      jnp.float32),
                                          precision=hi)
    return out


# The solve runs as FOUR jitted programs (band+splat → prolong → CG →
# iso) instead of one: a single program held the splat accumulator, the
# prolongation temporaries (the (M,8³,3) voxel-center tensor and six face
# stacks), the V field AND the CG state live simultaneously — compile-time
# HBM peaked 1.3-1.5 GB over a 16 GB chip at a 10⁵-block band. Between
# separate launches each phase's temporaries are freed before the next
# phase's exist.


# donate_argnames=(): nothing here is safely donatable — the retry loop
# in reconstruct_sparse re-submits the SAME points/normals/valid when
# the block budget overflows, and valid feeds _iso_sparse after the
# solve. in_shardings=None leaves placement to propagation (committed
# shardings pass through — the `parallel/` path relies on that) while
# recording the sharding-readiness decision explicitly (docs/JAXLINT.md).
@functools.partial(jax.jit,
                   static_argnames=("resolution", "max_blocks"),
                   donate_argnames=(),
                   in_shardings=None, out_shardings=None)
def _setup_sparse(points, normals, valid, resolution: int, max_blocks: int,
                  screen):
    R = resolution
    nb_axis = R // BS
    # Depth ≤ 13 packs a block coordinate into one int32 (10 bits/axis);
    # beyond that the wide (hi, lo) pair encoding takes over (module
    # constants). ``wide`` is static — jit specializes per resolution.
    wide = nb_axis > (1 << _KEY_BITS)
    n = points.shape[0]

    def pack2(bc):
        if wide:
            return bc[..., 0], (bc[..., 1] << _WB) | bc[..., 2]
        return _pack(bc), None

    def unpack2(kh, kl):
        if wide:
            return jnp.stack([kh, kl >> _WB, kl & ((1 << _WB) - 1)], -1)
        return _unpack(kh)

    def invalidate(kh, kl, ok):
        kh = jnp.where(ok, kh, _BIG)
        if kl is not None:
            kl = jnp.where(ok, kl, 0)
        return kh, kl

    def lookup2(th, tl, qbc):
        """(table, (..., 3) in-range query coords) → (slot, found)."""
        qh, ql = pack2(qbc)
        if wide:
            slot, found = _rank_lookup(th, tl, qh.reshape(-1),
                                       ql.reshape(-1))
        else:
            slot, found = _rank_lookup1(th, qh.reshape(-1))
        return slot.reshape(qh.shape), found.reshape(qh.shape)

    grid_pts, origin, scale = dense_poisson.normalize_points(points, valid, R)

    # Active band: 27-dilated block keys, in TWO stages — (1) sort-unique
    # the N OCCUPIED block keys (one sort of N), (2) dilate only the unique
    # occupied blocks by the 27-neighborhood and sort-unique again (one
    # sort of 27·M_occ ≪ 27·N). A single-stage sort of all 27·N dilated
    # sample keys was ~5× this cost at 1M points.
    pblock = jnp.clip((grid_pts // BS).astype(jnp.int32), 0, nb_axis - 1)
    ohi, olo = invalidate(*pack2(pblock), valid)
    ohi_s, olo_s, ofirst = _sorted_unique(ohi, olo)
    occ_hi, occ_lo, n_occ = _scatter_table(ohi_s, olo_s, ofirst, max_blocks)
    # Occupied blocks can't overflow the budget before the dilated set
    # does (occupied ⊆ dilated), so surplus here implies surplus below;
    # the dilated count reported in n_blocks triggers the caller's retry.
    occ_coords = unpack2(occ_hi, occ_lo)                   # (Mb, 3)
    occ_ok = occ_hi < _BIG

    offs = jnp.asarray([(dx, dy, dz) for dx in (-1, 0, 1)
                        for dy in (-1, 0, 1) for dz in (-1, 0, 1)],
                       jnp.int32)
    cand = occ_coords[:, None, :] + offs[None, :, :]      # (Mb, 27, 3)
    in_rng = jnp.all((cand >= 0) & (cand < nb_axis), axis=-1)
    khi, klo = pack2(jnp.clip(cand, 0, nb_axis - 1))
    khi, klo = invalidate(khi, klo, in_rng & occ_ok[:, None])
    khi = khi.reshape(-1)
    klo = None if klo is None else klo.reshape(-1)

    sk_h, sk_l, first = _sorted_unique(khi, klo)
    bk_hi, bk_lo, n_dil = _scatter_table(sk_h, sk_l, first, max_blocks)
    # True dilated-band size: occupied blocks dropped by the budget can't
    # contribute their dilation, so count conservatively from the occupied
    # count when it overflows (the caller retries with a larger budget).
    n_blocks = jnp.where(n_occ > max_blocks, n_occ, n_dil)
    block_valid = bk_hi < _BIG
    block_coords = jnp.where(block_valid[:, None], unpack2(bk_hi, bk_lo),
                             jnp.int32(nb_axis + 1))
    m = max_blocks

    # Neighbor table (M, 6): slots of the ±x/±y/±z blocks (m → "absent").
    units = jnp.asarray([[1, 0, 0], [-1, 0, 0], [0, 1, 0],
                         [0, -1, 0], [0, 0, 1], [0, 0, -1]], jnp.int32)
    nb_coords = block_coords[:, None, :] + units[None]     # (M, 6, 3)
    nb_ok = jnp.all((nb_coords >= 0) & (nb_coords < nb_axis), axis=-1)
    nb_slot, nb_found = lookup2(bk_hi, bk_lo,
                                jnp.clip(nb_coords, 0, nb_axis - 1))
    nbr = jnp.where(nb_ok & nb_found & block_valid[:, None], nb_slot, m)

    # Sparse trilinear splat of [normals, 1] into the bricks.
    g = jnp.clip(grid_pts, 0.0, R - 1 - 1e-4)
    i0 = jnp.floor(g).astype(jnp.int32)
    f = g - i0
    corners = jnp.asarray([[dx, dy, dz] for dx in (0, 1) for dy in (0, 1)
                           for dz in (0, 1)], jnp.int32)
    vidx = jnp.clip(i0[:, None, :] + corners[None], 0, R - 1)  # (N, 8, 3)
    cb = vidx // BS
    intra = vidx - cb * BS
    cslot, cfound = lookup2(bk_hi, bk_lo, cb)
    cf = corners[None].astype(jnp.float32)
    w = jnp.prod(cf * f[:, None, :] + (1 - cf) * (1 - f[:, None, :]),
                 axis=-1)
    w = w * (valid[:, None] & cfound).astype(jnp.float32)
    flat = (cslot * BS * BS * BS
            + (intra[..., 0] * BS + intra[..., 1]) * BS + intra[..., 2])
    vals = jnp.concatenate([normals, jnp.ones((n, 1), jnp.float32)], -1)
    contrib = w[..., None] * vals[:, None, :]              # (N, 8, 4)
    # Plain UNSORTED scatter-add — the round-5 head-to-head at the true
    # production shapes (8.4M rows into the 100M-row accumulator,
    # scripts/probe_splat_variants.py) measured it FASTEST: unsorted add
    # 806 ms, argsort+sorted add (the r4 form) 949 ms, double-float
    # prefix scan + compact 1471 ms, segmented scan + drop-unique set
    # 994 ms. At this table size every variant is dominated by the
    # accumulator's init+write traffic, so the extra sort/scan passes
    # only add cost — the scan trick that measured 371 vs 857 ms on a
    # 2M-row table does NOT survive the real 100M-row one.
    dest = jnp.where(cfound, flat, m * BS**3).reshape(-1)
    acc = jnp.zeros((m * BS**3 + 1, 4), jnp.float32)
    acc = acc.at[dest].add(contrib.reshape(-1, 4))[:-1]
    V = acc[:, :3].reshape(m, BS ** 3, 3)
    density = acc[:, 3].reshape(m, BS**3)

    rhs = _div_band_flat(V, nbr)

    W = dense_poisson.screen_weights(density, screen)

    return (rhs, W, nbr, block_valid, block_coords, density,
            flat, w, cfound, origin, scale, n_blocks)


# Static index maps from the (10,10,10) extended-block interpolation cube
# (axis positions e = voxel index + 1, e=0 / e=9 are the −/+ halo planes)
# into the flat brick layout and the (6, BS²) Dirichlet face layout.
_E = 10  # extended positions per axis: voxels 0..7 plus the two halos


def _extended_index_maps():
    vx, vy, vz = _np.meshgrid(_np.arange(BS), _np.arange(BS),
                              _np.arange(BS), indexing="ij")
    interior = (((vx + 1) * _E + (vy + 1)) * _E + (vz + 1)).reshape(-1)
    faces = []
    a, b = _np.meshgrid(_np.arange(BS), _np.arange(BS), indexing="ij")
    af, bf = (a + 1).reshape(-1), (b + 1).reshape(-1)
    for d in range(6):
        ax = d // 2
        wall = _E - 1 if d % 2 == 0 else 0
        e = [None, None, None]
        e[ax] = _np.full(BS * BS, wall)
        others = [i for i in range(3) if i != ax]
        e[others[0]], e[others[1]] = af, bf
        faces.append((e[0] * _E + e[1]) * _E + e[2])
    return (interior.astype(_np.int32),
            _np.concatenate(faces).astype(_np.int32))


_INTERIOR_IDX, _FACE_IDX = _extended_index_maps()


def _coarse_ratio_width(resolution: int, coarse_resolution: int):
    """(cr, W): fine→coarse coordinate ratio and the static coarse
    neighborhood width covering one block's footprint. ``int()`` runs on
    a trace-time python float (both resolutions are STATIC), never a
    tracer. # jaxlint: disable=host-sync-in-jit"""
    cr = (coarse_resolution - 1.0) / (resolution - 1.0)
    # Block footprint spans 9·cr coarse cells (+1 for floor straddle).
    W = int(_np.floor(9.0 * cr + 1.0)) + 2
    return cr, W


def _sep_weights(bcc, e, cr, Rc: int, W: int):
    """Separable per-axis interpolation data for a chunk of blocks.

    ``bcc`` (C, 3) block coords, ``e`` (E,) per-axis fine offsets within
    the block (−1 and 8 are the halo planes). Every extended position
    interpolates the coarse field at ``t = clip(fine_coord · cr)``; the
    weights factor per axis, so ONE (E, W) weight matrix per axis plus a
    (W, W, W) gathered coarse neighborhood per block reproduce the
    trilinear gather exactly. Returns (wgt (C, 3, E, W), flat_idx
    (C, W, W, W) int32 into the flat coarse grid)."""
    iota = jnp.arange(W, dtype=jnp.int32)
    g = bcc[:, :, None].astype(jnp.float32) * BS + e[None, None, :]
    t = jnp.clip(g * cr, 0.0, Rc - 1 - 1e-4)           # (C, 3, E)
    c0 = jnp.clip(jnp.floor(t[:, :, 0]).astype(jnp.int32), 0, Rc - W)
    tl = t - c0[:, :, None].astype(jnp.float32)        # ∈ [0, W-1)
    i0 = jnp.clip(jnp.floor(tl).astype(jnp.int32), 0, W - 2)
    f = tl - i0.astype(jnp.float32)
    wgt = (jnp.where(iota == i0[..., None], 1.0 - f[..., None], 0.0)
           + jnp.where(iota == i0[..., None] + 1, f[..., None], 0.0))
    ix = jnp.clip(c0[:, 0, None] + iota, 0, Rc - 1)
    iy = jnp.clip(c0[:, 1, None] + iota, 0, Rc - 1)
    iz = jnp.clip(c0[:, 2, None] + iota, 0, Rc - 1)
    flat_idx = ((ix[:, :, None, None] * Rc
                 + iy[:, None, :, None]) * Rc
                + iz[:, None, None, :])
    return wgt, flat_idx


# coarse_chi and rhs die here (the folded b replaces rhs; the coarse
# field only seeds x0), so both donate — at a 10⁵-block band that is
# two (M, BS³) buffers of headroom per solve. nbr/block_valid/
# block_coords are NOT donated: the CG and extraction reuse them.
@functools.partial(jax.jit, static_argnames=("resolution",
                                             "coarse_resolution", "chunk"),
                   donate_argnames=("coarse_chi", "rhs"),
                   in_shardings=None, out_shardings=None)
def _prolong_band(coarse_chi, rhs, nbr, block_valid, block_coords,
                  resolution: int, coarse_resolution: int,
                  chunk: int = 8192):
    """Prolong the coarse solution onto the band: the CG seed ``x0`` and
    the Dirichlet-halo-folded RHS ``b``.

    The interpolation is SEPARABLE per axis: every extended block position
    (8 voxels + 2 halos per axis) interpolates the coarse field at
    ``t = clip(fine_coord · cr)``, so one (10, W) weight matrix per axis
    and one (W, W, W) gathered coarse neighborhood per block reproduce the
    old per-point trilinear gather exactly — with M·W³ (~12M) random loads
    instead of M·896 interpolation points × 8 corners (~1.4G element
    loads, the measured 14 s of the round-2 solve). W is the static
    neighborhood width covering the block's coarse footprint."""
    R, Rc = resolution, coarse_resolution
    cr, W = _coarse_ratio_width(R, Rc)
    m = block_coords.shape[0]
    coarse_flat = coarse_chi.reshape(-1)

    m_pad = ((m + chunk - 1) // chunk) * chunk
    bc = block_coords
    if m_pad != m:
        bc = jnp.concatenate(
            [bc, jnp.zeros((m_pad - m, 3), bc.dtype)])

    def per_chunk(bcc):
        C = bcc.shape[0]
        e = jnp.arange(_E, dtype=jnp.float32) - 1.0        # halo..halo
        # (C, 3, 10, W) separable weights; (C, W, W, W) coarse values.
        wgt, flat_idx = _sep_weights(bcc, e, cr, Rc, W)
        G = coarse_flat[flat_idx.reshape(C, -1)].reshape(C, W, W, W)
        E3 = jnp.einsum("cxi,cyj,czk,cijk->cxyz",
                        wgt[:, 0], wgt[:, 1], wgt[:, 2], G)
        Ef = E3.reshape(C, _E ** 3)
        return Ef[:, _INTERIOR_IDX], Ef[:, _FACE_IDX]

    x0p, dirp = jax.lax.map(
        per_chunk, bc.reshape(m_pad // chunk, chunk, 3))
    x0 = x0p.reshape(m_pad, BS ** 3)[:m]
    dir_chi = dirp.reshape(m_pad, 6, BS * BS)[:m]
    band = block_valid[:, None]
    x0 = jnp.where(band, x0, 0.0)
    dir_chi = jnp.where(block_valid[:, None, None], dir_chi, 0.0)

    # Fold the constant Dirichlet halo into the RHS once:
    #   A(x; halo) = A0(x) + L_halo  ⇒  solve A0 x = b − L_halo.
    halo_term = _lap_band_flat(jnp.zeros_like(x0), nbr, dirichlet=dir_chi)
    b = jnp.where(band, -(rhs - halo_term), 0.0)
    return b, x0


# donate_argnames=() is a DECISION, not an omission: callers
# legitimately re-solve one assembled (b, x0) system — the
# preconditioner parity tests and probe scripts sweep rtol/precond over
# the same buffers, and x0 is the warm-start surface (reconstruct_sparse
# seeds it from a caller-held previous grid). Donating either breaks
# that reuse the moment a backend honors donation (CPU does).
@functools.partial(jax.jit, static_argnames=("cg_iters", "use_pallas"),
                   donate_argnames=(),
                   in_shardings=None, out_shardings=None)
def _cg_sparse(b, W, x0, nbr, block_valid, cg_iters: int,
               rtol=3e-4, use_pallas: bool | None = None):
    # rtol default is a PLAIN float (and matches the public 3e-4): a
    # jnp.float32 default would evaluate at import time and initialize
    # the XLA backend, breaking jax.distributed for multi-host users
    # (the same rule as the module-level _BIG comment).
    """Jacobi-preconditioned CG. All state is FLAT (M, BS³): the loop
    carry materializes with the buffer layout, and a (…,8,8,8) carry pads
    16× under the (8,128) tile — the 16 GB allocation that originally
    OOM'd this solve.

    The preconditioner is the operator diagonal ``6 + W``: the screening
    term varies over the band with splat density, which is exactly the
    variation a diagonal scaling removes — measured on the 1M bench cloud
    it reaches ‖r‖/‖b‖ = 1e-4 in ~80 iterations where plain CG needed
    ~200 (Jacobi preserves SPD, so CG theory still applies).

    ``cg_iters`` is the CAP; the residual stop (‖r‖ ≤ rtol·‖b‖, a
    ``lax.while_loop``) ends the solve as soon as the coarse-seeded x0
    has been refined to tolerance. Returns (chi, iterations_used).

    ``use_pallas``: None = the Mosaic one-pass stencil
    (`ops/poisson_pallas.py`) on TPU backends, the XLA roll/face/matmul
    form elsewhere (it remains the oracle — parity pinned in
    tests/test_poisson_pallas.py)."""
    band = block_valid[:, None]
    dinv = jnp.where(band, 1.0 / (6.0 + W), 0.0)

    # Resolve the engine from the backend alone so the kernel module (and
    # with it jax.experimental.pallas) is only imported on the path that
    # uses it — CPU-only deployments must never touch pallas (round-5
    # advisor finding; enforced by the `pallas-import` jaxlint rule).
    if use_pallas is None:
        use_pallas = _backend.tpu_backend()
    if use_pallas:
        from . import poisson_pallas

        # v2 hybrid (XLA face/halo prep + fused roll/place kernel):
        # 31 ms/apply vs 52 ms XLA at the 1M depth-10 shape — the pure
        # whole-brick-DMA kernel (matvec_pallas) measured DMA-issue-bound
        # at 35-46 ms (numbers in ops/poisson_pallas.py).
        def matvec(xf):
            return poisson_pallas.matvec_pallas_v2(xf, W, nbr,
                                                   block_valid, cb=64)
    else:
        def matvec(xf):
            out = _lap_band_flat(xf, nbr) - W * xf
            return jnp.where(band, -out, 0.0)

    r0 = b - matvec(x0)
    z0 = dinv * r0
    rz0 = jnp.vdot(r0, z0)
    tol2 = rtol * rtol * jnp.vdot(b, b)

    def cond(state):
        _, _, _, _, rs, it = state
        return (it < cg_iters) & (rs > tol2)

    def body(state):
        x, r, p, rz, _, it = state
        Ap = matvec(p)
        alpha = rz / jnp.maximum(jnp.vdot(p, Ap), 1e-30)
        x = x + alpha * p
        r = r - alpha * Ap
        z = dinv * r
        rz_new = jnp.vdot(r, z)
        beta = rz_new / jnp.maximum(rz, 1e-30)
        p = z + beta * p
        return x, r, p, rz_new, jnp.vdot(r, r), it + 1

    chi, _, _, _, _, iters = jax.lax.while_loop(
        cond, body, (x0, r0, z0, rz0, jnp.vdot(r0, r0), jnp.int32(0)))
    return jnp.where(band, chi, 0.0), iters  # (M, BS³) flat


# Same donation contract as _cg_sparse: b/x0 are deliberately
# re-solvable (donate nothing).
@functools.partial(jax.jit, static_argnames=(
    "resolution", "coarse_resolution", "cg_iters", "use_pallas",
    "precond", "precond_coarse_iters", "cheby_degree", "chunk",
    "fine_dtype"),
    donate_argnames=(),
    in_shardings=None, out_shardings=None)
def _pcg_sparse(b, W, x0, nbr, block_valid, block_coords, coarse_W,
                resolution: int, coarse_resolution: int, cg_iters: int,
                rtol=3e-4, use_pallas: bool | None = None,
                precond: str = "additive",
                precond_coarse_iters: int | None = None,
                smooth_omega=None, cheby_lmin=0.06, cheby_lmax=2.0,
                cheby_degree: int = 4, chunk: int = 8192,
                fine_dtype: str = "float32"):
    """Flexible PCG with a two-level (additive or V-cycle) or Chebyshev
    preconditioner.

    The Jacobi path (:func:`_cg_sparse`) converges but spends 62-71 fine
    matvecs at the 1M depth-10 shape: the diagonal removes the screening
    term's density variation and nothing else, so the SMOOTH error modes
    of the Laplacian decay one grid-sweep per iteration. The two-level
    schemes kill exactly those modes on the dense coarse grid the solve
    already owns (the Dirichlet-seed grid), through the same separable
    restriction/prolongation machinery as :func:`_prolong_band`.

    ``precond="additive"`` (default): ``M⁻¹r = ω·D⁻¹r + P·Mc⁻¹·Pᵀ·r`` —
    the Jacobi term and the coarse correction applied to the SAME
    residual and summed. No fine matvec inside the preconditioner at
    all, so total band traffic ≈ the iteration count — measured 26 vs 65
    Jacobi iterations at the 37.9k-block depth-9 probe shape
    (ω=2, 4 coarse iters; scripts/probe_precond_iters.py), with the
    coarse PCG (a 128³ dense grid, ~2% of the band's cells at 1M)
    almost free.

    ``precond="vcycle"``: one damped-Jacobi pre-smooth, the coarse
    correction, one post-smooth (multiplicative). Few iterations
    (28 at the probe shape) but each application costs 2 extra band
    matvecs, so it only wins when the outer loop, not the matvec,
    dominates.

    Both two-level schemes MASK the coarse solve to the band footprint
    (coarse cells the restriction writes to, plus nothing else): a
    fixed-iteration coarse PCG spends its whole budget on the region
    that feeds back through prolongation instead of converging empty
    space — and the mask IS the fine problem's real boundary (the band
    edge is Dirichlet, folded into ``b``). Masked vs unmasked additive
    at the probe shape: 30 vs 36 iterations (ω=2), 35 vs 44 (ω=1).

    The coarse correction solves the fine ERROR equation, so the coarse
    operator must match the fine one's scaling: the unscaled 7-point
    Laplacian represents ``h²∇²`` at each level, hence restriction
    carries a ``cr = h_f/h_c`` factor (full-weighting ``Pᵀ/ratio³``
    times the ``ratio²`` operator rescale) and the coarse screen is the
    coarse grid's own normalized density screen amplified by ``ratio²``
    (the same per-level screen scaling as Kazhdan's screened-Poisson
    multigrid).

    The fixed-iteration coarse PCG makes the preconditioner slightly
    nonlinear, so the outer loop uses the Polak-Ribière (flexible) beta
    — identical to Fletcher-Reeves for an exactly linear M, and immune
    to the drift otherwise. The stopping rule (‖r‖ ≤ rtol·‖b‖) and
    returned (chi, iterations) contract match :func:`_cg_sparse`.

    ``precond="chebyshev"``: degree-``cheby_degree`` Chebyshev
    semi-iteration on the Jacobi-scaled band operator over
    ``[cheby_lmin, cheby_lmax]`` — linear, symmetric, no coarse traffic;
    each application costs ``cheby_degree - 1`` band matvecs.

    ``fine_dtype="bfloat16"`` (PoissonParams.fine_dtype) demotes the
    RELAXATION arithmetic — the band-side elementwise terms of
    ``apply_M`` (scaled-Jacobi branch, V-cycle smoothing steps, the
    Chebyshev recurrence state) — to bf16. Everything on the Krylov
    side stays fp32: the matvec, the residual updates, every ``vdot``
    and the accumulation of ``x`` — so the stopping rule measures the
    true fp32 residual and the only effect of the demotion is a
    slightly perturbed preconditioner, which the flexible beta already
    tolerates (it exists for the coarse-truncation nonlinearity). With
    the default ``"float32"`` every cast is a no-op and the compiled
    program is the pre-existing one bit for bit.
    """
    R, Rc = resolution, coarse_resolution
    band = block_valid[:, None]
    dinv = jnp.where(band, 1.0 / (6.0 + W), 0.0)
    if fine_dtype not in ("float32", "bfloat16"):
        raise ValueError(f"fine_dtype must be 'float32' or 'bfloat16', "
                         f"got {fine_dtype!r}")
    cdt = jnp.bfloat16 if fine_dtype == "bfloat16" else jnp.float32
    # Relaxation-side diagonal: the only band-resident field the
    # preconditioner reads elementwise every application.
    dinv_l = dinv.astype(cdt)

    # Per-scheme measured defaults (PoissonParams docstring): the SAME
    # knob plays a different role per scheme — additive's ω weights the
    # diagonal branch against the coarse one (optimum ≥ 2), vcycle's ω
    # damps the Jacobi smoother (must stay < 1).
    if precond_coarse_iters is None:
        precond_coarse_iters = 4 if precond == "additive" else 8
    if smooth_omega is None:
        smooth_omega = 2.0 if precond == "additive" else 0.8

    # Same lazy kernel-module gate as _cg_sparse (pallas-import rule).
    if use_pallas is None:
        use_pallas = _backend.tpu_backend()
    if use_pallas:
        from . import poisson_pallas

        def matvec(xf):
            return poisson_pallas.matvec_pallas_v2(xf, W, nbr,
                                                   block_valid, cb=64)
    else:
        def matvec(xf):
            out = _lap_band_flat(xf, nbr) - W * xf
            return jnp.where(band, -out, 0.0)

    if precond == "chebyshev":
        # Chebyshev semi-iteration for A z ≈ r on the Jacobi-scaled
        # operator: fixed degree, fixed coefficients — a polynomial in A,
        # hence exactly linear and symmetric.
        theta = 0.5 * (cheby_lmax + cheby_lmin)
        delta = 0.5 * (cheby_lmax - cheby_lmin)

        def apply_M(r):
            # Recurrence state in the relaxation dtype; matvec and the
            # final mask-out stay fp32 (fine_dtype docstring above).
            rl = r.astype(cdt)
            z = jnp.asarray(1.0 / theta, cdt) * dinv_l * rl

            # Three-term recurrence (z_{k-1}, z_k) with the standard
            # rho update; degree-1 is the scaled-Jacobi seed above.
            def chb3(_i, st):
                z_prev, z_c, rho_o = st
                rho = 1.0 / (2.0 * theta / delta - rho_o)
                resid = dinv_l * (rl - matvec(
                    z_c.astype(jnp.float32)).astype(cdt))
                z_n = z_c + jnp.asarray(rho, cdt) * (
                    jnp.asarray(2.0 / delta, cdt) * resid
                    + rho_o.astype(cdt) * (z_c - z_prev))
                return z_c, z_n, rho

            _, z, _ = jax.lax.fori_loop(
                0, cheby_degree - 1, chb3,
                (jnp.zeros_like(z), z,
                 jnp.asarray(delta / theta, jnp.float32)))
            return jnp.where(band, z.astype(jnp.float32), 0.0)

    elif precond in ("vcycle", "additive"):
        cr, Wn = _coarse_ratio_width(R, Rc)
        crf = jnp.float32(cr)
        m = block_coords.shape[0]
        m_pad = ((m + chunk - 1) // chunk) * chunk
        bc = block_coords
        if m_pad != m:
            bc = jnp.concatenate(
                [bc, jnp.zeros((m_pad - m, 3), bc.dtype)])
        n_chunks = m_pad // chunk
        bc_ch = bc.reshape(n_chunks, chunk, 3)
        # Precompute the separable transfer data once per solve — the
        # interior 8 positions only (the preconditioner never touches
        # the halo planes; the Dirichlet fold lives in b already).
        e_int = jnp.arange(BS, dtype=jnp.float32)

        # ratio² screen amplification: the coarse operator acts on the
        # fine error equation multiplied through by (h_c/h_f)².
        ratio2 = jnp.float32(((R - 1.0) / (Rc - 1.0)) ** 2)
        Wc = coarse_W * ratio2
        dinv_c = 1.0 / (6.0 + Wc)

        def restrict(rf):
            """Band residual (M, BS³) → coarse grid (Rc³,): Pᵀ·cr,
            chunked scan so the transient 3-D views stay one chunk
            long (the (…, 8, 8) TPU-tile padding note up top)."""
            rf_p = jnp.concatenate(
                [rf, jnp.zeros((m_pad - m, BS ** 3), rf.dtype)]) \
                if m_pad != m else rf
            rf_ch = rf_p.reshape(n_chunks, chunk, BS ** 3)

            def step(acc, ch):
                bcc, rc_ = ch
                wgt, flat_idx = _sep_weights(bcc, e_int, cr, Rc, Wn)
                r3 = rc_.reshape(chunk, BS, BS, BS)
                G = jnp.einsum("cxi,cyj,czk,cxyz->cijk",
                               wgt[:, 0], wgt[:, 1], wgt[:, 2], r3)
                acc = acc.at[flat_idx.reshape(-1)].add(
                    G.reshape(-1) * crf)
                return acc, None

            acc0 = jnp.zeros((Rc ** 3,), jnp.float32)
            acc, _ = jax.lax.scan(step, acc0, (bc_ch, rf_ch))
            return acc

        def prolong(ec_flat):
            """Coarse correction (Rc³,) → band interiors (M, BS³)."""
            def step(_c, bcc):
                wgt, flat_idx = _sep_weights(bcc, e_int, cr, Rc, Wn)
                G = ec_flat[flat_idx.reshape(chunk, -1)].reshape(
                    chunk, Wn, Wn, Wn)
                E3 = jnp.einsum("cxi,cyj,czk,cijk->cxyz",
                                wgt[:, 0], wgt[:, 1], wgt[:, 2], G)
                return _c, E3.reshape(chunk, BS ** 3)

            _, out = jax.lax.scan(step, 0, bc_ch)
            return out.reshape(m_pad, BS ** 3)[:m]

        # Band footprint on the coarse grid: cells the restriction of a
        # band-supported field can reach (one restrict of all-ones).
        # Fixing the coarse PCG to this region (zero-Dirichlet outside)
        # spends its whole fixed budget on cells that feed back through
        # prolongation — measured 6-9 iterations cheaper than unmasked
        # at every (ω, ci) point of the probe sweep (docstring above).
        cmask = (restrict(jnp.broadcast_to(
            band.astype(jnp.float32), (m, BS ** 3))) > 0.0).astype(
            jnp.float32).reshape(Rc, Rc, Rc)

        def matvec_c(xc):
            return cmask * -(dense_poisson.laplacian(xc) - Wc * xc)

        def coarse_solve(rc):
            """Fixed-iteration Jacobi-PCG on the masked coarse grid
            (4-8 iters at 128³ — a sliver of one band matvec of traffic
            at the 1M depth-10 shape; MORE coarse iterations measured
            strictly worse, ci=4 < 8 < 16 in outer-iteration count).
            Fixed count keeps cost deterministic; the flexible outer
            beta absorbs the nonlinearity of truncation."""
            r = cmask * rc.reshape(Rc, Rc, Rc)
            x = jnp.zeros_like(r)
            z = dinv_c * r
            p = z
            rz = jnp.vdot(r, z)

            def step(_i, st):
                x, r, p, rz = st
                Ap = matvec_c(p)
                alpha = rz / jnp.maximum(jnp.vdot(p, Ap), 1e-30)
                x = x + alpha * p
                r = r - alpha * Ap
                z = dinv_c * r
                rz_new = jnp.vdot(r, z)
                beta = rz_new / jnp.maximum(rz, 1e-30)
                return x, r, z + beta * p, rz_new

            x, _, _, _ = jax.lax.fori_loop(
                0, precond_coarse_iters, step, (x, r, p, rz))
            return (cmask * x).reshape(-1)

        om = smooth_omega
        # Relaxation-dtype smoothing weight: the ω·D⁻¹ branch is the
        # band-side elementwise term fine_dtype demotes; restriction,
        # the coarse solve and prolongation keep fp32 accumulation.
        om_l = jnp.asarray(om, cdt)

        if precond == "additive":
            def apply_M(r):
                # Jacobi term + coarse correction of the SAME residual,
                # summed: no fine matvec inside the preconditioner.
                ec = coarse_solve(restrict(r))
                zj = (om_l * dinv_l * r.astype(cdt)).astype(jnp.float32)
                z = zj + jnp.where(band, prolong(ec), 0.0)
                return jnp.where(band, z, 0.0)
        else:
            def apply_M(r):
                # Pre-smooth from zero (free of matvecs), coarse-correct,
                # post-smooth — the symmetric two-grid preconditioner.
                z = (om_l * dinv_l * r.astype(cdt)).astype(jnp.float32)
                rr = r - matvec(z)
                ec = coarse_solve(restrict(rr))
                z = z + jnp.where(band, prolong(ec), 0.0)
                z = z + (om_l * dinv_l
                         * (r - matvec(z)).astype(cdt)).astype(jnp.float32)
                return jnp.where(band, z, 0.0)

    else:
        raise ValueError(f"unknown preconditioner {precond!r}")

    r0 = b - matvec(x0)
    z0 = apply_M(r0)
    rz0 = jnp.vdot(r0, z0)
    tol2 = rtol * rtol * jnp.vdot(b, b)

    def cond(state):
        _, _, _, _, _, rs, it = state
        return (it < cg_iters) & (rs > tol2)

    def body(state):
        x, r, p, z, rz, _, it = state
        Ap = matvec(p)
        alpha = rz / jnp.maximum(jnp.vdot(p, Ap), 1e-30)
        x = x + alpha * p
        r_new = r - alpha * Ap
        z_new = apply_M(r_new)
        rz_new = jnp.vdot(r_new, z_new)
        # Polak-Ribière (flexible) beta: subtracts the stale-direction
        # component a variable M injects; equals FR when M is linear.
        beta = (rz_new - jnp.vdot(r_new, z)) / jnp.maximum(rz, 1e-30)
        p = z_new + beta * p
        return (x, r_new, p, z_new, rz_new, jnp.vdot(r_new, r_new),
                it + 1)

    chi, _, _, _, _, _, iters = jax.lax.while_loop(
        cond, body, (x0, r0, z0, z0, rz0, jnp.vdot(r0, r0),
                     jnp.int32(0)))
    return jnp.where(band, chi, 0.0), iters


# flat/w/cfound (the per-sample trilinear gather tables) die here;
# chi/density are the returned grid's fields and valid is the caller's
# — none of those may donate.
@functools.partial(jax.jit, donate_argnames=("flat", "w", "cfound"),
                   in_shardings=None, out_shardings=None)
def _iso_sparse(chi, density, flat, w, cfound, valid):
    """Density-weighted mean of chi at the samples (8 trilinear corners
    per sample, gathered from the bricks)."""
    cflat = chi.reshape(-1)
    dflat = density.reshape(-1)
    ok8 = cfound & valid[:, None]
    chi_pts = jnp.sum(jnp.where(ok8, cflat[flat], 0.0) * w, axis=1)
    den_pts = jnp.sum(jnp.where(ok8, dflat[flat], 0.0) * w, axis=1)
    return jnp.sum(chi_pts * den_pts) / jnp.maximum(jnp.sum(den_pts), 1e-12)


# donate_argnames=() is a DECISION: prev_chi belongs to the caller's
# preview grid (a finalize may re-mesh at a new trim and warm-start
# again) and points/valid feed the setup + coarse solve after this.
# in_shardings=None leaves placement to propagation, like every solver
# jit here (docs/JAXLINT.md sharding-readiness).
@functools.partial(jax.jit, static_argnames=("rc",),
                   donate_argnames=(),
                   in_shardings=None, out_shardings=None)
def _resample_chi_to_coarse(prev_chi, prev_origin, prev_scale, points,
                            valid, rc: int):
    """Trilinearly resample a DENSE preview χ grid onto this solve's
    internal coarse frame (the dense→sparse half of the warm-start
    contract): the coarse dense solve then starts from the preview's
    converged field instead of zeros, so its residual stop fires after
    measurably fewer iterations (streaming finalize — the previews
    watched the SAME model the finalize merges). World-aligned through
    each grid's own (origin, scale), so the preview's normalization
    never has to match. Outside the preview's domain the seed is the
    cold zero. Slab-mapped (``lax.map`` over x-planes) so the 256³
    coarse case never materializes the full gather tensor."""
    rp = prev_chi.shape[0]
    _, origin_c, scale_c = dense_poisson.normalize_points(points, valid,
                                                          rc)
    v = jnp.arange(rc, dtype=jnp.float32)

    def slab(xi):
        Y, Z = jnp.meshgrid(v, v, indexing="ij")
        world = origin_c[None, None, :] + jnp.stack(
            [jnp.full((rc, rc), xi, jnp.float32), Y, Z],
            axis=-1) * scale_c
        q = (world - prev_origin[None, None, :]) / prev_scale
        inside = jnp.all((q >= 0.0) & (q <= rp - 1.0), axis=-1)
        qc = jnp.clip(jnp.floor(q).astype(jnp.int32), 0, rp - 2)
        f = jnp.clip(q - qc.astype(jnp.float32), 0.0, 1.0)

        def g(dx, dy, dz):
            return prev_chi[qc[..., 0] + dx, qc[..., 1] + dy,
                            qc[..., 2] + dz]

        fx, fy, fz = f[..., 0], f[..., 1], f[..., 2]
        c00 = g(0, 0, 0) * (1 - fz) + g(0, 0, 1) * fz
        c01 = g(0, 1, 0) * (1 - fz) + g(0, 1, 1) * fz
        c10 = g(1, 0, 0) * (1 - fz) + g(1, 0, 1) * fz
        c11 = g(1, 1, 0) * (1 - fz) + g(1, 1, 1) * fz
        c0 = c00 * (1 - fy) + c01 * fy
        c1 = c10 * (1 - fy) + c11 * fy
        return jnp.where(inside, c0 * (1 - fx) + c1 * fx, 0.0)

    return jax.lax.map(slab, v)


def _dense_cover_blocks(block_coords, block_valid, origin, scale,
                        prev) -> int:
    """How many ACTIVE band blocks the dense preview grid covers — the
    ``warm_start_blocks`` bookkeeping of the dense-x0 path (the blocks
    whose coarse seed the preview field informed)."""
    bv = _np.asarray(block_valid)
    bc = _np.asarray(block_coords)[bv]
    if bc.shape[0] == 0:
        return 0
    center = (bc.astype(_np.float64) * BS + 0.5 * BS)
    world = _np.asarray(origin, _np.float64) + center * float(scale)
    q = (world - _np.asarray(prev.origin, _np.float64)) \
        / float(prev.scale)
    rp = prev.chi.shape[0]
    inside = _np.all((q >= 0.0) & (q <= rp - 1.0), axis=-1)
    return int(inside.sum())


def _warm_start_seed(seed, prev: SparsePoissonGrid, block_coords,
                     block_valid, origin, scale, resolution: int):
    """Overlay a previous solve's χ onto the new band's CG seed.

    Blocks present in BOTH bands (matched by integer block coordinate —
    valid only when the grid normalization did not move) start from the
    previous converged χ instead of the coarse prolongation; new blocks
    keep the coarse seed. The previous chi is COPIED (``.at[].set``), so
    the caller-held grid stays valid.
    Returns ``(seed, matched_block_count)`` — 0 means the warm start was
    skipped (resolution/normalization mismatch or disjoint bands)."""
    if prev.resolution != resolution:
        log.info("sparse warm start skipped: previous grid resolution "
                 "%d != %d", prev.resolution, resolution)
        return seed, 0
    prev_origin = np.asarray(prev.origin, np.float64)
    new_origin = np.asarray(origin, np.float64)
    prev_scale = float(prev.scale)
    new_scale = float(scale)
    tol = 1e-5 * max(abs(prev_scale), abs(new_scale))
    if abs(prev_scale - new_scale) > tol or not np.allclose(
            prev_origin, new_origin, rtol=0.0, atol=tol * BS):
        log.info("sparse warm start skipped: grid normalization moved "
                 "(origin/scale differ) — the previous chi is not "
                 "voxel-aligned with this band")
        return seed, 0
    pv = np.asarray(prev.block_valid)
    nv = np.asarray(block_valid)
    pi = np.nonzero(pv)[0]
    ni = np.nonzero(nv)[0]
    if pi.size == 0 or ni.size == 0:
        return seed, 0
    bits = 21  # nb_axis ≤ 2^13 at depth 16 — 21 bits/axis is ample

    def pack(bc):
        bc = bc.astype(np.int64)
        return (bc[:, 0] << (2 * bits)) | (bc[:, 1] << bits) | bc[:, 2]

    pk = pack(np.asarray(prev.block_coords)[pi])
    nk = pack(np.asarray(block_coords)[ni])
    order = np.argsort(pk)
    pos = np.minimum(np.searchsorted(pk, nk, sorter=order), pk.size - 1)
    hit = pk[order[pos]] == nk
    if not hit.any():
        return seed, 0
    dst = jnp.asarray(ni[hit], jnp.int32)
    src = jnp.asarray(pi[order[pos[hit]]], jnp.int32)
    seed = seed.at[dst].set(jnp.asarray(prev.chi, jnp.float32)[src])
    return seed, int(hit.sum())


def reconstruct_sparse(points, normals, valid=None, depth: int | None = None,
                       cg_iters: int | None = None,
                       screen: float | None = None,
                       max_blocks: int | None = None,
                       coarse_depth: int | None = None,
                       coarse_iters: int | None = None,
                       rtol: float | None = None,
                       preconditioner: str | None = None,
                       params: PoissonParams | None = None,
                       with_stats: bool = False,
                       x0: "SparsePoissonGrid | None" = None):
    """Band-sparse screened Poisson at depth 9-16 (module docstring).

    Matches the reference's octree-Poisson acceptance envelope: default
    depth 10 (`server/processing.py:293`), any depth ≤ 16 accepted, > 16
    rejected (`server/processing.py:207-208` — "will freeze your PC").
    Depths 14-16 route block keys through the wide (hi, lo) pair path.

    Memory is governed by the BAND, not the virtual grid: each field costs
    ``max_blocks · 8³ · 4`` bytes and ~8 live simultaneously through CG
    (~1.7 GB at the default budget). The band grows with depth — each
    sample's dilated neighborhood becomes its own blocks once the block
    edge (2^(depth-3) per axis) out-resolves the sampling density — so at
    depth 14+ a dense 1M-point scan can demand tens of millions of blocks:
    the budget-overflow retry below then grows ``max_blocks`` toward HBM
    limits and warns. Like the reference (whose octree at depth 16 also
    eats whatever the cloud demands), deep depths are ACCEPTED, bounded,
    and honest about cost — not silently truncated.

    ``cg_iters`` caps the fine-band CG; the residual stop (``rtol``)
    usually ends it far sooner. The 3e-4 default is measured, not
    guessed: on the depth-10 ground-truth sphere (120k points) the
    extracted surface error is IDENTICAL at rtol 1e-4 / 3e-4 / 1e-3
    (median 0.014 ≈ 6% of a voxel, p90 0.037 — discretization-limited),
    while the iteration count drops 75 → 61 → 50; 3e-4 keeps a 2×
    margin above the loosest tolerance that still matched.

    ``preconditioner`` selects the fine CG's preconditioner (see
    :class:`PoissonParams`): ``"additive"`` (default — additive
    two-level geometric multigrid over the coarse seed grid, ≤ half the
    Jacobi iteration count at the same rtol with no extra band matvec
    per iteration), ``"vcycle"`` (multiplicative), ``"chebyshev"``, or
    ``"jacobi"`` (the original path, bit-for-bit untouched). ``params``
    bundles every knob as one hashable object; :class:`PoissonParams` is
    the SINGLE source of defaults (every keyword above defaults to None
    = "take it from params"), and mixing ``params`` with explicit
    keywords is an error — silent precedence between the two was a
    depth-10-instead-of-15 footgun.

    ``x0`` WARM-STARTS the solve from a previous grid. A
    :class:`SparsePoissonGrid` (a previous ``reconstruct_sparse``)
    seeds the FINE band directly: blocks present in both bands start
    from the previous converged χ instead of the coarse prolongation —
    accepted only when resolution AND grid normalization (origin/scale)
    match, otherwise skipped with a log line. A DENSE
    ``poisson.PoissonGrid`` (a streaming preview's last solve) instead
    warm-starts the INTERNAL COARSE dense solve, world-aligned through
    each grid's own normalization (the preview watched the same model
    the finalize merges, so the coarse residual stop fires after
    measurably fewer iterations); overlaying a coarser preview onto the
    fine band directly would only degrade the prolongation it replaces.

    ``with_stats`` appends a third return value, a dict with
    ``cg_iters_used`` (fine-band iterations the residual stop actually
    spent), ``coarse_iters_used`` (the internal coarse solve's count —
    the dense-x0 warm start's measurable win), ``preconditioner`` and
    ``warm_start_blocks`` (band blocks seeded/covered by ``x0``; 0 =
    cold) — the bench's ≤ 30-iteration gate and the convergence tests
    read it instead of scraping logs.
    """
    given = {k: v for k, v in dict(
        depth=depth, cg_iters=cg_iters, screen=screen,
        max_blocks=max_blocks, coarse_depth=coarse_depth,
        coarse_iters=coarse_iters, rtol=rtol,
        preconditioner=preconditioner).items() if v is not None}
    if params is None:
        params = PoissonParams()._replace(**given)
    elif given:
        raise ValueError(
            "pass solver knobs either as keywords or bundled in params, "
            f"not both (got params plus {sorted(given)})")
    depth = params.depth
    cg_iters = params.cg_iters
    screen = params.screen
    max_blocks = params.max_blocks
    coarse_depth = params.coarse_depth
    coarse_iters = params.coarse_iters
    rtol = params.rtol
    preconditioner = params.preconditioner
    if preconditioner not in ("additive", "vcycle", "chebyshev", "jacobi"):
        raise ValueError(
            f"preconditioner must be 'additive', 'vcycle', 'chebyshev' "
            f"or 'jacobi', got {preconditioner!r}")
    fine_dtype = params.fine_dtype
    if fine_dtype not in ("float32", "bfloat16"):
        raise ValueError(f"fine_dtype must be 'float32' or 'bfloat16', "
                         f"got {fine_dtype!r}")
    if fine_dtype != "float32" and preconditioner == "jacobi":
        raise ValueError(
            "fine_dtype='bfloat16' rides the preconditioned schemes' "
            "relaxation stage; the 'jacobi' oracle path has none and "
            "stays fp32 bit-for-bit — pick additive/vcycle/chebyshev")
    if depth > 16:
        raise ValueError(f"depth={depth} > 16: rejected exactly like the "
                         "reference's octree guard "
                         "(server/processing.py:207-208)")
    if 2 ** depth < 4 * BS:
        raise ValueError(f"depth={depth} too shallow for the block solver; "
                         "use ops.poisson.reconstruct")
    if coarse_depth is None:
        # Depth-aware coarse grid: keep the coarse/fine ratio ≤ 128.
        # At ratio 256 (depth 15 over the old fixed 128³) the band is
        # ~0.05 coarse cells thick: the Dirichlet halo folded from the
        # coarse field pins BOTH band faces to nearly the same coarse
        # value, so wherever the coarse blob misplaces the surface the
        # fine level set shifts with it — the depth-15 p90 = 4.63-voxel
        # error tail of BENCH r5 (depth 14 at ratio 128, same cloud
        # density: p90 0.29). Capped at 8 (256³ dense ≈ 470 MB of
        # solver state); an explicit coarse_depth is always honored.
        coarse_depth = min(8, max(7, depth - 7))
        if coarse_depth > 7:
            log.info("sparse Poisson depth=%d: coarse grid auto-raised "
                     "to %d^3 (coarse/fine ratio cap 128)", depth,
                     2 ** coarse_depth)
        if depth - coarse_depth > 7:
            # Depth 16 only: the memory cap (256³ dense ≈ 470 MB of
            # coarse solver state) wins over the ratio cap, so the
            # ratio is 256 — the regime with the measured p90 tail.
            log.warning(
                "sparse Poisson depth=%d: coarse/fine ratio is %d "
                "(memory-capped at coarse 256³) — surface error can "
                "carry the unresolved-coarse-halo tail the ratio-128 "
                "cap removes at depth ≤ 15; pass an explicit "
                "coarse_depth to trade memory for accuracy", depth,
                2 ** (depth - coarse_depth))
    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], dtype=bool)
    # Active blocks beyond the static budget are silently dropped by the
    # discovery scatter (holes in the surface). The discovery pass counts
    # TRUE active blocks regardless of the budget, so overflow is detected
    # right after setup — BEFORE the expensive coarse+CG solves — and the
    # band is rebuilt with an enlarged budget (1.25× observed suffices).
    for attempt in range(3):
        (rhs, W, nbr, block_valid, block_coords, density,
         flat, w, cfound, origin, scale, n_blocks) = _setup_sparse(
            points, normals, valid, 2 ** depth, max_blocks,
            jnp.float32(screen))
        nb_host = int(n_blocks)
        if nb_host <= max_blocks:
            break
        if attempt == 2:
            raise RuntimeError(
                f"sparse Poisson depth={depth}: active blocks ({nb_host}) "
                f"still exceed the budget ({max_blocks}) after retries")
        log.warning(
            "sparse Poisson depth=%d: %d active blocks exceed the budget "
            "of %d — rebuilding the band with a larger budget", depth,
            nb_host, max_blocks)
        max_blocks = int(nb_host * 1.25) + 1024
        est_gb = max_blocks * BS ** 3 * 4 * 8 / 1e9
        if est_gb > 8.0:
            log.warning(
                "sparse Poisson depth=%d: the retried band needs ~%.1f GB "
                "of solver state (%d blocks) — deep depths on dense "
                "clouds are memory-hungry by nature (the reference's "
                "octree warns the same way at depth > 16); consider a "
                "shallower depth or a downsampled cloud", depth, est_gb,
                max_blocks)
    # Coarse dense solve (its own launch — the dense grid and CG state die
    # before the band phases allocate), then the separable prolongation.
    # rtol forwards: the coarse chi becomes the fine band's Dirichlet
    # halo, so coarse accuracy bounds what the caller's rtol can buy.
    rc = 2 ** min(coarse_depth, depth)
    dense_x0 = None
    if x0 is not None and isinstance(x0, dense_poisson.PoissonGrid):
        # Dense preview grid (streaming finalize): it warm-starts the
        # INTERNAL COARSE solve — the band seed then prolongs from a
        # coarse field that converged in fewer iterations; overlaying
        # a coarser preview onto the fine band directly would only
        # degrade the prolongation it replaces.
        dense_x0, x0 = x0, None
    if dense_x0 is not None:
        x0c = _resample_chi_to_coarse(
            jnp.asarray(dense_x0.chi, jnp.float32),
            jnp.asarray(dense_x0.origin, jnp.float32),
            jnp.asarray(dense_x0.scale, jnp.float32), points, valid, rc)
        coarse, coarse_used = dense_poisson._solve(
            points, normals, valid, x0c, rc, coarse_iters,
            jnp.float32(screen), rtol=rtol, warm=True)
    else:
        # warm=False: the cold-start zeros grid allocates INSIDE the
        # jitted solve (hoisting it pinned an extra non-donated rc³
        # operand for the whole coarse phase — see dense_poisson.
        # _solve).
        coarse, coarse_used = dense_poisson._solve(
            points, normals, valid, jnp.zeros((), jnp.float32),
            rc, coarse_iters, jnp.float32(screen), rtol=rtol,
            warm=False)
    b, seed = _prolong_band(coarse.chi, rhs, nbr, block_valid,
                            block_coords, 2 ** depth,
                            2 ** min(coarse_depth, depth))
    warm_blocks = 0
    if dense_x0 is not None:
        warm_blocks = _dense_cover_blocks(block_coords, block_valid,
                                          origin, scale, dense_x0)
        log.info("sparse Poisson depth=%d: dense preview grid warm-"
                 "started the %d^3 coarse solve (%d/%d iterations, "
                 "%d band blocks covered)", depth, rc, int(coarse_used),
                 coarse_iters, warm_blocks)
    if x0 is not None:
        if not isinstance(x0, SparsePoissonGrid):
            raise TypeError(
                f"x0 must be a SparsePoissonGrid from a previous "
                f"reconstruct_sparse call (or a dense poisson."
                f"PoissonGrid preview), got {type(x0).__name__}")
        seed, warm_blocks = _warm_start_seed(
            seed, x0, block_coords, block_valid, origin, scale,
            2 ** depth)
        if warm_blocks:
            log.info("sparse Poisson depth=%d: warm start seeded %d "
                     "band block(s) from the previous grid", depth,
                     warm_blocks)
    if preconditioner == "jacobi":
        chi, cg_used = _cg_sparse(b, W, seed, nbr, block_valid, cg_iters,
                                  jnp.float32(rtol))
    else:
        # Coarse screen for the preconditioner's coarse operator: the
        # coarse grid's own normalized-density screen — the SAME helper
        # dense_poisson._solve applies internally, recomputed from the
        # density field the coarse solve already returns.
        coarse_W = dense_poisson.screen_weights(coarse.density,
                                                jnp.float32(screen))
        om = params.smooth_omega
        chi, cg_used = _pcg_sparse(
            b, W, seed, nbr, block_valid, block_coords, coarse_W,
            2 ** depth, 2 ** min(coarse_depth, depth), cg_iters,
            rtol=jnp.float32(rtol), precond=preconditioner,
            precond_coarse_iters=params.precond_coarse_iters,
            smooth_omega=None if om is None else jnp.float32(om),
            cheby_lmin=jnp.float32(params.cheby_lmin),
            cheby_lmax=jnp.float32(params.cheby_lmax),
            cheby_degree=params.cheby_degree,
            fine_dtype=fine_dtype)
    log.info("sparse Poisson depth=%d: fine CG (%s) stopped after %d/%d "
             "iterations", depth, preconditioner, int(cg_used), cg_iters)
    iso = _iso_sparse(chi, density, flat, w, cfound, valid)
    grid = SparsePoissonGrid(chi, density, block_coords, block_valid,
                             iso, origin, scale, 2 ** depth, nbr=nbr)
    if with_stats:
        return grid, n_blocks, {"cg_iters_used": int(cg_used),
                                "coarse_iters_used": int(coarse_used),
                                "preconditioner": preconditioner,
                                "fine_dtype": fine_dtype,
                                "warm_start_blocks": warm_blocks}
    return grid, n_blocks
