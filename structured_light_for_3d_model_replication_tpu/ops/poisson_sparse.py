"""Block-sparse screened-Poisson solve — depth 9-12 within HBM.

The dense solver (:mod:`.poisson`) is the right shape for TPU up to 256³
(depth 8), but the reference's octree path runs at depth 10 by default and
accepts up to 16 (`server/processing.py:207-208,293`); a dense 1024³ grid
is 4 GB per field and CG needs ~7 fields. This module recovers the
octree's adaptivity with a TPU-idiomatic structure: a **two-level scheme**
over a dense coarse grid plus a **block-sparse fine band**.

1. **Coarse solve**: the existing dense screened-Poisson at
   ``min(depth, coarse_depth)`` — gives the global interior/exterior
   field far from the surface (exactly the role of an octree's shallow
   nodes).
2. **Active band**: the set of 8³ voxel blocks within one block of any
   sample, found with one sort-unique over 27-dilated block keys — static
   capacity ``max_blocks``, padded, shape-stable.
3. **Fine solve**: splat, divergence and screened-Laplacian CG run ONLY
   on the band, stored as ``(M, 8, 8, 8)`` brick tensors. Cross-block
   stencil halos come from a precomputed (M, 6) neighbor table; at the
   band boundary the halo is a **Dirichlet condition prolonged from the
   coarse solution** (folded into the RHS once, so the CG operator is
   halo-free). The coarse solution also seeds ``x0``, so the fine CG only
   refines the band.
4. Iso level and density trimming gather from the sparse bricks; marching
   extraction (:func:`.marching.extract_sparse`) walks only active
   blocks.

Memory at depth 10 on a 1M-point surface scan: ~10⁵ active blocks →
~50M voxels → ~200 MB per field, an order of magnitude under the dense
grid, with identical numerics inside the band.

Everything is jit-compiled with static ``(resolution, max_blocks,
cg_iters)``; block discovery, splat and halo exchange are sorts, segment
ops and gathers — no pointer chasing.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import poisson as dense_poisson

BS = 8                       # voxels per block edge
_KEY_BITS = 10               # per-axis block-coordinate bits (≤ depth 13)
_KEY_MAX = (1 << _KEY_BITS) - 1
# Plain Python int (a module-level jnp value would initialize the XLA
# backend at import, breaking jax.distributed for multi-host users).
_BIG = 1 << 30               # sentinel key: sorts after every real block


class SparsePoissonGrid(NamedTuple):
    """Band-sparse solve result; extraction input for ``extract_sparse``."""

    chi: jnp.ndarray           # (M, BS, BS, BS) float32
    density: jnp.ndarray       # (M, BS, BS, BS) float32 splat density
    block_coords: jnp.ndarray  # (M, 3) int32 block coords (padded rows big)
    block_valid: jnp.ndarray   # (M,) bool
    iso: jnp.ndarray           # () float32
    origin: jnp.ndarray        # (3,) world position of voxel (0,0,0) center
    scale: jnp.ndarray         # () world size of one fine voxel
    resolution: int            # static: fine voxels per axis


def _pack(bc: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) block coords → packed int32 key (coords must be in range)."""
    return ((bc[..., 0] << (2 * _KEY_BITS)) | (bc[..., 1] << _KEY_BITS)
            | bc[..., 2])


def _unpack(key: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([key >> (2 * _KEY_BITS),
                      (key >> _KEY_BITS) & _KEY_MAX,
                      key & _KEY_MAX], axis=-1)


def _lookup(block_keys: jnp.ndarray, key: jnp.ndarray):
    """Sorted-key → slot index. Returns (slot, found) with slot clamped."""
    m = block_keys.shape[0]
    pos = jnp.searchsorted(block_keys, key).astype(jnp.int32)
    pos_c = jnp.minimum(pos, m - 1)
    return pos_c, block_keys[pos_c] == key


@functools.partial(jax.jit,
                   static_argnames=("resolution", "max_blocks", "cg_iters",
                                    "coarse_resolution", "coarse_iters"))
def _solve_sparse(points, normals, valid, resolution: int, max_blocks: int,
                  cg_iters: int, screen, coarse_resolution: int,
                  coarse_iters: int):
    R = resolution
    nb_axis = R // BS
    n = points.shape[0]

    grid_pts, origin, scale = dense_poisson.normalize_points(points, valid, R)

    # ------------------------------------------------------------------
    # Coarse dense solve (same world cube: coords differ by a pure ratio).
    # ------------------------------------------------------------------
    coarse = dense_poisson._solve(points, normals, valid, coarse_resolution,
                                  coarse_iters, screen)
    c_ratio = (coarse_resolution - 1.0) / (R - 1.0)

    # ------------------------------------------------------------------
    # Active band: 27-dilated block keys of every sample, sort-unique into
    # max_blocks static slots (ascending keys; surplus blocks dropped).
    # ------------------------------------------------------------------
    pblock = jnp.clip((grid_pts // BS).astype(jnp.int32), 0, nb_axis - 1)
    offs = jnp.asarray([(dx, dy, dz) for dx in (-1, 0, 1)
                        for dy in (-1, 0, 1) for dz in (-1, 0, 1)],
                       jnp.int32)
    cand = pblock[:, None, :] + offs[None, :, :]          # (N, 27, 3)
    in_rng = jnp.all((cand >= 0) & (cand < nb_axis), axis=-1)
    keys = jnp.where(in_rng & valid[:, None], _pack(cand), _BIG).reshape(-1)

    sk = jnp.sort(keys)
    first = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
    new = first & (sk < _BIG)
    rank = jnp.cumsum(new.astype(jnp.int32)) - 1
    n_blocks = jnp.sum(new.astype(jnp.int32))
    slot_of = jnp.where(new & (rank < max_blocks), rank, max_blocks)
    block_keys = jnp.full((max_blocks + 1,), _BIG,
                          jnp.int32).at[slot_of].set(
        jnp.where(new, sk, _BIG))[:max_blocks]
    block_valid = block_keys < _BIG
    block_coords = jnp.where(block_valid[:, None], _unpack(block_keys),
                             jnp.int32(nb_axis + 1))
    m = max_blocks

    # Neighbor table (M, 6): slots of the ±x/±y/±z blocks (m → "absent").
    units = jnp.asarray([[1, 0, 0], [-1, 0, 0], [0, 1, 0],
                         [0, -1, 0], [0, 0, 1], [0, 0, -1]], jnp.int32)
    nb_coords = block_coords[:, None, :] + units[None]     # (M, 6, 3)
    nb_ok = jnp.all((nb_coords >= 0) & (nb_coords < nb_axis), axis=-1)
    nb_slot, nb_found = _lookup(block_keys, _pack(jnp.clip(nb_coords, 0,
                                                           _KEY_MAX)))
    nbr = jnp.where(nb_ok & nb_found & block_valid[:, None], nb_slot, m)

    # ------------------------------------------------------------------
    # Sparse trilinear splat of [normals, 1] into the bricks.
    # ------------------------------------------------------------------
    g = jnp.clip(grid_pts, 0.0, R - 1 - 1e-4)
    i0 = jnp.floor(g).astype(jnp.int32)
    f = g - i0
    corners = jnp.asarray([[dx, dy, dz] for dx in (0, 1) for dy in (0, 1)
                           for dz in (0, 1)], jnp.int32)
    vidx = jnp.clip(i0[:, None, :] + corners[None], 0, R - 1)  # (N, 8, 3)
    cb = vidx // BS
    intra = vidx - cb * BS
    cslot, cfound = _lookup(block_keys, _pack(cb))
    cf = corners[None].astype(jnp.float32)
    w = jnp.prod(cf * f[:, None, :] + (1 - cf) * (1 - f[:, None, :]),
                 axis=-1)
    w = w * (valid[:, None] & cfound).astype(jnp.float32)
    flat = (cslot * BS * BS * BS
            + (intra[..., 0] * BS + intra[..., 1]) * BS + intra[..., 2])
    vals = jnp.concatenate([normals, jnp.ones((n, 1), jnp.float32)], -1)
    contrib = w[..., None] * vals[:, None, :]              # (N, 8, 4)
    acc = jnp.zeros((m * BS**3 + 1, 4), jnp.float32)
    acc = acc.at[jnp.where(cfound, flat, m * BS**3).reshape(-1)].add(
        contrib.reshape(-1, 4))[:-1]
    bricks = acc.reshape(m, BS, BS, BS, 4)
    V = bricks[..., :3]
    density = bricks[..., 3]

    # ------------------------------------------------------------------
    # Halo'd stencils over the band.
    # ------------------------------------------------------------------
    def haloed(x, dirichlet=None):
        """(M,8,8,8) → (M,10,10,10) with face halos from neighbors;
        absent neighbors use ``dirichlet`` (M,6,8,8) or zero."""
        xp = jnp.concatenate([x, jnp.zeros((1, BS, BS, BS), x.dtype)])
        H = jnp.zeros((m, BS + 2, BS + 2, BS + 2), x.dtype)
        H = H.at[:, 1:-1, 1:-1, 1:-1].set(x)
        face_src = [  # neighbor slot axis face → our halo face
            (0, xp[nbr[:, 0], 0, :, :], (slice(None), BS + 1,
                                         slice(1, -1), slice(1, -1))),
            (1, xp[nbr[:, 1], BS - 1, :, :], (slice(None), 0,
                                              slice(1, -1), slice(1, -1))),
            (2, xp[nbr[:, 2], :, 0, :], (slice(None), slice(1, -1),
                                         BS + 1, slice(1, -1))),
            (3, xp[nbr[:, 3], :, BS - 1, :], (slice(None), slice(1, -1),
                                              0, slice(1, -1))),
            (4, xp[nbr[:, 4], :, :, 0], (slice(None), slice(1, -1),
                                         slice(1, -1), BS + 1)),
            (5, xp[nbr[:, 5], :, :, BS - 1], (slice(None), slice(1, -1),
                                              slice(1, -1), 0)),
        ]
        for fidx, vals_f, dst in face_src:
            have = (nbr[:, fidx] < m)[:, None, None]
            if dirichlet is not None:
                fill = jnp.where(have, vals_f, dirichlet[:, fidx])
            else:
                fill = jnp.where(have, vals_f, 0.0)
            H = H.at[dst].set(fill)
        return H

    def lap_band(x, dirichlet=None):
        H = haloed(x, dirichlet)
        c = H[:, 1:-1, 1:-1, 1:-1]
        return (H[:, 2:, 1:-1, 1:-1] + H[:, :-2, 1:-1, 1:-1]
                + H[:, 1:-1, 2:, 1:-1] + H[:, 1:-1, :-2, 1:-1]
                + H[:, 1:-1, 1:-1, 2:] + H[:, 1:-1, 1:-1, :-2]
                - 6.0 * c)

    def div_band(Vb):
        out = jnp.zeros((m, BS, BS, BS), jnp.float32)
        for axis in range(3):
            H = haloed(Vb[..., axis])
            sl = [slice(None), slice(1, -1), slice(1, -1), slice(1, -1)]
            hi = list(sl)
            lo = list(sl)
            hi[axis + 1] = slice(2, None)
            lo[axis + 1] = slice(0, -2)
            out = out + 0.5 * (H[tuple(hi)] - H[tuple(lo)])
        return out

    rhs = div_band(V)

    wmean = jnp.sum(density) / jnp.maximum(
        jnp.sum((density > 0).astype(jnp.float32)), 1.0)
    W = screen * density / jnp.maximum(wmean, 1e-12)

    # Voxel centers of every brick voxel, in fine grid coords.
    vox = jnp.arange(BS, dtype=jnp.int32)
    bx = block_coords[:, 0, None, None, None] * BS + vox[:, None, None]
    by = block_coords[:, 1, None, None, None] * BS + vox[None, :, None]
    bz = block_coords[:, 2, None, None, None] * BS + vox[None, None, :]
    vox_xyz = jnp.stack(jnp.broadcast_arrays(bx, by, bz), -1).astype(
        jnp.float32)                                       # (M,8,8,8,3)

    def prolong(coords_xyz):
        """Trilinear sample of the coarse chi at fine-grid coords, chunked:
        a flat gather would materialize (M·8³, 8, 3) corner-index tensors —
        tens of GB at a 10⁵-block band."""
        flat = coords_xyz.reshape(-1, 3)
        rows = flat.shape[0]
        chunk = 1 << 21
        pad = (-rows) % chunk
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad, 3), flat.dtype)])
        parts = flat.reshape(-1, chunk, 3)
        vals = jax.lax.map(
            lambda c: dense_poisson.gather(coarse.chi, c * c_ratio), parts)
        return vals.reshape(-1)[:rows].reshape(coords_xyz.shape[:-1])

    x0 = jnp.where(block_valid[:, None, None, None], prolong(vox_xyz), 0.0)

    # Dirichlet halo values for chi at absent-neighbor faces (the halo
    # voxel = face voxel + unit step, prolonged from the coarse solution).
    face_coords = []
    for fidx in range(6):
        ax = fidx // 2
        sl = [slice(None)] * 4
        sl[ax + 1] = BS - 1 if fidx % 2 == 0 else 0
        fc = vox_xyz[tuple(sl)]                            # (M, 8, 8, 3)
        face_coords.append(fc + units[fidx].astype(jnp.float32))
    dir_chi = jnp.stack([prolong(fc) for fc in face_coords], 1)  # (M,6,8,8)
    dir_chi = jnp.where(block_valid[:, None, None, None], dir_chi, 0.0)

    # Fold the constant Dirichlet halo into the RHS once:
    #   A(x; halo) = A0(x) + L_halo  ⇒  solve A0 x = b − L_halo.
    halo_term = lap_band(jnp.zeros_like(x0), dirichlet=dir_chi)

    def A0(x):
        return lap_band(x) - W * x

    band = block_valid[:, None, None, None]

    def matvec(x):
        return jnp.where(band, -(A0(x)), 0.0)

    b = jnp.where(band, -(rhs - halo_term), 0.0)
    r0 = b - matvec(x0)
    p0 = r0
    rs0 = jnp.vdot(r0, r0)

    def body(_, state):
        x, r, p, rs = state
        Ap = matvec(p)
        alpha = rs / jnp.maximum(jnp.vdot(p, Ap), 1e-30)
        x = x + alpha * p
        r = r - alpha * Ap
        rs_new = jnp.vdot(r, r)
        beta = rs_new / jnp.maximum(rs, 1e-30)
        p = r + beta * p
        return x, r, p, rs_new

    chi, _, _, _ = jax.lax.fori_loop(0, cg_iters, body, (x0, r0, p0, rs0))
    chi = jnp.where(band, chi, 0.0)

    # Iso level: density-weighted mean of chi at the samples, gathered
    # from the bricks (8 trilinear corners per sample).
    cflat = chi.reshape(-1)
    dflat = density.reshape(-1)
    ok8 = cfound & valid[:, None]
    w8 = w  # already masked by validity & found
    chi_pts = jnp.sum(jnp.where(ok8, cflat[flat], 0.0) * w8, axis=1)
    den_pts = jnp.sum(jnp.where(ok8, dflat[flat], 0.0) * w8, axis=1)
    iso = jnp.sum(chi_pts * den_pts) / jnp.maximum(
        jnp.sum(den_pts), 1e-12)

    return SparsePoissonGrid(chi, density, block_coords, block_valid,
                             iso, origin, scale, R), n_blocks


def reconstruct_sparse(points, normals, valid=None, depth: int = 10,
                       cg_iters: int = 200, screen: float = 4.0,
                       max_blocks: int = 131_072, coarse_depth: int = 7,
                       coarse_iters: int = 300):
    """Band-sparse screened Poisson at depth 9-12 (module docstring).

    Matches the reference's octree-Poisson role at its default depth 10
    (`server/processing.py:293`); depth > 12 is rejected the way the
    reference rejects > 16 (`server/processing.py:207-208`) — 4096³ virtual
    grids exceed the band budget this scheme targets.
    """
    if depth > 12:
        raise ValueError(f"depth={depth} > 12: the band-sparse solver is "
                         "bounded at 4096³ virtual resolution (the "
                         "reference similarly guards depth > 16)")
    if 2 ** depth < 4 * BS:
        raise ValueError(f"depth={depth} too shallow for the block solver; "
                         "use ops.poisson.reconstruct")
    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], dtype=bool)
    grid, n_blocks = _solve_sparse(
        points, normals, valid, 2 ** depth, max_blocks, cg_iters,
        jnp.float32(screen), 2 ** min(coarse_depth, depth), coarse_iters)
    return grid, n_blocks
