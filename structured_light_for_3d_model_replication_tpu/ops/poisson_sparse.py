"""Block-sparse screened-Poisson solve — depth 9-12 within HBM.

The dense solver (:mod:`.poisson`) is the right shape for TPU up to 256³
(depth 8), but the reference's octree path runs at depth 10 by default and
accepts up to 16 (`server/processing.py:207-208,293`); a dense 1024³ grid
is 4 GB per field and CG needs ~7 fields. This module recovers the
octree's adaptivity with a TPU-idiomatic structure: a **two-level scheme**
over a dense coarse grid plus a **block-sparse fine band**.

1. **Coarse solve**: the existing dense screened-Poisson at
   ``min(depth, coarse_depth)`` — gives the global interior/exterior
   field far from the surface (exactly the role of an octree's shallow
   nodes).
2. **Active band**: the set of 8³ voxel blocks within one block of any
   sample, found with one sort-unique over 27-dilated block keys — static
   capacity ``max_blocks``, padded, shape-stable.
3. **Fine solve**: splat, divergence and screened-Laplacian CG run ONLY
   on the band, stored as ``(M, 8, 8, 8)`` brick tensors. Cross-block
   stencil halos come from a precomputed (M, 6) neighbor table; at the
   band boundary the halo is a **Dirichlet condition prolonged from the
   coarse solution** (folded into the RHS once, so the CG operator is
   halo-free). The coarse solution also seeds ``x0``, so the fine CG only
   refines the band.
4. Iso level and density trimming gather from the sparse bricks; marching
   extraction (:func:`.marching.extract_sparse`) walks only active
   blocks.

Memory at depth 10 on a 1M-point surface scan: ~10⁵ active blocks →
~50M voxels → ~200 MB per field, an order of magnitude under the dense
grid, with identical numerics inside the band.

Everything is jit-compiled with static ``(resolution, max_blocks,
cg_iters)``; block discovery, splat and halo exchange are sorts, segment
ops and gathers — no pointer chasing.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import poisson as dense_poisson

BS = 8                       # voxels per block edge
_KEY_BITS = 10               # per-axis block-coordinate bits (≤ depth 13)
_KEY_MAX = (1 << _KEY_BITS) - 1
# Plain Python int (a module-level jnp value would initialize the XLA
# backend at import, breaking jax.distributed for multi-host users).
_BIG = 1 << 30               # sentinel key: sorts after every real block


class SparsePoissonGrid(NamedTuple):
    """Band-sparse solve result; extraction input for ``extract_sparse``.

    Brick fields are stored FLAT as (M, BS³): a materialized (M,8,8,8)
    tensor pads 16× under the TPU's (8,128) tile (the last dim 8 rounds to
    128) — flat bricks tile exactly. 3-D views exist only transiently
    inside the stencil computations."""

    chi: jnp.ndarray           # (M, BS³) float32
    density: jnp.ndarray       # (M, BS³) float32 splat density
    block_coords: jnp.ndarray  # (M, 3) int32 block coords (padded rows big)
    block_valid: jnp.ndarray   # (M,) bool
    iso: jnp.ndarray           # () float32
    origin: jnp.ndarray        # (3,) world position of voxel (0,0,0) center
    scale: jnp.ndarray         # () world size of one fine voxel
    resolution: int            # static: fine voxels per axis


def _pack(bc: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) block coords → packed int32 key (coords must be in range)."""
    return ((bc[..., 0] << (2 * _KEY_BITS)) | (bc[..., 1] << _KEY_BITS)
            | bc[..., 2])


def _unpack(key: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([key >> (2 * _KEY_BITS),
                      (key >> _KEY_BITS) & _KEY_MAX,
                      key & _KEY_MAX], axis=-1)


def _lookup(block_keys: jnp.ndarray, key: jnp.ndarray):
    """Sorted-key → slot index. Returns (slot, found) with slot clamped."""
    m = block_keys.shape[0]
    pos = jnp.searchsorted(block_keys, key).astype(jnp.int32)
    pos_c = jnp.minimum(pos, m - 1)
    return pos_c, block_keys[pos_c] == key


# ---------------------------------------------------------------------------
# Flat-space stencils. EVERYTHING stays (M, BS³): on TPU any materialized
# (…, 8, 8) / (…, 10, 10) trailing shape pads to the (8, 128) tile — 13-16×
# memory blowup, the OOM that killed the first three layouts of this solver.
# In flat index space (idx = (ix·8 + iy)·8 + iz) the 7-point stencil is six
# rolls (±1, ±8, ±64) under boundary masks, and cross-brick faces are
# static-index gathers from the neighbor brick's flat row.
# ---------------------------------------------------------------------------

import numpy as _np

_FLAT_IDX = _np.arange(BS ** 3)
_FIZ = _FLAT_IDX % BS
_FIY = (_FLAT_IDX // BS) % BS
_FIX = _FLAT_IDX // (BS * BS)

# Direction order MATCHES the neighbor-table column order (units):
# +x, -x, +y, -y, +z, -z.
_DIRS = []
for _ax, (_coord, _stride) in enumerate(
        ((_FIX, BS * BS), (_FIY, BS), (_FIZ, 1))):
    for _sign in (+1, -1):
        _interior = (_coord < BS - 1) if _sign > 0 else (_coord > 0)
        _at_face = ~_interior
        # Neighbor-brick source index for our face positions: the same
        # (other two coords), opposite wall on the stepped axis.
        _src = _FLAT_IDX - _sign * _stride * (BS - 1)
        # Dirichlet face map: dir_chi stores each face as the (a, b) plane
        # of the two non-stepped axes, flattened a*8+b in vox order.
        _others = [c for c in (_FIX, _FIY, _FIZ)
                   if c is not _coord]
        _face_map = _others[0] * BS + _others[1]
        _DIRS.append((
            _sign * _stride,
            _interior.astype(_np.float32),
            _at_face.astype(_np.float32),
            _np.where(_at_face, _src, 0).astype(_np.int32),
            _np.where(_at_face, _face_map, 0).astype(_np.int32),
        ))


def _dir_consts(d):
    delta, interior, at_face, src, fmap = _DIRS[d]
    return (delta, jnp.asarray(interior), jnp.asarray(at_face),
            jnp.asarray(src), jnp.asarray(fmap))


def _neighbor_sum(x, nbr, dirichlet=None):
    """Σ over the 6 neighbors of each voxel, flat (M, BS³) in and out.
    ``dirichlet`` (M, 6, BS²) supplies values past absent-neighbor faces
    (None → zero)."""
    m = x.shape[0]
    xpad = jnp.concatenate([x, jnp.zeros((1, BS ** 3), x.dtype)])
    acc = jnp.zeros_like(x)
    for d in range(6):
        delta, interior, at_face, src, fmap = _dir_consts(d)
        inner = jnp.roll(x, -delta, axis=1) * interior
        xn = xpad[nbr[:, d]]                       # (M, BS³) neighbor brick
        face_vals = jnp.take(xn, src, axis=1)
        if dirichlet is not None:
            have = (nbr[:, d] < m)[:, None]
            dvals = jnp.take(dirichlet[:, d], fmap, axis=1)
            face_vals = jnp.where(have, face_vals, dvals)
        acc = acc + inner + face_vals * at_face
    return acc


def _lap_band_flat(x, nbr, dirichlet=None):
    return _neighbor_sum(x, nbr, dirichlet) - 6.0 * x


def _div_band_flat(Vflat, nbr):
    """Central-difference divergence; ``Vflat`` is (M, BS³, 3) (zero
    Dirichlet — the splat support never reaches the band edge)."""
    m = Vflat.shape[0]
    out = jnp.zeros((m, BS ** 3), jnp.float32)
    for ax in range(3):
        x = Vflat[..., ax]
        xpad = jnp.concatenate([x, jnp.zeros((1, BS ** 3), x.dtype)])
        vals = []
        for d in (2 * ax, 2 * ax + 1):             # +axis, −axis
            delta, interior, at_face, src, _ = _dir_consts(d)
            inner = jnp.roll(x, -delta, axis=1) * interior
            xn = xpad[nbr[:, d]]
            vals.append(inner + jnp.take(xn, src, axis=1) * at_face)
        out = out + 0.5 * (vals[0] - vals[1])
    return out


# The solve runs as FOUR jitted programs (band+splat → prolong → CG →
# iso) instead of one: a single program held the splat accumulator, the
# prolongation temporaries (the (M,8³,3) voxel-center tensor and six face
# stacks), the V field AND the CG state live simultaneously — compile-time
# HBM peaked 1.3-1.5 GB over a 16 GB chip at a 10⁵-block band. Between
# separate launches each phase's temporaries are freed before the next
# phase's exist.


@functools.partial(jax.jit,
                   static_argnames=("resolution", "max_blocks"))
def _setup_sparse(points, normals, valid, resolution: int, max_blocks: int,
                  screen):
    R = resolution
    nb_axis = R // BS
    n = points.shape[0]

    grid_pts, origin, scale = dense_poisson.normalize_points(points, valid, R)

    # Active band: 27-dilated block keys of every sample, sort-unique into
    # max_blocks static slots (ascending keys; surplus blocks dropped).
    pblock = jnp.clip((grid_pts // BS).astype(jnp.int32), 0, nb_axis - 1)
    offs = jnp.asarray([(dx, dy, dz) for dx in (-1, 0, 1)
                        for dy in (-1, 0, 1) for dz in (-1, 0, 1)],
                       jnp.int32)
    cand = pblock[:, None, :] + offs[None, :, :]          # (N, 27, 3)
    in_rng = jnp.all((cand >= 0) & (cand < nb_axis), axis=-1)
    keys = jnp.where(in_rng & valid[:, None], _pack(cand), _BIG).reshape(-1)

    sk = jnp.sort(keys)
    first = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
    new = first & (sk < _BIG)
    rank = jnp.cumsum(new.astype(jnp.int32)) - 1
    n_blocks = jnp.sum(new.astype(jnp.int32))
    slot_of = jnp.where(new & (rank < max_blocks), rank, max_blocks)
    block_keys = jnp.full((max_blocks + 1,), _BIG,
                          jnp.int32).at[slot_of].set(
        jnp.where(new, sk, _BIG))[:max_blocks]
    block_valid = block_keys < _BIG
    block_coords = jnp.where(block_valid[:, None], _unpack(block_keys),
                             jnp.int32(nb_axis + 1))
    m = max_blocks

    # Neighbor table (M, 6): slots of the ±x/±y/±z blocks (m → "absent").
    units = jnp.asarray([[1, 0, 0], [-1, 0, 0], [0, 1, 0],
                         [0, -1, 0], [0, 0, 1], [0, 0, -1]], jnp.int32)
    nb_coords = block_coords[:, None, :] + units[None]     # (M, 6, 3)
    nb_ok = jnp.all((nb_coords >= 0) & (nb_coords < nb_axis), axis=-1)
    nb_slot, nb_found = _lookup(block_keys, _pack(jnp.clip(nb_coords, 0,
                                                           _KEY_MAX)))
    nbr = jnp.where(nb_ok & nb_found & block_valid[:, None], nb_slot, m)

    # Sparse trilinear splat of [normals, 1] into the bricks.
    g = jnp.clip(grid_pts, 0.0, R - 1 - 1e-4)
    i0 = jnp.floor(g).astype(jnp.int32)
    f = g - i0
    corners = jnp.asarray([[dx, dy, dz] for dx in (0, 1) for dy in (0, 1)
                           for dz in (0, 1)], jnp.int32)
    vidx = jnp.clip(i0[:, None, :] + corners[None], 0, R - 1)  # (N, 8, 3)
    cb = vidx // BS
    intra = vidx - cb * BS
    cslot, cfound = _lookup(block_keys, _pack(cb))
    cf = corners[None].astype(jnp.float32)
    w = jnp.prod(cf * f[:, None, :] + (1 - cf) * (1 - f[:, None, :]),
                 axis=-1)
    w = w * (valid[:, None] & cfound).astype(jnp.float32)
    flat = (cslot * BS * BS * BS
            + (intra[..., 0] * BS + intra[..., 1]) * BS + intra[..., 2])
    vals = jnp.concatenate([normals, jnp.ones((n, 1), jnp.float32)], -1)
    contrib = w[..., None] * vals[:, None, :]              # (N, 8, 4)
    acc = jnp.zeros((m * BS**3 + 1, 4), jnp.float32)
    acc = acc.at[jnp.where(cfound, flat, m * BS**3).reshape(-1)].add(
        contrib.reshape(-1, 4))[:-1]
    V = acc[:, :3].reshape(m, BS ** 3, 3)
    density = acc[:, 3].reshape(m, BS**3)

    rhs = _div_band_flat(V, nbr)

    wmean = jnp.sum(density) / jnp.maximum(
        jnp.sum((density > 0).astype(jnp.float32)), 1.0)
    W = screen * density / jnp.maximum(wmean, 1e-12)

    return (rhs, W, nbr, block_valid, block_coords, density,
            flat, w, cfound, origin, scale, n_blocks)


@functools.partial(jax.jit, static_argnames=("coarse_resolution",
                                             "coarse_iters", "resolution"))
def _prolong_sparse(points, normals, valid, rhs, nbr, block_valid,
                    block_coords, screen, resolution: int,
                    coarse_resolution: int, coarse_iters: int):
    """Coarse dense solve + its prolongation onto the band: the CG seed
    ``x0`` and the Dirichlet-halo-folded RHS ``b``."""
    R = resolution
    coarse = dense_poisson._solve(points, normals, valid, coarse_resolution,
                                  coarse_iters, screen)
    c_ratio = (coarse_resolution - 1.0) / (R - 1.0)
    units = jnp.asarray([[1, 0, 0], [-1, 0, 0], [0, 1, 0],
                         [0, -1, 0], [0, 0, 1], [0, 0, -1]], jnp.int32)

    # Voxel centers of every brick voxel, in fine grid coords.
    vox = jnp.arange(BS, dtype=jnp.int32)
    bx = block_coords[:, 0, None, None, None] * BS + vox[:, None, None]
    by = block_coords[:, 1, None, None, None] * BS + vox[None, :, None]
    bz = block_coords[:, 2, None, None, None] * BS + vox[None, None, :]
    vox_xyz = jnp.stack(jnp.broadcast_arrays(bx, by, bz), -1).astype(
        jnp.float32)                                       # (M,8,8,8,3)

    def prolong(coords_xyz):
        """Trilinear sample of the coarse chi at fine-grid coords, chunked:
        a flat gather would materialize (M·8³, 8, 3) corner-index tensors —
        tens of GB at a 10⁵-block band."""
        flat_c = coords_xyz.reshape(-1, 3)
        rows = flat_c.shape[0]
        chunk = 1 << 21
        pad = (-rows) % chunk
        if pad:
            flat_c = jnp.concatenate(
                [flat_c, jnp.zeros((pad, 3), flat_c.dtype)])
        parts = flat_c.reshape(-1, chunk, 3)
        vals_c = jax.lax.map(
            lambda c: dense_poisson.gather(coarse.chi, c * c_ratio), parts)
        return vals_c.reshape(-1)[:rows].reshape(coords_xyz.shape[:-1])

    m = block_coords.shape[0]
    x0 = jnp.where(block_valid[:, None],
                   prolong(vox_xyz).reshape(m, BS ** 3), 0.0)

    # Dirichlet halo values for chi at absent-neighbor faces (the halo
    # voxel = face voxel + unit step, prolonged from the coarse solution).
    face_coords = []
    for fidx in range(6):
        ax = fidx // 2
        sl = [slice(None)] * 4
        sl[ax + 1] = BS - 1 if fidx % 2 == 0 else 0
        fc = vox_xyz[tuple(sl)]                            # (M, 8, 8, 3)
        face_coords.append(fc + units[fidx].astype(jnp.float32))
    dir_chi = jnp.stack(
        [prolong(fc).reshape(m, BS * BS) for fc in face_coords], 1)
    dir_chi = jnp.where(block_valid[:, None, None], dir_chi, 0.0)

    # Fold the constant Dirichlet halo into the RHS once:
    #   A(x; halo) = A0(x) + L_halo  ⇒  solve A0 x = b − L_halo.
    halo_term = _lap_band_flat(jnp.zeros_like(x0), nbr, dirichlet=dir_chi)
    band = block_valid[:, None]
    b = jnp.where(band, -(rhs - halo_term), 0.0)
    return b, x0


@functools.partial(jax.jit, static_argnames=("cg_iters",))
def _cg_sparse(b, W, x0, nbr, block_valid, cg_iters: int):
    """All CG state is FLAT (M, BS³): the fori_loop carry materializes
    with the buffer layout, and a (…,8,8,8) carry pads 16× under the
    (8,128) tile — the 16 GB allocation that originally OOM'd this
    solve."""
    band = block_valid[:, None]

    def matvec(xf):
        out = _lap_band_flat(xf, nbr) - W * xf
        return jnp.where(band, -out, 0.0)

    r0 = b - matvec(x0)
    p0 = r0
    rs0 = jnp.vdot(r0, r0)

    def body(_, state):
        x, r, p, rs = state
        Ap = matvec(p)
        alpha = rs / jnp.maximum(jnp.vdot(p, Ap), 1e-30)
        x = x + alpha * p
        r = r - alpha * Ap
        rs_new = jnp.vdot(r, r)
        beta = rs_new / jnp.maximum(rs, 1e-30)
        p = r + beta * p
        return x, r, p, rs_new

    chi, _, _, _ = jax.lax.fori_loop(0, cg_iters, body, (x0, r0, p0, rs0))
    return jnp.where(band, chi, 0.0)  # (M, BS³) flat


@jax.jit
def _iso_sparse(chi, density, flat, w, cfound, valid):
    """Density-weighted mean of chi at the samples (8 trilinear corners
    per sample, gathered from the bricks)."""
    cflat = chi.reshape(-1)
    dflat = density.reshape(-1)
    ok8 = cfound & valid[:, None]
    chi_pts = jnp.sum(jnp.where(ok8, cflat[flat], 0.0) * w, axis=1)
    den_pts = jnp.sum(jnp.where(ok8, dflat[flat], 0.0) * w, axis=1)
    return jnp.sum(chi_pts * den_pts) / jnp.maximum(jnp.sum(den_pts), 1e-12)


def reconstruct_sparse(points, normals, valid=None, depth: int = 10,
                       cg_iters: int = 200, screen: float = 4.0,
                       max_blocks: int = 131_072, coarse_depth: int = 7,
                       coarse_iters: int = 300):
    """Band-sparse screened Poisson at depth 9-12 (module docstring).

    Matches the reference's octree-Poisson role at its default depth 10
    (`server/processing.py:293`); depth > 12 is rejected the way the
    reference rejects > 16 (`server/processing.py:207-208`) — 4096³ virtual
    grids exceed the band budget this scheme targets.
    """
    if depth > 12:
        raise ValueError(f"depth={depth} > 12: the band-sparse solver is "
                         "bounded at 4096³ virtual resolution (the "
                         "reference similarly guards depth > 16)")
    if 2 ** depth < 4 * BS:
        raise ValueError(f"depth={depth} too shallow for the block solver; "
                         "use ops.poisson.reconstruct")
    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], dtype=bool)
    (rhs, W, nbr, block_valid, block_coords, density,
     flat, w, cfound, origin, scale, n_blocks) = _setup_sparse(
        points, normals, valid, 2 ** depth, max_blocks,
        jnp.float32(screen))
    b, x0 = _prolong_sparse(points, normals, valid, rhs, nbr, block_valid,
                            block_coords, jnp.float32(screen), 2 ** depth,
                            2 ** min(coarse_depth, depth), coarse_iters)
    chi = _cg_sparse(b, W, x0, nbr, block_valid, cg_iters)
    iso = _iso_sparse(chi, density, flat, w, cfound, valid)
    grid = SparsePoissonGrid(chi, density, block_coords, block_valid,
                             iso, origin, scale, 2 ** depth)
    return grid, n_blocks
