"""Pallas TPU kernel: fused distance + running-argmin nearest neighbor.

The k=1 correspondence sweep is ICP's wall-clock floor (`registration.icp`
— 30+ annealed iterations, each a full M×N squared-distance field). The
XLA path (`ops/knn.py`, k==1 running argmin) materializes the (M, N)
distance matrix in HBM and reads it back for the argmin reduction — XProf
measured ~3 GB of round-trip traffic per ICP iteration on the 23-edge
ring (~0.5 s of the 24-stop scan, `fusion.137` + `iota_reduce_fusion.5`).

This kernel keeps the whole distance tile in VMEM: the key table streams
in ONCE per query tile ((3, N) transposed so the point dimension rides
the 128-lane axis instead of padding 3 → 128), distances are computed
chunk by chunk on the MXU, and only the per-query (d², argmin) pair ever
reaches HBM. Key validity is folded into the precomputed ‖p‖² term
(+inf for invalid keys) so the kernel needs no mask input and no
branches.

Used by `registration.icp` / `information_matrix` on TPU backends
(`jax.default_backend() in ("tpu", "axon")` — the same gating as
`ops/decode_pallas`); the XLA path remains the oracle elsewhere.
Replaces the Open3D KDTree correspondence search of the reference's
`registration_icp` (`server/processing.py:154-156`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import _backend

_TQ = 1024      # queries per grid step: (TQ, KC) f32 distance tile in VMEM
_KC = 1024      # keys per chunk
# Index bits packed into the low distance mantissa (see kernel): bounds the
# key count. Plain Python ints (a module-level jnp value would be captured
# as a trace constant, which pallas kernels reject — and would also
# initialize the XLA backend at import time).
_IDX_BITS = 13
_IDX_MASK = (1 << _IDX_BITS) - 1


def available() -> bool:
    """Mosaic kernels are TPU-only ('axon' = the tunneled dev TPU)."""
    return _backend.tpu_backend()


def max_keys() -> int:
    return 1 << _IDX_BITS


def _nn1_kernel(q_ref, kt_ref, p2v_ref, d2_ref, idx_ref, *, n_keys: int):
    """Packed running min: the key index rides the low 13 mantissa bits of
    the (nonnegative) squared distance, so the whole argmin is ONE min
    reduction per chunk with no index operand — measured ~1.6× the best
    two-operand variant and ~2× the XLA path. Distances quantize to ~2⁻¹⁰
    relative; a k=1 correspondence only flips between near-equidistant
    keys, which every consumer tolerates (ICP already ran bf16×3 dots)."""
    q = q_ref[0]                                   # (TQ, 3)
    best = jnp.full((_TQ, 1), jnp.inf, jnp.float32)
    qx = q[:, 0:1]
    qy = q[:, 1:2]
    qz = q[:, 2:3]
    for c in range(n_keys // _KC):                 # static unroll
        kp = kt_ref[0, :, c * _KC:(c + 1) * _KC]   # (3, KC)
        p2v = p2v_ref[0, :, c * _KC:(c + 1) * _KC] # (1, KC), +inf = invalid
        # Exact f32 distances on the VPU (an MXU dot here rounds inputs
        # to bf16 — measured d² errors ~1e-2 relative at mm scale, enough
        # to flip ~20% of argmins vs the fp32 oracle).
        dx = qx - kp[0:1, :]
        dy = qy - kp[1:2, :]
        dz = qz - kp[2:3, :]
        dd = dx * dx + dy * dy + dz * dz           # (TQ, KC)
        # Floor at a small NORMAL float: a denormal packed value could be
        # flushed to zero by the VPU, dropping the embedded index.
        dd = jnp.maximum(dd, 1e-30)
        bits = jax.lax.bitcast_convert_type(dd, jnp.int32)
        ids = (jax.lax.broadcasted_iota(jnp.int32, (_TQ, _KC), 1)
               + c * _KC)
        pk = (bits & ~jnp.int32(_IDX_MASK)) | ids
        # Invalid keys: +inf from p2v → packed stays +inf (index dropped),
        # sorting after every finite distance.
        pk = jnp.where(jnp.isfinite(p2v),
                       jax.lax.bitcast_convert_type(pk, jnp.float32),
                       jnp.inf)
        best = jnp.minimum(best, jnp.min(pk, axis=1, keepdims=True))
    tb = jax.lax.bitcast_convert_type(best, jnp.int32)
    d2_ref[0, 0, :] = jnp.where(
        jnp.isfinite(best[:, 0]),
        jax.lax.bitcast_convert_type(tb[:, 0] & ~jnp.int32(_IDX_MASK),
                                     jnp.float32),
        jnp.inf)
    idx_ref[0, 0, :] = jnp.minimum(tb[:, 0] & _IDX_MASK, n_keys - 1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def nearest_one(queries: jnp.ndarray, keys_t: jnp.ndarray,
                p2v: jnp.ndarray, interpret: bool = False):
    """(M, 3) queries × transposed (3, N) keys → (d² (M,), idx (M,)).

    ``p2v`` is the precomputed per-key ‖p‖² with +inf at invalid keys —
    callers that sweep the SAME key set repeatedly (every ICP iteration)
    build it once via :func:`key_table`. Rows with no valid key return
    d² = +inf (callers mask on it). Indices are clamped into range so
    downstream gathers stay in bounds.
    """
    m = queries.shape[0]
    n = keys_t.shape[1]
    if n % _KC:
        raise ValueError(f"key count {n} must be a multiple of {_KC}; "
                         "pad via key_table()")
    if n > max_keys():
        raise ValueError(f"key count {n} exceeds the packed-index budget "
                         f"({max_keys()}); use ops.knn for larger sweeps")
    m_pad = ((m + _TQ - 1) // _TQ) * _TQ
    if m_pad != m:
        queries = jnp.concatenate(
            [queries, jnp.zeros((m_pad - m, 3), queries.dtype)])
    grid = m_pad // _TQ
    d2, idx = pl.pallas_call(
        functools.partial(_nn1_kernel, n_keys=n),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, _TQ, 3), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 3, n), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, 1, n), lambda i: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, _TQ), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, _TQ), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid, 1, _TQ), jnp.float32),
            jax.ShapeDtypeStruct((grid, 1, _TQ), jnp.int32),
        ],
        interpret=interpret,
    )(queries.reshape(grid, _TQ, 3), keys_t[None], p2v[None])
    return d2.reshape(m_pad)[:m], idx.reshape(m_pad)[:m]


def key_table(points: jnp.ndarray, valid: jnp.ndarray | None = None):
    """Precompute the kernel's key-side operands from an (N, 3) cloud:
    (keys_t (3, N'), p2v (1, N')) with N' padded to the chunk multiple
    and padding/invalid keys carrying ‖p‖² = +inf."""
    n = points.shape[0]
    if valid is None:
        valid = jnp.ones(n, dtype=bool)
    pad = (-n) % _KC
    pts = jnp.asarray(points, jnp.float32)
    if pad:
        pts = jnp.concatenate([pts, jnp.zeros((pad, 3), jnp.float32)])
        valid = jnp.concatenate([valid, jnp.zeros(pad, dtype=bool)])
    p2 = jnp.sum(pts * pts, axis=1)
    p2v = jnp.where(valid, p2, jnp.inf)[None, :]
    return pts.T, p2v
