"""Tile-binned Gaussian-splat rasterizer (XLA form = the CPU oracle).

The appearance tier's renderer (`splat/`, docs/RENDERING.md): anisotropic
3D Gaussians anchored on the TSDF shell are projected to screen-space
conics (the EWA recipe), binned into fixed-size image tiles, depth-sorted
front-to-back per tile and alpha-composited — the Gaussian-Plus-SDF /
3DGS rendering model restated under this repo's static-shape discipline:

* every shape is fixed by ``(splat capacity, RenderConfig)`` — the splat
  count, the camera pose and the view angles are all TRACED, so a render
  sweep over arbitrary azimuth/elevation reuses ONE compiled program per
  resolution (the serve render endpoint's zero-steady-state-recompile
  bar);
* tile binning is a dense (tiles, splats) overlap mask + ``lax.top_k``
  by depth — the prefix-sum-compaction spirit of `ops/marching_jax.py`
  (bounded static capacities, never a host hash), with the K nearest
  splats per tile kept and the far tail truncated (K is generous:
  ``RenderConfig.max_per_tile``);
* the per-tile composite exists twice with one numerical contract: the
  vectorized XLA form below (differentiable — the fit loop in
  `splat/fit.py` rides its gradients) and the fused Pallas kernel
  (:mod:`.splat_render_pallas`) behind ``_backend.tpu_backend()``,
  pinned against each other in tests/test_splat.py.

Camera model: pinhole ``u = fx·x/z + cx`` after the world→camera rigid
map ``x = R_wc (p − eye)`` — :func:`orbit_camera` reproduces the `viz`
orbit conventions (y-up turntable, image +v down) so rendered previews
and ``cli view`` agree on framing, and :func:`stop_camera` turns a
session stop pose into the same tuple for fitting against captured RGB.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as _np

import jax
import jax.numpy as jnp

from . import _backend
from ..utils.log import get_logger

log = get_logger(__name__)

#: Background matches viz.BACKGROUND so mixed mesh/splat previews read
#: as one family.
BG_DEFAULT = (18, 20, 26)


class RenderConfig(NamedTuple):
    """Static (program-keying) half of a render: one compiled program
    per distinct config — resolution changes recompile, angles never.

    ``tile``/``max_per_tile`` trade memory for depth capacity: each
    tile composites its ``max_per_tile`` NEAREST splats (truncating the
    far tail), so a tile must be small enough that K covers the front
    surface across the tile's whole AREA — a coarse tile over a dense
    cloud keeps K splats clustered at its closest corner and leaves the
    rest showing background (the failure mode the 8-px default
    avoids)."""

    width: int = 384
    height: int = 288
    tile: int = 8              # square pixel tiles
    max_per_tile: int = 128    # K nearest splats composited per tile
    bg: tuple = BG_DEFAULT     # RGB 0-255

    @property
    def tiles_x(self) -> int:
        return -(-self.width // self.tile)

    @property
    def tiles_y(self) -> int:
        return -(-self.height // self.tile)


# ---------------------------------------------------------------------------
# Cameras (host-side helpers; outputs are plain arrays, traced by render)
# ---------------------------------------------------------------------------


def orbit_camera(lo, hi, azim_deg: float, elev_deg: float,
                 width: int, height: int, zoom: float = 2.1,
                 fov_scale: float = 1.15):
    """Orbit pinhole around the bbox ``[lo, hi]`` — the `viz`
    ``_orbit_camera`` conventions (y-up axis, image +v down) expressed
    as the ``(R_wc, eye, fx, fy, cx, cy)`` tuple :func:`render` takes.
    Angles are plain floats: they land in TRACED operands, so a sweep
    never recompiles."""
    lo = _np.asarray(lo, _np.float64)
    hi = _np.asarray(hi, _np.float64)
    center = 0.5 * (lo + hi)
    radius = max(float(_np.linalg.norm(hi - lo)) * 0.5, 1e-6)
    dist = zoom * radius
    az = _np.deg2rad(azim_deg)
    el = _np.deg2rad(elev_deg)
    off = _np.array([_np.sin(az) * _np.cos(el), _np.sin(el),
                     -_np.cos(az) * _np.cos(el)])
    eye = center + dist * off
    fwd = center - eye
    fwd /= _np.linalg.norm(fwd)
    up = _np.array([0.0, -1.0, 0.0])
    right = _np.cross(fwd, up)
    nr = _np.linalg.norm(right)
    right = _np.array([1.0, 0.0, 0.0]) if nr < 1e-9 else right / nr
    dn = _np.cross(fwd, right)
    R = _np.stack([right, -dn, fwd])
    f = fov_scale * min(width, height) * 0.5
    return (R.astype(_np.float32), eye.astype(_np.float32),
            _np.float32(f), _np.float32(f),
            _np.float32((width - 1) * 0.5), _np.float32((height - 1) * 0.5))


def stop_camera(pose, fx, fy, cx, cy):
    """A session stop's camera as a render tuple: ``pose`` is the stop's
    camera→model 4×4 (the decode frame has the camera at the origin), so
    world→camera is its inverse rigid map."""
    pose = _np.asarray(pose, _np.float64)
    R = pose[:3, :3].T
    eye = pose[:3, 3]
    return (R.astype(_np.float32), eye.astype(_np.float32),
            _np.float32(fx), _np.float32(fy), _np.float32(cx),
            _np.float32(cy))


# ---------------------------------------------------------------------------
# Projection + binning + composite (one jitted program per (S, cfg, path))
# ---------------------------------------------------------------------------


def _project(means, normals, log_scales, colors_sh, opacity, valid,
             R_wc, eye, fx, fy, cx, cy, cfg: RenderConfig):
    """World splats → screen records: (u, v, z, conic(a,b,c), color,
    alpha₀, visible). All (S,)-shaped; EWA projection of the anisotropic
    covariance built on the splat's normal frame."""
    n = normals / jnp.maximum(
        jnp.linalg.norm(normals, axis=-1, keepdims=True), 1e-9)
    helper = jnp.where(jnp.abs(n[:, 2:3]) < 0.9,
                       jnp.asarray([0.0, 0.0, 1.0], jnp.float32),
                       jnp.asarray([1.0, 0.0, 0.0], jnp.float32))
    t1 = jnp.cross(n, helper)
    t1 = t1 / jnp.maximum(jnp.linalg.norm(t1, axis=-1, keepdims=True),
                          1e-9)
    t2 = jnp.cross(n, t1)
    basis = jnp.stack([t1, t2, n], axis=-1)            # (S, 3, 3) columns
    s = jnp.exp(log_scales)                            # (S, 3)

    x = (means - eye[None, :]) @ R_wc.T                # (S, 3) camera
    z = x[:, 2]
    in_front = z > 1e-6
    zs = jnp.where(in_front, z, 1.0)
    u = fx * x[:, 0] / zs + cx
    v = fy * x[:, 1] / zs + cy

    # EWA: Σ2d = J (R B) diag(s²) (R B)ᵀ Jᵀ, J the projective Jacobian.
    A = (R_wc @ basis) * s[:, None, :]                 # (S, 3, 3)
    j00 = fx / zs
    j11 = fy / zs
    j02 = -fx * x[:, 0] / (zs * zs)
    j12 = -fy * x[:, 1] / (zs * zs)
    # Rows of J @ A: (S, 3) each.
    r0 = j00[:, None] * A[:, 0, :] + j02[:, None] * A[:, 2, :]
    r1 = j11[:, None] * A[:, 1, :] + j12[:, None] * A[:, 2, :]
    c00 = jnp.sum(r0 * r0, axis=-1) + 0.3              # 0.3 px low-pass
    c11 = jnp.sum(r1 * r1, axis=-1) + 0.3
    c01 = jnp.sum(r0 * r1, axis=-1)
    det = c00 * c11 - c01 * c01
    inv_det = 1.0 / jnp.maximum(det, 1e-12)
    conic_a = c11 * inv_det
    conic_b = -c01 * inv_det
    conic_c = c00 * inv_det
    mid = 0.5 * (c00 + c11)
    lam = mid + jnp.sqrt(jnp.maximum(mid * mid - det, 0.1))
    radius = jnp.ceil(3.0 * jnp.sqrt(lam))

    # Degree-1 SH on the per-splat viewing direction (the 3DGS recipe).
    d = means - eye[None, :]
    d = d / jnp.maximum(jnp.linalg.norm(d, axis=-1, keepdims=True), 1e-9)
    color = colors_sh[:, 0, :] + jnp.einsum(
        "skc,sk->sc", colors_sh[:, 1:4, :], d)          # (S, 3)
    alpha0 = jax.nn.sigmoid(opacity)

    W, H = cfg.width, cfg.height
    visible = (valid & in_front & (det > 1e-12)
               & (u + radius > 0) & (u - radius < W)
               & (v + radius > 0) & (v - radius < H))
    return u, v, z, radius, conic_a, conic_b, conic_c, color, alpha0, \
        visible


def _bin_tiles(u, v, z, radius, visible, cfg: RenderConfig):
    """(tiles, K) nearest-first splat indices + membership mask: a dense
    tile×splat overlap test, then ``top_k`` on −depth — static shapes
    throughout (the bounded-capacity rule)."""
    T = cfg.tile
    tx = jnp.arange(cfg.tiles_x, dtype=jnp.float32) * T
    ty = jnp.arange(cfg.tiles_y, dtype=jnp.float32) * T
    x0 = jnp.tile(tx, cfg.tiles_y)                     # (NT,)
    y0 = jnp.repeat(ty, cfg.tiles_x)
    member = (visible[None, :]
              & (u[None, :] + radius[None, :] >= x0[:, None])
              & (u[None, :] - radius[None, :] < x0[:, None] + T)
              & (v[None, :] + radius[None, :] >= y0[:, None])
              & (v[None, :] - radius[None, :] < y0[:, None] + T))
    key = jnp.where(member, z[None, :], jnp.inf)
    k = min(cfg.max_per_tile, key.shape[1])  # tiny scenes: K ≤ S
    neg, idx = jax.lax.top_k(-key, k)                  # nearest K first
    ok = jnp.isfinite(neg)
    return idx, ok, x0, y0


def _composite_xla(u, v, ca, cb, cc, cr, cg, cbl, opa, ok, x0, y0,
                   cfg: RenderConfig):
    """Front-to-back alpha composite of the per-tile records — the
    differentiable oracle the Pallas kernel is pinned against.

    All record arrays are (NT, K); returns (NT, T², 3) premultiplied
    color and (NT, T²) alpha."""
    T = cfg.tile
    px = jnp.tile(jnp.arange(T, dtype=jnp.float32), T)       # (T²,)
    py = jnp.repeat(jnp.arange(T, dtype=jnp.float32), T)
    gx = x0[:, None] + px[None, :]                           # (NT, T²)
    gy = y0[:, None] + py[None, :]
    dx = gx[:, :, None] - u[:, None, :]                      # (NT, T², K)
    dy = gy[:, :, None] - v[:, None, :]
    power = -0.5 * (ca[:, None, :] * dx * dx
                    + cc[:, None, :] * dy * dy) \
        - cb[:, None, :] * dx * dy
    g = jnp.exp(jnp.minimum(power, 0.0))
    alpha = jnp.clip(opa[:, None, :] * g, 0.0, 0.995) \
        * ok[:, None, :].astype(jnp.float32)
    # Exclusive cumulative transmittance along the (sorted) K axis.
    trans = jnp.cumprod(1.0 - alpha, axis=-1)
    trans = jnp.concatenate(
        [jnp.ones_like(trans[..., :1]), trans[..., :-1]], axis=-1)
    w = trans * alpha                                        # (NT, T², K)
    rgb = jnp.stack([jnp.sum(w * c[:, None, :], axis=-1)
                     for c in (cr, cg, cbl)], axis=-1)
    a_out = 1.0 - jnp.prod(1.0 - alpha, axis=-1)
    return rgb, a_out


@functools.partial(jax.jit,
                   static_argnames=("cfg", "use_pallas", "interpret"))
def _render_fn(means, normals, log_scales, colors_sh, opacity, valid,
               R_wc, eye, fx, fy, cx, cy, cfg: RenderConfig,
               use_pallas: bool = False, interpret: bool = False):
    """Full render at static (S, cfg): returns ((H, W, 3) float 0–1,
    (H, W) alpha). One program per config — see module docstring."""
    (u, v, z, radius, ca, cb, cc, color, alpha0,
     visible) = _project(means, normals, log_scales, colors_sh, opacity,
                         valid, R_wc, eye, fx, fy, cx, cy, cfg)
    idx, ok, x0, y0 = _bin_tiles(u, v, z, radius, visible, cfg)

    def take(a):
        # Sanitize unselected slots to zeros at the gather: a masked-out
        # splat may carry arbitrary (even non-finite) values, and
        # ``0 · NaN`` downstream would poison the whole tile.
        return jnp.where(ok, jnp.take(a, idx, axis=0), 0.0)   # (NT, K)

    recs = (take(u), take(v), take(ca), take(cb), take(cc),
            take(jnp.clip(color[:, 0], 0.0, 1.0)),
            take(jnp.clip(color[:, 1], 0.0, 1.0)),
            take(jnp.clip(color[:, 2], 0.0, 1.0)), take(alpha0), ok)
    if use_pallas:
        from . import splat_render_pallas

        rgb, a_out = splat_render_pallas.composite_pallas(
            *recs, x0, y0, cfg, interpret=interpret)
    else:
        rgb, a_out = _composite_xla(*recs, x0, y0, cfg)

    # Tile sheet → image crop + background blend.
    TY, TX, T = cfg.tiles_y, cfg.tiles_x, cfg.tile
    sheet = rgb.reshape(TY, TX, T, T, 3).transpose(0, 2, 1, 3, 4)
    img = sheet.reshape(TY * T, TX * T, 3)[:cfg.height, :cfg.width]
    a_sheet = a_out.reshape(TY, TX, T, T).transpose(0, 2, 1, 3)
    a_img = a_sheet.reshape(TY * T, TX * T)[:cfg.height, :cfg.width]
    bg = jnp.asarray(cfg.bg, jnp.float32) / 255.0
    img = img + (1.0 - a_img)[..., None] * bg[None, None, :]
    return img, a_img


def render(means, normals, log_scales, colors_sh, opacity, valid,
           camera, cfg: RenderConfig = RenderConfig(),
           use_pallas: bool | None = None):
    """Render one view; ``camera`` is an ``(R_wc, eye, fx, fy, cx, cy)``
    tuple (:func:`orbit_camera` / :func:`stop_camera`). Returns
    ``((H, W, 3) float32 0–1, (H, W) float32 alpha)`` device arrays.

    ``use_pallas=None`` auto-dispatches the fused tile-composite kernel
    on TPU backends; the XLA form is the CPU path AND the gradient path
    (`splat/fit.py` always fits through it)."""
    if use_pallas is None:
        use_pallas = _backend.tpu_backend()
    R_wc, eye, fx, fy, cx, cy = camera
    return _render_fn(
        jnp.asarray(means, jnp.float32), jnp.asarray(normals, jnp.float32),
        jnp.asarray(log_scales, jnp.float32),
        jnp.asarray(colors_sh, jnp.float32),
        jnp.asarray(opacity, jnp.float32), jnp.asarray(valid, bool),
        jnp.asarray(R_wc, jnp.float32), jnp.asarray(eye, jnp.float32),
        jnp.asarray(fx, jnp.float32), jnp.asarray(fy, jnp.float32),
        jnp.asarray(cx, jnp.float32), jnp.asarray(cy, jnp.float32),
        cfg, bool(use_pallas))


def to_uint8(img) -> _np.ndarray:
    """(H, W, 3) float 0–1 → host uint8 image (the PNG writer's input)."""
    return _np.clip(_np.round(_np.asarray(img) * 255.0), 0,
                    255).astype(_np.uint8)
