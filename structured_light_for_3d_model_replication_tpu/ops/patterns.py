"""Gray-code pattern generation.

The reference builds Gray codes with a recursive string generator and a Python
loop over bit-planes (`server/sl_system.py:44-86`). Here the whole stack is one
vectorized expression: ``g = i ^ (i >> 1)`` per projector column/row, then a
broadcasted bit-extraction over all planes at once — a single fused XLA kernel.

Frame protocol (must match the reference's on-disk numbering,
`server/sl_system.py:133-150`): frame 0 = white, frame 1 = black, then for each
column bit MSB-first a (pattern, inverse) pair, then the same for row bits.
1920x1080 => 46 frames.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..config import ProjectorConfig


def gray_code(x: jnp.ndarray) -> jnp.ndarray:
    """Binary-reflected Gray code of integer array x."""
    return x ^ (x >> 1)


def gray_to_binary(g: jnp.ndarray, n_bits: int) -> jnp.ndarray:
    """Inverse of :func:`gray_code` via doubling XOR shifts.

    Replaces the reference's per-bit iterative XOR loop
    (`server/sl_system.py:567-570`) with log2(n_bits) whole-array XORs.
    """
    b = g
    shift = 1
    while shift < n_bits:
        b = b ^ (b >> shift)
        shift *= 2
    return b


def bit_planes(n: int, n_bits: int, downsample: int = 1) -> jnp.ndarray:
    """(n_bits, n) uint8 array: Gray-code bit b (MSB-first) of each COARSE index.

    With downsampling the projected code is the Gray code of idx//downsample —
    coarser stripes, fewer planes (reference D_SAMPLE_PROJ semantics,
    `server/sl_system.py:144-146`): n_bits must be the coarse bit count.
    """
    idx = jnp.arange(n, dtype=jnp.int32) // downsample
    g = gray_code(idx)
    shifts = jnp.arange(n_bits - 1, -1, -1, dtype=jnp.int32)[:, None]
    return ((g[None, :] >> shifts) & 1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def pattern_stack(
    width: int,
    height: int,
    col_bits: int,
    row_bits: int,
    brightness: int = 200,
    downsample: int = 1,
) -> jnp.ndarray:
    """Full projector frame stack, shape (n_frames, height, width) uint8.

    Layout: [white, black, colbit0, ~colbit0, ..., rowbit0, ~rowbit0, ...].
    """
    b = jnp.uint8(brightness)
    white = jnp.full((1, height, width), b, dtype=jnp.uint8)
    black = jnp.zeros((1, height, width), dtype=jnp.uint8)

    cols = bit_planes(width, col_bits, downsample)  # (cb, W)
    col_pat = (cols[:, None, None, :] * b).astype(jnp.uint8)  # (cb,1,1,W)
    col_pat = jnp.broadcast_to(col_pat, (col_bits, 1, height, width))
    col_inv = (b - col_pat).astype(jnp.uint8)
    col_frames = jnp.concatenate([col_pat, col_inv], axis=1)  # (cb, 2, H, W)
    col_frames = col_frames.reshape(2 * col_bits, height, width)

    rows = bit_planes(height, row_bits, downsample)  # (rb, H)
    row_pat = (rows[:, None, :, None] * b).astype(jnp.uint8)
    row_pat = jnp.broadcast_to(row_pat, (row_bits, 1, height, width))
    row_inv = (b - row_pat).astype(jnp.uint8)
    row_frames = jnp.concatenate([row_pat, row_inv], axis=1)
    row_frames = row_frames.reshape(2 * row_bits, height, width)

    return jnp.concatenate([white, black, col_frames, row_frames], axis=0)


def pattern_stack_for(proj: ProjectorConfig) -> jnp.ndarray:
    return pattern_stack(
        proj.width,
        proj.height,
        proj.col_bits,
        proj.row_bits,
        proj.brightness,
        proj.downsample,
    )
