"""Structured logging for the framework.

The reference logs with bare ``print`` throughout (`server/sl_system.py:490,
574-576`, `server/server.py:35,51,73,91` — emoji-tagged console lines). Here
every module gets a namespaced stdlib logger with one process-wide
configuration point, an opt-in JSON-lines mode for machine consumption, and an
env override (``SL_TPU_LOG=debug``) so benchmark runs can be silenced or
traced without code edits.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_CONFIGURED = False
ROOT_NAME = "structured_light_for_3d_model_replication_tpu"


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def configure(level: str | int | None = None, json_lines: bool = False,
              stream=None) -> None:
    """Configure the framework's root logger (idempotent; call again to
    reconfigure). Level resolution order: arg > $SL_TPU_LOG > INFO."""
    global _CONFIGURED
    if level is None:
        level = os.environ.get("SL_TPU_LOG", "info")
    if isinstance(level, str):
        level = getattr(logging, level.upper(), logging.INFO)
    root = logging.getLogger(ROOT_NAME)
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    if json_lines or os.environ.get("SL_TPU_LOG_JSON"):
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s",
            datefmt="%H:%M:%S"))
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    """Namespaced logger; lazily configures defaults on first use."""
    if not _CONFIGURED:
        configure()
    if not name.startswith(ROOT_NAME):
        name = f"{ROOT_NAME}.{name}"
    return logging.getLogger(name)
