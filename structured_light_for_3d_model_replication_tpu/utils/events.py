"""Flight recorder: a correlated, bounded journal of structured events.

Logs answer "what did the process print"; the flight recorder answers
"what happened to THIS scan/job, in order, just before it died". It is a
thread-safe ring buffer of structured :class:`Event` records, each
stamped with wall + monotonic time, a severity, and whatever correlation
fields (``scan_id``/``job_id``/``stop``/…) were ambient when it was
recorded:

* :func:`context` — a ``contextvars``-scoped correlation context.
  ``with events.context(scan_id=sid, stop=3): ...`` tags every event
  (and, via `utils.trace`, every span) recorded inside the block. Worker
  threads establish their own context (contextvars are per-thread), so
  concurrent jobs never cross-tag.
* :func:`record` — append one event to the global recorder. O(1), lock
  + deque append; cheap enough for per-frame retry paths.
* **dump-on-fault** — :class:`~..health.ScanFault` construction calls
  :func:`fault` (see `health.py`), so every taxonomy raise — capture
  retry exhaustion, gate rejection, serve containment — lands in the
  journal with its correlation fields; when a dump directory is
  configured (:func:`set_dump_dir` or ``SL_TPU_FLIGHT_DUMP_DIR``), the
  last-N events that led to the fault are written as JSONL next to it.

The ring is bounded by construction (default 4096 events): a week-long
serve process pays a fixed few MB, never a leak. Severity counts are
mirrored into the metrics registry (``sl_events_total{severity=…}``) so
a fault burst is visible on /metrics even after the ring has wrapped.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import json
import os
import threading
import time

from .log import get_logger

log = get_logger(__name__)

#: Severities, least to most alarming. "fault" is reserved for taxonomy
#: raises (ScanFault construction) — the dump-on-fault trigger.
SEVERITIES = ("debug", "info", "warning", "error", "fault")

_CONTEXT: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "sl_event_context", default=())


@contextlib.contextmanager
def context(**fields):
    """Push correlation fields (``scan_id=…``, ``job_id=…``, ``stop=…``)
    for the dynamic extent of the block. Nested contexts merge; inner
    wins on key collisions. Events AND tracer spans recorded inside pick
    the fields up automatically."""
    merged = dict(_CONTEXT.get())
    merged.update({k: v for k, v in fields.items() if v is not None})
    token = _CONTEXT.set(tuple(sorted(merged.items())))
    try:
        yield
    finally:
        _CONTEXT.reset(token)


def current_context() -> dict:
    """The ambient correlation fields (empty dict outside any context)."""
    return dict(_CONTEXT.get())


@dataclasses.dataclass
class Event:
    """One journal entry. ``t_wall`` is epoch seconds (humans, cross-host
    correlation); ``t_mono`` is monotonic (robust ordering/latency on one
    host, same clock as tracer spans)."""

    kind: str
    severity: str
    message: str
    t_wall: float
    t_mono: float
    thread: str
    fields: dict

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "severity": self.severity,
            "message": self.message,
            "t_wall": round(self.t_wall, 6),
            "t_mono": round(self.t_mono, 6),
            "thread": self.thread,
            **({"fields": self.fields} if self.fields else {}),
        }


#: Sentinel: no explicit dump-dir choice — fall back to the env var.
_ENV_DUMP = object()


def _jsonable(v):
    """Coerce a correlation value to something json.dumps accepts —
    events must never be the thing that crashes a failing pipeline."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    try:
        return str(v)
    except Exception:
        return "<unprintable>"


class FlightRecorder:
    """Thread-safe bounded ring buffer of :class:`Event` records."""

    def __init__(self, capacity: int = 4096, registry=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: collections.deque[Event] = collections.deque(
            maxlen=capacity)
        self._dropped = 0          # events evicted by the ring bound
        # Lifetime tally per severity, independent of the ring bound —
        # the source consumers (serve's /metrics sync) read deltas from.
        self._severity_counts: dict[str, int] = {}
        # _ENV_DUMP = "defer to SL_TPU_FLIGHT_DUMP_DIR"; None = dumps
        # explicitly disabled (set_dump_dir(None) must win over the env).
        self._dump_dir: "str | None | object" = _ENV_DUMP
        self._dump_min_interval_s = 1.0
        self._last_dump_mono = -float("inf")
        self._dump_seq = 0
        self._registry = registry  # None = resolve trace.REGISTRY lazily

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, message: str = "", severity: str = "info",
               **fields) -> Event:
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {severity!r}")
        merged = current_context()
        # None-valued kwargs are "no value", same as in context(): they
        # must not mask an ambient correlation field.
        merged.update({k: v for k, v in fields.items() if v is not None})
        ev = Event(kind=str(kind), severity=severity, message=str(message),
                   t_wall=time.time(), t_mono=time.monotonic(),
                   thread=threading.current_thread().name,
                   fields={k: _jsonable(v) for k, v in merged.items()})
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(ev)
            self._severity_counts[severity] = \
                self._severity_counts.get(severity, 0) + 1
        self._count(severity)
        return ev

    def severity_counts(self) -> dict[str, int]:
        """Lifetime {severity: events recorded} — survives ring wrap."""
        with self._lock:
            return dict(self._severity_counts)

    def _count(self, severity: str) -> None:
        try:
            reg = self._registry
            if reg is None:
                from . import trace
                reg = trace.REGISTRY
            reg.counter("sl_events_total",
                        "flight-recorder events by severity",
                        severity=severity).inc()
        except Exception as e:  # metrics must never break recording
            log.debug("event severity counter unavailable: %s", e)

    def fault(self, exc: BaseException, **fields) -> Event:
        """Record a taxonomy raise and, for genuine faults, write the
        journal that led to it (when a dump directory is configured).

        The exception chooses its own journal severity via a
        ``flight_severity`` class attribute (default "fault"):
        designed-for flow control like serve's backpressure rejections
        declares "warning", so an overload burst neither wraps the ring
        past the real fault history nor storms the dump directory —
        only severity="fault" events trigger dumps."""
        taxonomy = [c.__name__ for c in type(exc).__mro__
                    if c not in (object, BaseException, Exception,
                                 RuntimeError)]
        severity = getattr(exc, "flight_severity", "fault")
        ev = self.record("fault", message=str(exc), severity=severity,
                         exc_type=type(exc).__name__,
                         taxonomy=",".join(taxonomy), **fields)
        if severity == "fault":
            self._maybe_dump(ev)
        return ev

    # -- dump-on-fault -----------------------------------------------------

    def set_dump_dir(self, path: str | None,
                     min_interval_s: float = 1.0) -> None:
        """Enable (or disable with None — this overrides the
        ``SL_TPU_FLIGHT_DUMP_DIR`` env var, which only applies while no
        explicit choice has been made) journal dumps on fault events.
        ``min_interval_s`` rate-limits a fault storm to one file per
        interval — the journal each dump carries covers the storm."""
        with self._lock:
            self._dump_dir = path
            self._dump_min_interval_s = float(min_interval_s)
            self._last_dump_mono = -float("inf")

    def _resolve_dump_dir(self) -> str | None:
        if self._dump_dir is _ENV_DUMP:
            return os.environ.get("SL_TPU_FLIGHT_DUMP_DIR") or None
        return self._dump_dir

    def _maybe_dump(self, ev: Event) -> str | None:
        with self._lock:
            dump_dir = self._resolve_dump_dir()
            if not dump_dir:
                return None
            now = time.monotonic()
            if now - self._last_dump_mono < self._dump_min_interval_s:
                return None
            self._dump_seq += 1
            seq = self._dump_seq
        path = os.path.join(
            dump_dir, f"flight_{os.getpid()}_{seq:04d}.jsonl")
        try:
            os.makedirs(dump_dir, exist_ok=True)
            self.dump(path)
        except OSError as e:
            # The rate-limit slot is only consumed on SUCCESS: a failed
            # write (permissions, disk full) must not suppress the next
            # fault's journal for the whole interval.
            log.warning("flight journal dump to %s failed: %s", path, e)
            return None
        with self._lock:
            self._last_dump_mono = time.monotonic()
        log.warning("flight journal dumped to %s (%s: %s)", path,
                    ev.fields.get("exc_type", "fault"), ev.message)
        return path

    # -- inspection --------------------------------------------------------

    def tail(self, n: int | None = None,
             kind: str | None = None) -> list[Event]:
        """Last ``n`` events, optionally restricted to one ``kind``
        (exact match) — the filter behind ``GET /events?kind=`` and the
        soak bench's eviction/recovery assertions. The kind filter
        applies BEFORE the tail bound, so `tail(8, kind="job_terminal")`
        is the last 8 terminals, not terminals among the last 8 events."""
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e.kind == kind]
        return evs if n is None else evs[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def to_jsonl(self, n: int | None = None,
                 kind: str | None = None) -> str:
        lines = [json.dumps(e.to_dict()) for e in self.tail(n, kind=kind)]
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self, path: str, n: int | None = None) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl(n))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0


# ---------------------------------------------------------------------------
# Global default recorder (mirrors trace.GLOBAL / trace.REGISTRY)
# ---------------------------------------------------------------------------

RECORDER = FlightRecorder()
record = RECORDER.record
fault = RECORDER.fault
tail = RECORDER.tail
to_jsonl = RECORDER.to_jsonl
dump = RECORDER.dump
set_dump_dir = RECORDER.set_dump_dir
