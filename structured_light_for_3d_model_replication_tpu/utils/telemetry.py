"""Device & compile telemetry: XLA compiles, device memory, recompile storms.

A serving process that silently recompiles is a latency mystery: the
symptom is a multi-second p99 spike, the cause is an off-menu shape or a
non-hashable-static bug three layers down. This module makes compiles a
first-class metric:

* :class:`DeviceTelemetry` — subscribes to ``jax.monitoring``'s
  ``/jax/core/compile/backend_compile_duration`` events (fired once per
  actual XLA compile, NOT per cache hit) and meters them into the
  registry as ``sl_compile_total`` + an ``sl_compile_seconds`` histogram.
  Where ``jax.monitoring`` is unavailable (older jaxlib) automatic
  metering is off — ``install()`` logs it, and callers that need
  compile metrics there wrap their jit entry points with the
  :func:`meter_jit` shim themselves (it is not applied automatically).
* **recompile-storm detector** — a sliding window over compile times; a
  burst above threshold increments ``sl_recompile_storms_total`` and
  records a warning event in the flight recorder, so "it recompiled 40
  times in a minute" is an alert, not archaeology.
* :meth:`DeviceTelemetry.sample_memory` — per-device
  ``bytes_in_use``/``peak_bytes_in_use`` gauges from
  ``Device.memory_stats()`` (TPU/GPU; CPU reports none and the gauges
  simply stay absent).

One process-level jax listener fans out to every installed
:class:`DeviceTelemetry` (jax's listener list is append-only), so tests
can install a telemetry against a private registry and uninstall it
without disturbing the process-global one.
"""

from __future__ import annotations

import collections
import functools
import threading
import time

from . import events as events_mod
from . import trace
from .log import get_logger

log = get_logger(__name__)

#: The jax.monitoring duration key fired once per real XLA compile.
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Fan-out: jax.monitoring listeners cannot be unregistered one at a time,
# so exactly one real listener is registered (lazily) and dispatches to
# the currently-installed telemetries.
_DISPATCH_LOCK = threading.Lock()
_DISPATCH: list["DeviceTelemetry"] = []
_LISTENER_STATE = {"installed": False, "available": None}


def _on_duration(key: str, duration_s: float, **_kw) -> None:
    if key != COMPILE_EVENT:
        return
    with _DISPATCH_LOCK:
        sinks = list(_DISPATCH)
    for t in sinks:
        t.observe_compile(duration_s)


def _ensure_listener() -> bool:
    """Register the process-level jax.monitoring listener once; returns
    whether the monitoring backend is available."""
    with _DISPATCH_LOCK:
        if _LISTENER_STATE["installed"]:
            return bool(_LISTENER_STATE["available"])
        _LISTENER_STATE["installed"] = True
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_duration)
            _LISTENER_STATE["available"] = True
        except Exception as e:   # ancient jaxlib / stubbed-out jax
            log.warning(
                "jax.monitoring unavailable (%s) — automatic compile "
                "metering is OFF; wrap jit entry points with "
                "telemetry.meter_jit to meter compiles manually", e)
            _LISTENER_STATE["available"] = False
        return bool(_LISTENER_STATE["available"])


class DeviceTelemetry:
    """Compile + device-memory meters over one :class:`MetricsRegistry`."""

    def __init__(self, registry: "trace.MetricsRegistry | None" = None,
                 recorder: "events_mod.FlightRecorder | None" = None,
                 storm_window_s: float = 30.0,
                 storm_threshold: int = 20):
        self.registry = registry if registry is not None else trace.REGISTRY
        self.recorder = (recorder if recorder is not None
                         else events_mod.RECORDER)
        self.storm_window_s = float(storm_window_s)
        self.storm_threshold = int(storm_threshold)
        self._lock = threading.Lock()
        self._recent: collections.deque[float] = collections.deque()
        self._in_storm = False
        self.monitoring_available: bool | None = None
        self._compiles = self.registry.counter(
            "sl_compile_total", "XLA backend compiles observed")
        self._compile_s = self.registry.histogram(
            "sl_compile_seconds", "per-compile wall-clock",
            buckets=trace.COMPILE_SECONDS_BUCKETS)
        self._storms = self.registry.counter(
            "sl_recompile_storms_total",
            "compile bursts above the storm threshold")

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "DeviceTelemetry":
        """Start receiving compile events. Idempotent."""
        self.monitoring_available = _ensure_listener()
        with _DISPATCH_LOCK:
            if self not in _DISPATCH:
                _DISPATCH.append(self)
        return self

    def uninstall(self) -> None:
        with _DISPATCH_LOCK:
            if self in _DISPATCH:
                _DISPATCH.remove(self)

    # -- compile metering --------------------------------------------------

    @property
    def compiles_total(self) -> int:
        """XLA compiles observed by THIS telemetry since install()."""
        return int(self._compiles.value)

    def observe_compile(self, duration_s: float) -> None:
        self._compiles.inc()
        self._compile_s.observe(float(duration_s))
        now = time.monotonic()
        with self._lock:
            self._recent.append(now)
            horizon = now - self.storm_window_s
            while self._recent and self._recent[0] < horizon:
                self._recent.popleft()
            burst = len(self._recent)
            storming = burst >= self.storm_threshold
            new_storm = storming and not self._in_storm
            self._in_storm = storming
        if new_storm:
            self._storms.inc()
            self.recorder.record(
                "recompile_storm", severity="warning",
                message=f"{burst} XLA compiles inside "
                        f"{self.storm_window_s:.0f}s — check for "
                        "shape churn / non-hashable statics",
                compiles_in_window=burst)
            log.warning("recompile storm: %d compiles in %.0fs", burst,
                        self.storm_window_s)

    # -- device memory -----------------------------------------------------

    def sample_memory(self) -> dict:
        """Refresh per-device memory gauges; returns {device: stats}.
        Devices without memory_stats (CPU) are reported but not gauged."""
        out: dict[str, dict] = {}
        try:
            import jax

            devices = jax.local_devices()
        except Exception as e:
            log.debug("device enumeration failed: %s", e)
            return out
        for d in devices:
            name = f"{d.platform}:{d.id}"
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                out[name] = {}
                continue
            out[name] = dict(stats)
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                self.registry.gauge(
                    "sl_device_bytes_in_use",
                    "live buffer bytes per device", device=name
                ).set(float(in_use))
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                self.registry.gauge(
                    "sl_device_peak_bytes",
                    "peak buffer bytes per device", device=name
                ).set(float(peak))
        return out

    def memory_pressure(self) -> float:
        """Worst-device ``bytes_in_use / bytes_limit`` in [0, 1] — the
        overload governor's device-memory shedding signal. 0.0 when no
        device reports memory stats (CPU) or limits are absent."""
        worst = 0.0
        for stats in self.sample_memory().values():
            in_use = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            if in_use is not None and limit:
                worst = max(worst, float(in_use) / float(limit))
        return min(1.0, worst)

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "monitoring_available": self.monitoring_available,
            "compiles_total": int(self._compiles.value),
            "compile_seconds": self._compile_s.snapshot(),
            "recompile_storms": int(self._storms.value),
            "device_memory": self.sample_memory(),
        }


def meter_jit(fn, telemetry: DeviceTelemetry):
    """Fallback shim for environments without ``jax.monitoring``: wrap a
    jitted callable so cache growth (``fn._cache_size()``) is counted as
    a compile, with the growing call's wall-clock as the (upper-bound)
    compile time. A no-op-cost passthrough when the cache is warm."""
    if not hasattr(fn, "_cache_size"):
        return fn

    @functools.wraps(fn)
    def metered(*args, **kwargs):
        before = fn._cache_size()
        t0 = time.monotonic()
        out = fn(*args, **kwargs)
        if fn._cache_size() > before:
            telemetry.observe_compile(time.monotonic() - t0)
        return out

    return metered


# ---------------------------------------------------------------------------
# Global default telemetry (lazy; serve/bench/diagnose call install_global)
# ---------------------------------------------------------------------------

_GLOBAL: DeviceTelemetry | None = None
_GLOBAL_LOCK = threading.Lock()


def install_global() -> DeviceTelemetry:
    """The process-default telemetry against ``trace.REGISTRY`` — created
    and installed once, returned thereafter."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = DeviceTelemetry().install()
        return _GLOBAL
