"""Tiny pipelined executor for the finalize tail (ROADMAP: overlap the
meshing solve with the ICP/merge tail).

The Poisson/extraction solve of a finalized scan shares no data with the
rest of the finalize work once the merged cloud exists — yet the batch
and streaming pipelines ran them strictly in sequence. A
:class:`PipelinedTask` runs ONE callable on a background thread so the
caller can keep executing the registration/merge tail (pose assembly,
health gating, artifact serialization) while the device chews on the
mesh solve, then joins deterministically: ``result()`` blocks until the
worker finished and re-raises its exception in the caller's frame, so
the call site's error behavior is exactly the sequential path's.

Design constraints (why this is 60 lines and not a thread pool):

* **determinism** — the task runs the SAME callable with the SAME
  arguments as the sequential path would; overlap changes *when* the
  work runs, never *what* runs. `tests/test_overlap.py` pins bitwise
  mesh parity of overlapped vs sequential finalize.
* **correlation context** — `utils/events.context` /
  `utils/trace.span` fields are contextvars; the task captures the
  submitter's context via ``contextvars.copy_context()`` so worker-side
  `events.record` / spans land in the same scan's journal slice. JAX's
  ``default_device`` is a THREAD-LOCAL, not a contextvar (verified: a
  copied context does not carry it), so it is captured explicitly at
  submit and re-entered in the worker — a serve session finalizing
  under its sticky lane's ``device_ctx`` keeps the solve on that lane.
* **sanitizer-clean** — the worker owns no package-created locks (the
  join is a bare Event wait), so the SL_SANITIZE lock-order checker
  sees no new orderings, and a caller must never hold a session/service
  lock across ``result()`` anyway (that would serialize the overlap it
  exists to create).
* **containment** — a worker crash is carried, not leaked: the
  exception surfaces at ``result()``, where the sequential path would
  have raised it.
"""

from __future__ import annotations

import contextvars
import threading
import time

from .log import get_logger

log = get_logger(__name__)


class PipelinedTask:
    """Run ``fn(*args, **kwargs)`` on a daemon thread, join later.

    ``timings()`` exposes submit/start/end instants (``time.monotonic``
    seconds) so callers can measure the realized concurrency window —
    bench [6b] asserts the solve genuinely overlapped the merge tail
    with these, and `stream/session.py` reports them in
    ``FinalizeResult.stats["overlap"]``.
    """

    def __init__(self, fn, *args, name: str = "task", **kwargs):
        self.name = str(name)
        self._done = threading.Event()
        self._result = None
        self._exc: BaseException | None = None
        self.t_submit = time.monotonic()
        self.t_start: float | None = None
        self.t_end: float | None = None
        ctx = contextvars.copy_context()
        # jax.default_device is thread-local (NOT a contextvar): read the
        # effective value here, on the submitter's thread, and re-enter
        # it in the worker. None (no jax, or no device override) → no-op.
        try:
            import jax

            dev = jax.config.jax_default_device
        except Exception:
            dev = None

        def _call():
            if dev is None:
                return fn(*args, **kwargs)
            import jax

            with jax.default_device(dev):
                return fn(*args, **kwargs)

        def _run():
            self.t_start = time.monotonic()
            try:
                self._result = ctx.run(_call)
            except BaseException as exc:  # re-raised at result()
                self._exc = exc
            finally:
                self.t_end = time.monotonic()
                self._done.set()

        self._thread = threading.Thread(
            target=_run, name=f"overlap-{self.name}", daemon=True)
        self._thread.start()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None):
        """Deterministic join: block until the worker finished, return
        its value or re-raise its exception here. ``timeout`` guards
        against a wedged device — expiry raises :class:`TimeoutError`
        and the worker keeps running (daemon: it cannot block exit)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"PipelinedTask({self.name!r}) still running after "
                f"{timeout:.1f}s — wedged device or runaway solve")
        self._thread.join()
        if self._exc is not None:
            raise self._exc
        return self._result

    def timings(self) -> dict:
        """Relative instants (seconds since submit); end values are
        None while the task runs."""
        t0 = self.t_submit
        return {
            "started_s": None if self.t_start is None
            else round(self.t_start - t0, 6),
            "ended_s": None if self.t_end is None
            else round(self.t_end - t0, 6),
        }
