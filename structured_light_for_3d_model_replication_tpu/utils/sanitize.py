"""Runtime sanitizers (``SL_SANITIZE=1``) — the dynamic half of jaxlint.

The static rules (`analysis/`) are instance-collapsed and lexical; this
module catches what they cannot, at runtime, with zero cost when off:

* **lock-order checker** — :func:`install` replaces ``threading.Lock`` /
  ``threading.RLock`` with factories returning instrumented locks (only
  for locks CREATED by this package's code or its tests — stdlib and
  third-party lock traffic is left untouched). Every blocking acquire
  records an acquired-while-holding edge in a process-wide order graph;
  an acquire that would close a cycle raises :class:`LockOrderError` at
  the *second* ordering, i.e. before any schedule can actually deadlock.
  Per-instance, so the cross-instance orderings `analysis/locks.py`
  collapses are tracked exactly.
* **no-compile region** — :func:`no_compile_region` turns the serve
  steady-state zero-recompile assertion into a reusable guard: it
  installs a scoped :class:`~.telemetry.DeviceTelemetry` listener and
  raises :class:`CompileInRegionError` if more than ``allowed`` XLA
  compiles landed inside the block.
* **NaN/Inf debug wrap** — :func:`assert_finite` /
  :func:`nan_debug_wrap` check array trees on the host side at
  containment boundaries (the serve worker runs its post-readback
  points through it when sanitizing), so a non-finite triangulation
  fails loudly AT the boundary instead of as a meaningless mesh later.

Enable with ``SL_SANITIZE=1`` (tests: `tests/conftest.py` installs the
lock checker for the whole session; CI runs the serve + chaos suites
under it in the ``sanitize`` job).
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import os
import sys
import threading
import _thread

from .log import get_logger

log = get_logger(__name__)

_PKG_MARKERS = ("structured_light_for_3d_model_replication_tpu", "tests")


def enabled() -> bool:
    return os.environ.get("SL_SANITIZE", "").lower() in ("1", "true", "on")


class SanitizerError(RuntimeError):
    """Base of the sanitizer fault vocabulary."""


class LockOrderError(SanitizerError):
    """Acquiring this lock here closes a cycle in the runtime
    acquisition-order graph — a schedule exists that deadlocks."""


class CompileInRegionError(SanitizerError):
    """XLA compiled inside a region asserted compile-free."""


class NonFiniteError(SanitizerError):
    """NaN/Inf where the pipeline contract says finite."""


# ---------------------------------------------------------------------------
# Lock-order checker
# ---------------------------------------------------------------------------


class _OrderGraph:
    """Process-wide acquired-while-holding digraph over sanitized locks.

    Nodes are per-instance (a monotonic id, never reused); edges carry
    the creation sites of both locks for the error message. All state is
    guarded by a RAW lock so the checker cannot recurse into itself."""

    def __init__(self):
        self._mu = _thread.allocate_lock()
        self._edges: dict[int, set] = {}       # a → {b}: a held when b taken
        self._names: dict[int, str] = {}
        self._local = threading.local()

    def register(self, lock_id: int, name: str) -> None:
        with self._mu:
            self._names[lock_id] = name

    def _held(self) -> list:
        if not hasattr(self._local, "held"):
            self._local.held = []
        return self._local.held

    def _reaches(self, src: int, dst: int) -> bool:
        seen, frontier = set(), [src]
        while frontier:
            cur = frontier.pop()
            for nxt in self._edges.get(cur, ()):
                if nxt == dst:
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return False

    def before_acquire(self, lock_id: int) -> None:
        """Record edges held→lock_id; raise on a would-be cycle."""
        held = self._held()
        if not held or lock_id in held:
            return  # first lock, or RLock re-entry: no new ordering
        with self._mu:
            for h in held:
                if h == lock_id or lock_id in self._edges.get(h, ()):
                    continue
                if self._reaches(lock_id, h):
                    a = self._names.get(h, f"lock#{h}")
                    b = self._names.get(lock_id, f"lock#{lock_id}")
                    raise LockOrderError(
                        f"lock-order violation: acquiring {b} while "
                        f"holding {a}, but {b} has (transitively) been "
                        f"held while acquiring {a} elsewhere — two "
                        "threads taking both paths deadlock; pick one "
                        "global order (SL_SANITIZE lock checker)")
                self._edges.setdefault(h, set()).add(lock_id)

    def acquired(self, lock_id: int) -> None:
        self._held().append(lock_id)

    def released(self, lock_id: int) -> None:
        held = self._held()
        # Remove the LAST occurrence (RLock depth, out-of-order release).
        for i in range(len(held) - 1, -1, -1):
            if held[i] == lock_id:
                del held[i]
                return

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()


GRAPH = _OrderGraph()
_lock_seq = itertools.count(1)


class _SanitizedLock:
    """Order-checked wrapper over one ``_thread`` lock (or RLock).

    Duck-types the lock protocol (``acquire``/``release``/context
    manager/``locked``) plus the private hooks ``threading.Condition``
    reaches for on reentrant locks."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self._sl_id = next(_lock_seq)
        self._sl_name = name
        GRAPH.register(self._sl_id, name)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            # Only blocking acquires can deadlock; try-locks never wait.
            GRAPH.before_acquire(self._sl_id)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            GRAPH.acquired(self._sl_id)
        return ok

    def release(self) -> None:
        self._inner.release()
        GRAPH.released(self._sl_id)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<sanitized {self._inner!r} from {self._sl_name}>"

    # Condition() integration: delegate the private lock protocol.
    def _release_save(self):
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        GRAPH.released(self._sl_id)
        return state

    def _acquire_restore(self, state) -> None:
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        GRAPH.acquired(self._sl_id)

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def _caller_is_ours(depth: int = 2) -> bool:
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return False
    fname = frame.f_code.co_filename.replace(os.sep, "/")
    return any(m in fname for m in _PKG_MARKERS)


def _site(depth: int = 2) -> str:
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return "<unknown>"
    return f"{os.path.basename(frame.f_code.co_filename)}:" \
           f"{frame.f_lineno}"


_real_lock = threading.Lock
_real_rlock = threading.RLock
_installed = False


def _make_lock():
    if _caller_is_ours():
        return _SanitizedLock(_real_lock(), f"Lock@{_site()}")
    return _real_lock()


def _make_rlock():
    if _caller_is_ours():
        return _SanitizedLock(_real_rlock(), f"RLock@{_site()}")
    return _real_rlock()


def install() -> bool:
    """Patch the ``threading`` lock factories (idempotent). Only locks
    created AFTER install, by this package/tests, are instrumented."""
    global _installed
    if _installed:
        return True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    _installed = True
    log.info("SL_SANITIZE lock-order checker installed")
    return True


def uninstall() -> None:
    global _installed
    if _installed:
        threading.Lock = _real_lock
        threading.RLock = _real_rlock
        _installed = False


def install_if_enabled() -> bool:
    if enabled():
        return install()
    return False


# ---------------------------------------------------------------------------
# No-compile region
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def no_compile_region(name: str = "", allowed: int = 0):
    """Assert the enclosed block performs at most ``allowed`` XLA
    compiles (default: none — the serve steady-state bar).

    Backed by PR-5's compile telemetry (`utils/telemetry.py`): a scoped
    DeviceTelemetry joins the process jax.monitoring fan-out for the
    block's extent. Where jax.monitoring is unavailable the guard
    degrades to a logged no-op (it must never invent a pass/fail signal
    it cannot measure). Yields the telemetry, so callers can also read
    ``compiles_total`` mid-region."""
    from . import telemetry, trace

    tel = telemetry.DeviceTelemetry(registry=trace.MetricsRegistry())
    tel.install()
    try:
        yield tel
    finally:
        tel.uninstall()
        compiles = int(tel.compiles_total)
        if not tel.monitoring_available:
            log.warning("no_compile_region(%s): jax.monitoring "
                        "unavailable — compile guard skipped", name)
        elif compiles > allowed and sys.exc_info()[0] is None:
            raise CompileInRegionError(
                f"no_compile_region({name!r}): {compiles} XLA "
                f"compile(s) inside a region allowing {allowed} — "
                "steady state is recompiling (off-menu shape? "
                "non-hashable static? cache eviction?)")


# ---------------------------------------------------------------------------
# NaN/Inf debug wrap
# ---------------------------------------------------------------------------


def _iter_arrays(tree):
    """Leaves of nested tuples/lists/dicts that look like arrays."""
    if isinstance(tree, (tuple, list)):
        for item in tree:
            yield from _iter_arrays(item)
    elif isinstance(tree, dict):
        for item in tree.values():
            yield from _iter_arrays(item)
    elif hasattr(tree, "dtype") and hasattr(tree, "shape"):
        yield tree


def assert_finite(tree, name: str = "") -> None:
    """Raise :class:`NonFiniteError` if any float array leaf holds a
    NaN/Inf. Host-side (``np.asarray`` readback) — use at containment
    boundaries, not inside jitted bodies."""
    import numpy as np

    for arr in _iter_arrays(tree):
        a = np.asarray(arr)
        if a.dtype.kind != "f" or a.size == 0:
            continue
        finite = np.isfinite(a)
        if not bool(finite.all()):
            bad = int(a.size - int(finite.sum()))
            raise NonFiniteError(
                f"assert_finite({name or 'array'}): {bad}/{a.size} "
                f"non-finite element(s) in a {a.shape} {a.dtype} array")


def nan_debug_wrap(fn, name: str | None = None):
    """Wrap ``fn`` so its return tree is finite-checked when the
    sanitizer is enabled; a passthrough otherwise."""
    label = name or getattr(fn, "__name__", "fn")

    @functools.wraps(fn)
    def inner(*args, **kwargs):
        out = fn(*args, **kwargs)
        if enabled():
            assert_finite(out, label)
        return out

    return inner
