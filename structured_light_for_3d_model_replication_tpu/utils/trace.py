"""Tracing & per-stage wall-clock metrics.

The reference has no profiling at all (SURVEY.md §5) — its closest artifact
is the auto-scan progress window's elapsed/avg/remaining arithmetic
(`server/gui.py:727-731`). Here tracing is first-class, because the
north-star metric of the whole build is scan→mesh wall-clock seconds:

* :class:`Tracer` — nested wall-clock spans with a thread-local stack;
  thread-safe aggregation; JSON export; human summary. Spans double as
  ``jax.profiler.TraceAnnotation`` contexts, so host spans line up with
  device timelines inside TensorBoard/XProf captures.
* :func:`device_trace` — wraps ``jax.profiler.start_trace/stop_trace``
  for a one-line XLA/TPU capture around any workflow.
* module-level :func:`span` / :func:`summary` / :func:`export` on a global
  default tracer, so pipeline stages can annotate themselves without
  threading a tracer object through every call.
* :class:`Counter` / :class:`Gauge` / :class:`Histogram` +
  :class:`MetricsRegistry` — thread-safe monotonic counters and gauges
  with a Prometheus text exporter (:meth:`MetricsRegistry.prometheus_text`).
  The serving layer's ``/metrics`` endpoint renders the module-level
  :data:`REGISTRY`, and the exporter folds in a :class:`Tracer`'s span
  aggregates (``sl_span_seconds_total{span="scan360.register"}`` …) so the
  existing scan360 stage spans surface on the same scrape.

Spans measure HOST wall-clock: async dispatches that return lazy arrays
cost ~0 unless the span body blocks. Wrap the section you time with
``jax.block_until_ready`` (the workflow entry points here do) or read the
numbers as dispatch time, which is also a real metric.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import json
import os
import threading
import time

#: Default bucket layouts for seconds-valued histograms. The Histogram
#: ctor default (1, 2, 4, 8) fits the serving layer's batch-OCCUPANCY
#: range; latency/compile observations need these instead (enforced by
#: the seconds-histogram audit in tests/test_trace.py).
LATENCY_SECONDS_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 60.0)
COMPILE_SECONDS_BUCKETS = (0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0)


@dataclasses.dataclass
class SpanRecord:
    name: str          # dotted path including ancestors ("scan360.register")
    start_s: float     # monotonic, relative to tracer creation
    duration_s: float
    thread: str
    meta: dict | None = None


class Tracer:
    #: Raw-record cap. A long-running serve process spans every batch;
    #: unbounded records are a slow leak. Past the cap the OLDEST records
    #: are folded into `_evicted` aggregates — `totals()` stays exact
    #: forever, only the raw span list (export/Perfetto) is windowed.
    DEFAULT_MAX_RECORDS = 16384

    def __init__(self, max_records: int | None = None):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t0 = time.monotonic()
        self.max_records = (self.DEFAULT_MAX_RECORDS if max_records is None
                            else int(max_records))
        if self.max_records < 1:
            raise ValueError(
                f"max_records must be >= 1, got {self.max_records}")
        self.records: collections.deque[SpanRecord] = collections.deque()
        # span path -> {count, total_s, max_s} for evicted records.
        self._evicted: dict[str, dict] = {}
        self.evicted_count = 0

    # ------------------------------------------------------------------

    def _stack(self) -> list[str]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str, **meta):
        """Context manager timing a (possibly nested) stage."""
        stack = self._stack()
        path = ".".join(stack + [name])
        stack.append(name)
        annot = _jax_annotation(path)
        start = time.monotonic()
        try:
            if annot is not None:
                with annot:
                    yield
            else:
                yield
        finally:
            dur = time.monotonic() - start
            stack.pop()
            # Ambient correlation fields (events.context scan_id/job_id/
            # stop) ride every span's meta, so Perfetto args and span
            # exports correlate with the flight journal. Lazy import:
            # events.py imports this module for REGISTRY.
            try:
                from . import events as _events

                ctx = _events.current_context()
            except Exception:
                ctx = {}
            if ctx:
                meta = {**ctx, **meta}
            with self._lock:
                self.records.append(SpanRecord(
                    name=path,
                    start_s=start - self._t0,
                    duration_s=dur,
                    thread=threading.current_thread().name,
                    meta=meta or None))
                while len(self.records) > self.max_records:
                    self._evict_locked()

    def _evict_locked(self) -> None:
        old = self.records.popleft()
        agg = self._evicted.setdefault(
            old.name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += old.duration_s
        agg["max_s"] = max(agg["max_s"], old.duration_s)
        self.evicted_count += 1

    def wrap(self, name: str):
        """Decorator form of :meth:`span`."""
        def deco(fn):
            @functools.wraps(fn)
            def inner(*a, **kw):
                with self.span(name):
                    return fn(*a, **kw)
            return inner
        return deco

    # ------------------------------------------------------------------

    def totals(self) -> dict[str, dict]:
        """Aggregate {span path: {count, total_s, mean_s, max_s}}. Exact
        over the tracer's whole lifetime: evicted records contribute via
        their folded aggregates."""
        with self._lock:
            records = list(self.records)
            agg: dict[str, dict] = {
                name: {"count": a["count"], "total_s": a["total_s"],
                       "max_s": a["max_s"]}
                for name, a in self._evicted.items()}
        for r in records:
            a = agg.setdefault(r.name, {"count": 0, "total_s": 0.0,
                                        "max_s": 0.0})
            a["count"] += 1
            a["total_s"] += r.duration_s
            a["max_s"] = max(a["max_s"], r.duration_s)
        for a in agg.values():
            a["mean_s"] = a["total_s"] / a["count"]
            for k in ("total_s", "mean_s", "max_s"):
                a[k] = round(a[k], 6)
        return agg

    def summary(self) -> str:
        rows = sorted(self.totals().items(),
                      key=lambda kv: -kv[1]["total_s"])
        if not rows:
            return "(no spans recorded)"
        w = max(len(k) for k, _ in rows)
        lines = [f"{'span':<{w}}  {'count':>5}  {'total':>9}  "
                 f"{'mean':>9}  {'max':>9}"]
        for k, a in rows:
            lines.append(f"{k:<{w}}  {a['count']:>5}  "
                         f"{a['total_s']:>8.3f}s  {a['mean_s']:>8.3f}s  "
                         f"{a['max_s']:>8.3f}s")
        return "\n".join(lines)

    def export(self, path: str) -> None:
        """JSON dump: raw spans (the retained window) + lifetime
        aggregates."""
        with self._lock:
            records = [dataclasses.asdict(r) for r in self.records]
            evicted = self.evicted_count
        with open(path, "w") as f:
            json.dump({"spans": records, "totals": self.totals(),
                       "evicted_spans": evicted}, f, indent=2)

    # -- Perfetto / Chrome trace_event export ---------------------------

    def to_perfetto(self) -> dict:
        """The retained spans as a Chrome/Perfetto ``trace_event`` JSON
        object (open at ui.perfetto.dev or chrome://tracing). Complete
        duration events ("ph": "X") on one track per thread; span meta —
        including the correlation IDs merged in by :meth:`span` — rides
        in ``args``, so a slow scan's track is searchable by scan_id
        next to a `device_trace` XProf capture of the same run."""
        with self._lock:
            records = list(self.records)
        pid = os.getpid()
        tids: dict[str, int] = {}
        trace_events: list[dict] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "sl-host"}}]
        for r in records:
            tid = tids.get(r.thread)
            if tid is None:
                tid = tids[r.thread] = len(tids) + 1
                trace_events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid, "args": {"name": r.thread}})
            trace_events.append({
                "ph": "X", "cat": "host", "name": r.name,
                "pid": pid, "tid": tid,
                "ts": round(r.start_s * 1e6, 3),
                "dur": round(r.duration_s * 1e6, 3),
                "args": dict(r.meta or {})})
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export_perfetto(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_perfetto(), f)

    def reset(self) -> None:
        with self._lock:
            self.records.clear()
            self._evicted.clear()
            self.evicted_count = 0
            self._t0 = time.monotonic()


def _jax_annotation(name: str):
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture an XLA/TPU profiler trace (TensorBoard/XProf format) for the
    enclosed block: ``with device_trace("/tmp/trace"): run_pipeline()``."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# Global default tracer
# ---------------------------------------------------------------------------

GLOBAL = Tracer()
span = GLOBAL.span
wrap = GLOBAL.wrap
summary = GLOBAL.summary
export = GLOBAL.export
export_perfetto = GLOBAL.export_perfetto
totals = GLOBAL.totals
reset = GLOBAL.reset


# ---------------------------------------------------------------------------
# Metrics: thread-safe counters/gauges/histograms + Prometheus text export
# ---------------------------------------------------------------------------


class Counter:
    """Monotonic counter. ``inc`` with a negative amount raises — a counter
    that can go down is a gauge, and Prometheus rate() silently mis-reads
    one disguised as the other."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth, cache entries, in-flight jobs)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with Prometheus semantics (cumulative
    ``_bucket{le=...}`` counts + ``_sum``/``_count``). Default buckets fit
    the serving layer's batch-occupancy range (1..8)."""

    def __init__(self, buckets: tuple = (1, 2, 4, 8)):
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * len(self.buckets)   # per-bucket, non-cumulative
        self._overflow = 0
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, le in enumerate(self.buckets):
                if value <= le:
                    self._counts[i] += 1
                    break
            else:
                self._overflow += 1

    def snapshot(self) -> dict:
        """{le: cumulative_count} (incl. "+Inf") + sum/count/mean."""
        with self._lock:
            counts = list(self._counts)
            overflow = self._overflow
            total = self._count
            s = self._sum
        cum, acc = {}, 0
        for le, c in zip(self.buckets, counts):
            acc += c
            cum[_fmt_float(le)] = acc
        cum["+Inf"] = acc + overflow
        return {"buckets": cum, "sum": s, "count": total,
                "mean": (s / total) if total else 0.0}


def _fmt_float(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _render_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


class MetricsRegistry:
    """Named metric families with optional labels.

    ``registry.counter("serve_jobs_total", status="done").inc()`` get-or-
    creates the ``status="done"`` child of the ``serve_jobs_total`` family;
    re-registering a name as a different kind raises (one name, one type —
    the Prometheus exposition-format rule)."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, help, {labels_tuple: instrument})
        self._families: dict[str, tuple] = {}

    def _get(self, kind: str, name: str, help_: str, labels: dict,
             **ctor_kwargs):
        key = tuple(sorted(labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (kind, help_, {})
                self._families[name] = fam
            elif fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, "
                    f"requested {kind}")
            children = fam[2]
            inst = children.get(key)
            if inst is None:
                inst = self._KINDS[kind](**ctor_kwargs)
                children[key] = inst
            elif "buckets" in ctor_kwargs:
                # A silently-ignored differing bucket layout would route
                # observations into the WRONG quantile bins; mismatches
                # fail loudly like kind mismatches do.
                want = tuple(sorted(float(b)
                                    for b in ctor_kwargs["buckets"]))
                if want != inst.buckets:
                    raise ValueError(
                        f"histogram {name!r}{dict(key)} already has "
                        f"buckets {inst.buckets}, requested {want}")
            return inst

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = (1, 2, 4, 8), **labels) -> Histogram:
        return self._get("histogram", name, help, labels, buckets=buckets)

    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """Plain-dict view (JSON-friendly; /status payloads, tests)."""
        out: dict[str, dict] = {}
        with self._lock:
            families = {n: (k, h, dict(c))
                        for n, (k, h, c) in self._families.items()}
        for name, (kind, _, children) in sorted(families.items()):
            fam_out = out.setdefault(name, {})
            for key, inst in sorted(children.items()):
                label_s = _render_labels(key) or "_"
                fam_out[label_s] = (inst.snapshot() if kind == "histogram"
                                    else inst.value)
        return out

    def prometheus_text(self, tracer: "Tracer | None" = None) -> str:
        """Prometheus exposition text of every registered metric, plus —
        when a tracer is given — its span aggregates as
        ``sl_span_seconds_total`` / ``sl_span_count_total`` /
        ``sl_span_max_seconds`` families labelled by span path."""
        lines: list[str] = []
        with self._lock:
            families = {n: (k, h, dict(c))
                        for n, (k, h, c) in self._families.items()}
        for name, (kind, help_, children) in sorted(families.items()):
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for key, inst in sorted(children.items()):
                if kind == "histogram":
                    snap = inst.snapshot()
                    for le, c in snap["buckets"].items():
                        lab = dict(key)
                        lab["le"] = le
                        lines.append(
                            f"{name}_bucket"
                            f"{_render_labels(tuple(sorted(lab.items())))}"
                            f" {c}")
                    lines.append(
                        f"{name}_sum{_render_labels(key)} "
                        f"{_fmt_metric(snap['sum'])}")
                    lines.append(
                        f"{name}_count{_render_labels(key)} {snap['count']}")
                else:
                    lines.append(f"{name}{_render_labels(key)} "
                                 f"{_fmt_metric(inst.value)}")
        if tracer is not None:
            agg = tracer.totals()
            if agg:
                lines.append("# HELP sl_span_seconds_total cumulative "
                             "wall-clock per tracer span")
                lines.append("# TYPE sl_span_seconds_total counter")
                for path, a in sorted(agg.items()):
                    lab = _render_labels((("span", path),))
                    lines.append(f"sl_span_seconds_total{lab} "
                                 f"{_fmt_metric(a['total_s'])}")
                lines.append("# HELP sl_span_count_total completed spans "
                             "per tracer span path")
                lines.append("# TYPE sl_span_count_total counter")
                for path, a in sorted(agg.items()):
                    lab = _render_labels((("span", path),))
                    lines.append(f"sl_span_count_total{lab} {a['count']}")
                lines.append("# HELP sl_span_max_seconds longest single "
                             "span per tracer span path")
                lines.append("# TYPE sl_span_max_seconds gauge")
                for path, a in sorted(agg.items()):
                    lab = _render_labels((("span", path),))
                    lines.append(f"sl_span_max_seconds{lab} "
                                 f"{_fmt_metric(a['max_s'])}")
        return "\n".join(lines) + "\n"


def _fmt_metric(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


# Module-level default registry, mirroring the GLOBAL tracer: callers that
# don't thread a registry through (serve/, bench) meter themselves here.
REGISTRY = MetricsRegistry()
