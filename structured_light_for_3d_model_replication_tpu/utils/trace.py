"""Tracing & per-stage wall-clock metrics.

The reference has no profiling at all (SURVEY.md §5) — its closest artifact
is the auto-scan progress window's elapsed/avg/remaining arithmetic
(`server/gui.py:727-731`). Here tracing is first-class, because the
north-star metric of the whole build is scan→mesh wall-clock seconds:

* :class:`Tracer` — nested wall-clock spans with a thread-local stack;
  thread-safe aggregation; JSON export; human summary. Spans double as
  ``jax.profiler.TraceAnnotation`` contexts, so host spans line up with
  device timelines inside TensorBoard/XProf captures.
* :func:`device_trace` — wraps ``jax.profiler.start_trace/stop_trace``
  for a one-line XLA/TPU capture around any workflow.
* module-level :func:`span` / :func:`summary` / :func:`export` on a global
  default tracer, so pipeline stages can annotate themselves without
  threading a tracer object through every call.

Spans measure HOST wall-clock: async dispatches that return lazy arrays
cost ~0 unless the span body blocks. Wrap the section you time with
``jax.block_until_ready`` (the workflow entry points here do) or read the
numbers as dispatch time, which is also a real metric.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import threading
import time


@dataclasses.dataclass
class SpanRecord:
    name: str          # dotted path including ancestors ("scan360.register")
    start_s: float     # monotonic, relative to tracer creation
    duration_s: float
    thread: str
    meta: dict | None = None


class Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t0 = time.monotonic()
        self.records: list[SpanRecord] = []

    # ------------------------------------------------------------------

    def _stack(self) -> list[str]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str, **meta):
        """Context manager timing a (possibly nested) stage."""
        stack = self._stack()
        path = ".".join(stack + [name])
        stack.append(name)
        annot = _jax_annotation(path)
        start = time.monotonic()
        try:
            if annot is not None:
                with annot:
                    yield
            else:
                yield
        finally:
            dur = time.monotonic() - start
            stack.pop()
            with self._lock:
                self.records.append(SpanRecord(
                    name=path,
                    start_s=start - self._t0,
                    duration_s=dur,
                    thread=threading.current_thread().name,
                    meta=meta or None))

    def wrap(self, name: str):
        """Decorator form of :meth:`span`."""
        def deco(fn):
            def inner(*a, **kw):
                with self.span(name):
                    return fn(*a, **kw)
            inner.__name__ = getattr(fn, "__name__", name)
            return inner
        return deco

    # ------------------------------------------------------------------

    def totals(self) -> dict[str, dict]:
        """Aggregate {span path: {count, total_s, mean_s, max_s}}."""
        agg: dict[str, dict] = {}
        with self._lock:
            records = list(self.records)
        for r in records:
            a = agg.setdefault(r.name, {"count": 0, "total_s": 0.0,
                                        "max_s": 0.0})
            a["count"] += 1
            a["total_s"] += r.duration_s
            a["max_s"] = max(a["max_s"], r.duration_s)
        for a in agg.values():
            a["mean_s"] = a["total_s"] / a["count"]
            for k in ("total_s", "mean_s", "max_s"):
                a[k] = round(a[k], 6)
        return agg

    def summary(self) -> str:
        rows = sorted(self.totals().items(),
                      key=lambda kv: -kv[1]["total_s"])
        if not rows:
            return "(no spans recorded)"
        w = max(len(k) for k, _ in rows)
        lines = [f"{'span':<{w}}  {'count':>5}  {'total':>9}  "
                 f"{'mean':>9}  {'max':>9}"]
        for k, a in rows:
            lines.append(f"{k:<{w}}  {a['count']:>5}  "
                         f"{a['total_s']:>8.3f}s  {a['mean_s']:>8.3f}s  "
                         f"{a['max_s']:>8.3f}s")
        return "\n".join(lines)

    def export(self, path: str) -> None:
        """JSON dump: raw spans + aggregates."""
        with self._lock:
            records = [dataclasses.asdict(r) for r in self.records]
        with open(path, "w") as f:
            json.dump({"spans": records, "totals": self.totals()}, f,
                      indent=2)

    def reset(self) -> None:
        with self._lock:
            self.records.clear()
            self._t0 = time.monotonic()


def _jax_annotation(name: str):
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture an XLA/TPU profiler trace (TensorBoard/XProf format) for the
    enclosed block: ``with device_trace("/tmp/trace"): run_pipeline()``."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# Global default tracer
# ---------------------------------------------------------------------------

GLOBAL = Tracer()
span = GLOBAL.span
wrap = GLOBAL.wrap
summary = GLOBAL.summary
export = GLOBAL.export
totals = GLOBAL.totals
reset = GLOBAL.reset
