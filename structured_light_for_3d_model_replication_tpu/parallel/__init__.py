"""Device-mesh / sharding layer: DP over scans, SP over image rows."""

from . import mesh, pipeline  # noqa: F401
