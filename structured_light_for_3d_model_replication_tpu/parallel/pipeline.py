"""Sharded batch reconstruction: DP over scans × SP over image rows.

BASELINE configs 4 and 5 in one entry point. The design is sharding-annotation
style (the scaling-book recipe): place the inputs with a ``NamedSharding``,
jit the pure batch function, and let XLA insert the collectives. For this
workload the decode/triangulate math is per-pixel, so the only cross-shard
traffic XLA generates is the adaptive-mask percentile reduction — everything
else is fully local to each (scan, row-block) tile and rides the VPU.

No shard_map is needed for the forward pipeline; it becomes necessary only for
the ICP/merge stages where per-scan results interact (see models/merge.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..config import DecodeConfig, TriangulationConfig
from ..models import pipeline as mp
from ..ops.triangulate import Calibration
from . import mesh as mesh_lib


def shard_inputs(stacks: jnp.ndarray, calib: Calibration, mesh: Mesh):
    """Place a (B, F, H, W) batch on the mesh: scans over `data`, rows over
    `space`; the calibration container is replicated (it is small next to the
    stacks and every shard needs all plane equations)."""
    stacks = jax.device_put(stacks, mesh_lib.stack_batch_sharding(mesh))
    calib = jax.device_put(calib, mesh_lib.replicated(mesh))
    return stacks, calib


def reconstruct_sharded(
    stacks: jnp.ndarray,
    calib: Calibration,
    mesh: Mesh,
    col_bits: int,
    row_bits: int,
    decode_cfg: DecodeConfig = DecodeConfig(),
    tri_cfg: TriangulationConfig = TriangulationConfig(),
    downsample: int = 1,
) -> mp.CloudResult:
    """Decode+triangulate a batch of scans across the mesh.

    Returns a batched CloudResult whose arrays are sharded (B over data,
    pixels over space). Call sites that need host data should np.asarray the
    fields they use.
    """
    stacks, calib = shard_inputs(stacks, calib, mesh)
    fn = mp.reconstruct_batch_fn(col_bits, row_bits, decode_cfg, tri_cfg,
                                 downsample)
    return fn(stacks, calib)
