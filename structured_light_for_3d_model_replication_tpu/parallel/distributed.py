"""Multi-host (DCN) initialization — the ``jax.distributed`` entry.

SURVEY §2e scopes the TPU-native comm story as: collectives over ICI
within a host's mesh (``parallel/mesh.py``), and — when the pipeline ever
spans hosts — DCN via the standard ``jax.distributed`` runtime. The
reference has no multi-node analogue (its "distribution" is PC↔phone↔MCU
over HTTP/serial); this module is the new surface area, kept deliberately
thin: one env-driven, idempotent, guarded initializer plus a helper to
assert the expected world size.

Environment contract (standard JAX names, so any launcher that speaks
them — SLURM-style wrappers, k8s indexed jobs, shell scripts — works):

* ``SL_COORDINATOR``  — ``host:port`` of process 0 (also accepts JAX's own
  ``JAX_COORDINATOR_ADDRESS``);
* ``SL_NUM_PROCESSES`` / ``SL_PROCESS_ID`` — world size and this rank
  (also ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``).

With none of these set, :func:`initialize_from_env` is a no-op returning
False — single-host flows never pay a thing. Tested by an actual
two-process CPU run in ``tests/test_distributed.py``.
"""

from __future__ import annotations

import os

from ..utils.log import get_logger

log = get_logger(__name__)

_initialized = False


def _env(*names: str) -> str | None:
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return None


def initialize_from_env() -> bool:
    """Initialize ``jax.distributed`` from the environment (idempotent).

    Returns True when a multi-process runtime was (or already is)
    initialized, False when the environment requests none.
    """
    global _initialized
    if _initialized:
        return True
    coord = _env("SL_COORDINATOR", "JAX_COORDINATOR_ADDRESS")
    if coord is None:
        return False
    nproc = _env("SL_NUM_PROCESSES", "JAX_NUM_PROCESSES")
    pid = _env("SL_PROCESS_ID", "JAX_PROCESS_ID")
    if nproc is None or pid is None:
        raise RuntimeError(
            "SL_COORDINATOR is set but SL_NUM_PROCESSES/SL_PROCESS_ID are "
            "not — a partial multi-host environment is a misconfiguration, "
            "refusing to guess")
    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # CPU cross-process collectives need an explicit transport; gloo is
        # the one shipped with jaxlib (TPU/DCN paths configure themselves).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older jaxlib without the option
            log.warning("gloo CPU collectives unavailable; cross-process "
                        "CPU collectives may not work")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=int(nproc),
                               process_id=int(pid))
    _initialized = True
    log.info("jax.distributed initialized: rank %s/%s via %s", pid, nproc,
             coord)
    return True


def world() -> tuple[int, int]:
    """(process_id, num_processes) — (0, 1) when uninitialized."""
    import jax

    if not _initialized:
        return 0, 1
    return jax.process_index(), jax.process_count()
