"""Device-mesh construction for the scan workload.

The reference has no accelerator parallelism at all (SURVEY.md §2e: no
DP/TP/PP/SP, no NCCL/MPI — its only concurrency is Python threads around
hardware IO). The TPU build's parallel axes are therefore designed from
scratch around the workload's natural structure:

* ``data`` — independent scans (turntable stops / batch jobs). Embarrassingly
  parallel; the analogue of DP. BASELINE config 5 (8 scans across a v4-8).
* ``space`` — spatial tiling of the camera image rows within one scan. The
  decode reduction is per-pixel (associative along the frame axis), so a row
  shard needs NO cross-chip communication except the global percentile in the
  adaptive mask, which XLA lowers to a small collective. The analogue of SP
  for the 46×4K stacks of BASELINE config 4.

Meshes are ordinary ``jax.sharding.Mesh`` objects; sharded entry points take
the mesh explicitly so multi-host setups (``jax.distributed``) can pass a
global mesh.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPACE_AXIS = "space"


def make_mesh(data: int | None = None, space: int = 1, devices=None) -> Mesh:
    """Build a (data, space) mesh. data=None → all remaining devices."""
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devices) % space:
            raise ValueError(f"{len(devices)} devices not divisible by space={space}")
        data = len(devices) // space
    n = data * space
    if n > len(devices):
        raise ValueError(f"need {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(data, space)
    return Mesh(grid, (DATA_AXIS, SPACE_AXIS))


def stack_batch_sharding(mesh: Mesh) -> NamedSharding:
    """(B, F, H, W) capture-stack batches: B over data, H (rows) over space."""
    return NamedSharding(mesh, P(DATA_AXIS, None, SPACE_AXIS, None))


def cloud_batch_sharding(mesh: Mesh) -> NamedSharding:
    """(B, N, 3) point batches: B over data, points over space."""
    return NamedSharding(mesh, P(DATA_AXIS, SPACE_AXIS, None))


def points_sharding(mesh: Mesh) -> NamedSharding:
    """(N, 3) unbatched clouds — the meshing solve's input: points over
    space. The Poisson/TSDF solvers' jit programs carry
    ``in_shardings=None`` (committed shardings pass through), so
    staging a cloud with this sharding is what flips their splat /
    normal phases from replicated to sharded (GSPMD derives the grid
    collectives)."""
    return NamedSharding(mesh, P(SPACE_AXIS, None))


def samples_sharding(mesh: Mesh) -> NamedSharding:
    """(N,) per-point scalars (validity masks, densities): over space."""
    return NamedSharding(mesh, P(SPACE_AXIS))


def serve_space_mesh(n_devices: int, devices=None) -> Mesh:
    """The serving tier's sharded-bucket mesh: one job spans
    ``n_devices`` chips with camera rows over the space axis (data=1 —
    the batch dimension stays whole; `serve/cache.ProgramKey.shards`)."""
    return make_mesh(data=1, space=int(n_devices), devices=devices)


def resolve_device_labels(labels, devices=None) -> list:
    """The jax.Device objects behind a span of ``"platform:id"`` labels,
    in device ENUMERATION order (not label order) — mesh row placement
    must be reproducible across processes that enumerate the same
    topology, regardless of how the span set was sorted for its
    program-key identity. Raises on a label no local device answers to
    (a span staged against a phantom chip must fail at build time, not
    at launch)."""
    devices = list(devices if devices is not None else jax.local_devices())
    want = set(labels)
    out = [d for d in devices if f"{d.platform}:{d.id}" in want]
    if len(out) != len(want):
        have = {f"{d.platform}:{d.id}" for d in devices}
        raise ValueError(
            f"unknown device label(s) {sorted(want - have)} in span "
            f"{sorted(want)}; local devices: {sorted(have)}")
    return out


def serve_span_mesh(labels, devices=None) -> Mesh:
    """Set-keyed serving mesh: one job spans EXACTLY the named devices
    (`serve/cache.ProgramKey.span`), not a count-prefix of the
    enumeration. This is what lets the sharded tier drop one dead
    member and keep the other chips working (docs/MESHING.md § shard
    degrade) — a prefix mesh dies whole when device 0 does."""
    devs = resolve_device_labels(labels, devices)
    return make_mesh(data=1, space=len(devs), devices=devs)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
