"""Job model + bounded admission queue.

The queue is the service's ONLY growth point, so it is bounded by
construction: over-admission is rejected at submit time with a retryable
status and a ``retry_after_s`` hint (computed from queue depth × the
observed per-job service EMA), never buffered. That is the AGS admission
rule (PAPERS.md: covisibility-gated frame admission — drop at the door,
not in the middle of the pipeline) applied to a reconstruction RPC.

Service-side faults subclass the PR-3 :class:`~..health.ScanFault`
taxonomy: the status payload of a failed job carries the same error
vocabulary (``CaptureError``/``StopQualityError``/…) that `scan-360`
health reports use, so a client can tell a malformed upload from a
decode-quality failure from an overloaded queue without parsing prose.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
import time
import uuid

import numpy as np

from ..config import DecodeConfig, TriangulationConfig
from ..health import CaptureError, ScanFault
from ..utils import events
from ..utils.log import get_logger

log = get_logger(__name__)


# ---------------------------------------------------------------------------
# Error taxonomy (service-side extensions of health.ScanFault)
# ---------------------------------------------------------------------------


class ServeError(ScanFault):
    """Base of the service-side fault vocabulary."""


class JobRejected(ServeError):
    """The job never entered the queue (full, closed, or malformed).

    ``retryable`` distinguishes "try again later" (backpressure) from
    "fix your request" (malformed stack). Rejections are designed flow
    control, not failures: they journal as warnings (``flight_severity``)
    so an overload burst — hundreds of constructions per second — never
    wraps the flight ring past genuine fault history or storms the
    dump-on-fault directory."""

    retryable = False
    flight_severity = "warning"


class QueueFullError(JobRejected):
    """Bounded queue at capacity — retry after ``retry_after_s``."""

    retryable = True

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"admission queue full ({depth} jobs); retry in "
            f"{retry_after_s:.2f}s")
        self.retry_after_s = retry_after_s


class QueueClosedError(JobRejected):
    """Service is draining (SIGTERM) — in-flight jobs finish, new work is
    refused."""

    retryable = True

    def __init__(self):
        super().__init__("service is draining; submit to another replica")
        self.retry_after_s = None


class StackFormatError(CaptureError, JobRejected):
    """Malformed capture stack (dtype/rank/frame-count/size) — the upload
    analogue of a truncated frame file, hence a ``CaptureError``."""


class DeadlineExceededError(ServeError):
    """The job's deadline lapsed before a worker could start it."""


def error_payload(exc: BaseException) -> dict:
    """Status-payload form of a fault: concrete type + the taxonomy chain
    (most-derived first) so clients can match on any ancestor they know."""
    taxonomy = [c.__name__ for c in type(exc).__mro__
                if issubclass(c, ScanFault)]
    out = {"type": type(exc).__name__, "message": str(exc),
           "taxonomy": taxonomy or ["Exception"]}
    retry = getattr(exc, "retry_after_s", None)
    if retry is not None:
        out["retry_after_s"] = round(float(retry), 3)
    return out


# ---------------------------------------------------------------------------
# Job
# ---------------------------------------------------------------------------

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


@dataclasses.dataclass(eq=False)  # identity equality: a job IS its object
class Job:
    """One reconstruction request: a capture stack in, a PLY/STL out.

    Mutable state (status, result, error) is guarded by ``_lock``;
    ``wait`` blocks on the terminal event. Timestamps are monotonic
    (queue-wait / batch-wait / run are per-stage latencies on /metrics).
    """

    stack: np.ndarray                 # (F, H, W) uint8 capture stack
    col_bits: int
    row_bits: int
    decode_cfg: DecodeConfig = DecodeConfig()
    tri_cfg: TriangulationConfig = TriangulationConfig()
    downsample: int = 1
    result_format: str = "ply"        # "ply" | "stl"
    priority: int = 1                 # 0 high, 1 normal, 2 low
    deadline_s: float | None = None   # seconds from submit; None = no limit
    job_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:16])

    # -- lifecycle state (lock-guarded) ------------------------------------
    status: str = QUEUED
    error: dict | None = None
    result_bytes: bytes | None = None
    result_meta: dict = dataclasses.field(default_factory=dict)
    # Terminal observer (set by the service before admission): called once
    # with the job after complete/fail, WHEREVER the transition happens —
    # worker postprocess, batch-scoped failure, or deadline scrub in the
    # queue/batcher. Keeps the jobs_total{done,failed} counters conserved
    # against submitted without every layer knowing the registry.
    on_terminal: "callable | None" = dataclasses.field(
        default=None, repr=False)
    # Streaming interop (serve/sessions.py): when set, the worker hands
    # this job's decoded dense arrays (points, colors, valid — the
    # per-job batch lanes) to the sink instead of building a PLY/STL;
    # the sink's dict return becomes the job's result meta and JSON
    # payload. Session stops ride the SAME queue → batcher → program
    # cache as one-shot jobs, so they coalesce into the same batches.
    decode_sink: "callable | None" = dataclasses.field(
        default=None, repr=False)
    # Durability plumbing (serve/store.py): the content-hash cache key of
    # this job's artifact (None = uncacheable, e.g. session stops), and
    # which journal vocabulary its terminal transition appends under
    # ("job" — one-shot, recoverable; "stop" and None journal nothing at
    # terminal: stops are tracked per session, synthesized jobs not at
    # all). ``recovered`` marks a job re-queued from the journal.
    content_key: str | None = dataclasses.field(default=None, repr=False)
    journal_kind: str | None = dataclasses.field(default=None, repr=False)
    session_id: str | None = dataclasses.field(default=None, repr=False)
    recovered: bool = dataclasses.field(default=False, repr=False)
    # Device-lane affinity (serve/lanes.py): None = any worker may take
    # this job; an index pins it to that lane's pending buckets so a
    # streaming session's stops always run on the session's STICKY
    # device (its jit programs were warmed there — migrating mid-scan
    # would compile).
    lane: int | None = dataclasses.field(default=None, repr=False)
    # Device-loss retries: how many times this job's batch died under it
    # with a device-class fault and was re-queued onto another lane
    # (serve/worker.py). Bounded — past the pool's live-device count the
    # job fails honestly instead of ping-ponging between sick chips.
    launch_retries: int = dataclasses.field(default=0, repr=False)
    # Deferred NaN attribution (serve/worker.py): the lane whose launch
    # returned NaN under this job, pending the cross-lane retry's
    # verdict — clean elsewhere convicts the chip, NaN elsewhere
    # convicts the data.
    nan_lane: str | None = dataclasses.field(default=None, repr=False)

    submitted_t: float = 0.0
    started_t: float | None = None
    finished_t: float | None = None

    def __post_init__(self):
        self._lock = threading.Lock()
        self._terminal = threading.Event()
        self.submitted_t = time.monotonic()

    # ------------------------------------------------------------------

    @property
    def deadline_t(self) -> float | None:
        if self.deadline_s is None:
            return None
        return self.submitted_t + self.deadline_s

    def expired(self, now: float | None = None) -> bool:
        dl = self.deadline_t
        return dl is not None and (now or time.monotonic()) > dl

    # ------------------------------------------------------------------

    def mark_running(self) -> None:
        with self._lock:
            self.status = RUNNING
            self.started_t = time.monotonic()

    def complete(self, result: bytes, **meta) -> None:
        with self._lock:
            if self._terminal.is_set():
                return  # first terminal transition wins
            self.status = DONE
            self.result_bytes = result
            self.result_meta.update(meta)
            self.finished_t = time.monotonic()
            # Release the input stack: terminal jobs stay registered for
            # /status///result polling (completed_cap of them), and at
            # 1080p each stack is ~95 MB — keeping them would let the
            # registry pin tens of GB of dead inputs.
            self.stack = None
        self._terminal.set()
        if self.on_terminal is not None:
            self.on_terminal(self)

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._terminal.is_set():
                return
            self.status = FAILED
            self.error = error_payload(exc)
            self.finished_t = time.monotonic()
            self.stack = None  # same release rule as complete()
        self._terminal.set()
        if self.on_terminal is not None:
            self.on_terminal(self)

    def release_result(self) -> int:
        """Drop the retained result payload (registry byte-budget
        eviction); returns bytes freed. The job entry itself survives, so
        /status stays truthful and /result can answer an explicit 410
        instead of a silent unknown-job 404."""
        with self._lock:
            n = len(self.result_bytes) if self.result_bytes else 0
            self.result_bytes = None
            if n:
                self.result_meta["result_evicted"] = True
        return n

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the job reaches a terminal state."""
        return self._terminal.wait(timeout)

    # ------------------------------------------------------------------

    def status_dict(self) -> dict:
        with self._lock:
            # Queue wait ends at start, or at the terminal transition for
            # jobs that never started (deadline scrub) — "now" only while
            # genuinely still waiting, else the number grows forever.
            wait_end = (self.started_t or self.finished_t
                        or time.monotonic())
            out = {
                "job_id": self.job_id,
                "status": self.status,
                "result_format": self.result_format,
                "priority": self.priority,
                "queue_wait_s": round(wait_end - self.submitted_t, 4),
            }
            if self.started_t is not None and self.finished_t is not None:
                out["run_s"] = round(self.finished_t - self.started_t, 4)
            if self.error is not None:
                out["error"] = dict(self.error)
            if self.status == DONE:
                out["result"] = dict(self.result_meta)
        return out


# ---------------------------------------------------------------------------
# Bounded admission queue
# ---------------------------------------------------------------------------


class AdmissionQueue:
    """Thread-safe bounded priority queue with deadline scrubbing.

    Ordering is (priority, arrival) — starvation-free within a priority
    class. ``submit`` never blocks and never grows past ``max_depth``:
    at capacity it raises :class:`QueueFullError` whose ``retry_after_s``
    is depth × the EMA of observed per-job service time (workers feed the
    EMA via :meth:`observe_service_time`), i.e. an honest estimate of when
    a slot frees up. ``close`` flips the queue into drain mode: pops still
    serve (in-flight work finishes), submits are refused.
    """

    def __init__(self, max_depth: int = 64,
                 default_service_s: float = 0.25):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._heap: list = []
        self._seq = itertools.count()
        self._closed = False
        self._service_ema_s = default_service_s

    # ------------------------------------------------------------------

    def check_admission(self) -> None:
        """Raise the rejection `submit` WOULD raise right now, without
        enqueueing. Advisory (another submitter can win the race), but it
        lets a transport reject an oversized upload at headers time
        instead of buffering ~95 MB per connection just to say 429 —
        `submit` remains the authoritative gate."""
        with self._lock:
            self._check_admission_locked()

    def _check_admission_locked(self) -> None:
        if self._closed:
            raise QueueClosedError()
        if len(self._heap) >= self.max_depth:
            retry = max(0.05, len(self._heap) * self._service_ema_s)
            raise QueueFullError(len(self._heap), retry)

    def submit(self, job: Job) -> None:
        with self._lock:
            self._check_admission_locked()
            heapq.heappush(self._heap,
                           (job.priority, next(self._seq), job))
            self._not_empty.notify()

    def pop(self, timeout: float | None = None) -> Job | None:
        """Next admissible job, or None on timeout. Jobs whose deadline
        lapsed while queued are failed (DeadlineExceededError) and skipped
        — a worker never spends a batch slot on work nobody is waiting
        for. The fail itself runs OUTSIDE the queue lock: constructing
        the fault records a flight event and may write a dump-on-fault
        journal, and that disk I/O must never stall every submitter and
        worker contending for this lock."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            job: Job | None = None
            expired: list[Job] = []
            timed_out = False
            with self._not_empty:
                while True:
                    while self._heap:
                        _, _, j = heapq.heappop(self._heap)
                        if j.expired():
                            expired.append(j)
                            continue
                        job = j
                        break
                    if job is not None or expired:
                        break  # fail the scrubbed jobs lock-free first
                    if deadline is None:
                        self._not_empty.wait()
                    else:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            timed_out = True
                            break
                        self._not_empty.wait(remaining)
            for j in expired:
                # Context so the fault event the constructor records
                # carries the scrubbed job's id.
                with events.context(job_id=j.job_id):
                    j.fail(DeadlineExceededError(
                        f"deadline {j.deadline_s:.2f}s lapsed after "
                        f"{time.monotonic() - j.submitted_t:.2f}s "
                        "in queue"))
            if job is not None or timed_out:
                return job

    # ------------------------------------------------------------------

    def observe_service_time(self, seconds: float) -> None:
        """EMA update from a worker's measured per-job latency — feeds the
        retry-after hint."""
        with self._lock:
            self._service_ema_s = (0.8 * self._service_ema_s
                                   + 0.2 * max(1e-3, seconds))

    def retry_hint(self) -> float:
        """Honest retry-after estimate at the CURRENT depth (what a
        QueueFullError would carry) — for rejections decided outside the
        queue, e.g. the overload governor's shedding tiers."""
        with self._lock:
            return max(0.05, max(1, len(self._heap)) * self._service_ema_s)

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def set_max_depth(self, max_depth: int) -> int:
        """Re-bound the queue (the device-loss tier's degraded-capacity
        honesty: a pool at N−1 chips advertises — and enforces — N−1
        chips' worth of admission headroom). Already-admitted jobs above
        a shrunken bound are NOT scrubbed (they were acked); the bound
        re-engages as they drain. Returns the new bound."""
        with self._lock:
            self.max_depth = max(1, int(max_depth))
            return self.max_depth

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
