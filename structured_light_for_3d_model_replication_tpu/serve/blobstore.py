"""Pluggable blob/object-store backend for the fleet's shared state.

PR 9's fleet tier shares session-handoff streams (and, optionally,
content-cache artifacts) through one POSIX directory — which makes "a
fleet" secretly mean "replicas mounting one filesystem volume". This
module extracts the storage seam: :class:`BlobStore` is the small
interface `store.SessionStreamStore` and `cache.ContentCache` actually
need, with two implementations —

* :class:`LocalDirStore` — the historical shared-directory layout,
  preserved bit-for-bit (same tmp + atomic-rename writes, same file
  names), so existing deployments and every on-disk assertion in the
  test suite see identical bytes;
* :class:`ObjectStore` — an S3-style flat key→bytes namespace over a
  tiny client protocol (``put/get/delete/list/append/head``). The
  in-process :class:`InMemoryObjectClient` and the stdlib
  :class:`ObjectStoreServer` + :class:`HTTPObjectClient` pair (a
  mini object service the fleet smoke runs replicas against across
  processes) are the reference backends; a real S3/GCS client only has
  to speak the same six calls. ``append`` is served atomically by these
  backends — a production S3 adapter would emulate it with per-record
  objects or multipart compose; the stream readers already tolerate
  interleaves and torn tails either way.

Failure posture: a missing object is ``None`` (or a no-op delete),
never an exception; every infrastructure failure is an ``OSError`` —
exactly what the WAL mirror containment, the content-cache quarantine
and the adoption degrade paths already catch. A store failure may
therefore degrade DURABILITY (shorter handoff stream, cache miss) but
never availability — the property :class:`FaultyBlobStore` (seeded
latency / errors / torn writes, ``SL_BLOB_FAULTS`` env for subprocess
replicas, hw/faults.py's determinism rule) exists to prove under chaos.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.log import get_logger

log = get_logger(__name__)

#: Env var carrying a JSON :class:`BlobFaultPlan` for subprocess
#: replicas (the chaos harness sets it; production never does).
BLOB_FAULTS_ENV = "SL_BLOB_FAULTS"


def _check_key(key: str) -> str:
    """Keys are "/"-joined relative names. Reject anything that could
    escape a local root (the object backends are flat namespaces, but
    one validation serves both)."""
    if not key or key.startswith("/") or ".." in key.split("/"):
        raise ValueError(f"bad blob key {key!r}")
    return key


class BlobStore:
    """The storage seam: whole-object put/get/delete/list plus ordered
    ``append`` (log semantics — session streams) and atomic ``replace``
    (tombstone rewrites). Missing objects read as None; infrastructure
    failures raise OSError."""

    backend = "abstract"

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def append(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def replace(self, key: str, data: bytes) -> None:
        """Atomically swap the whole object (default: a plain put —
        object backends overwrite atomically by construction)."""
        self.put(key, data)

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Move one object (quarantine paths). Missing src is OSError."""
        data = self.get(src)
        if data is None:
            raise FileNotFoundError(src)
        self.put(dst, data)
        self.delete(src)

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    def size(self, key: str) -> int | None:
        data = self.get(key)
        return None if data is None else len(data)

    def stats(self) -> dict:
        return {"backend": self.backend}


# ---------------------------------------------------------------------------
# Local directory backend (the historical shared-volume layout)
# ---------------------------------------------------------------------------


class LocalDirStore(BlobStore):
    """Keys are relative paths under ``root``. Writes are tmp + atomic
    rename (a torn put can never be mistaken for an object); appends are
    single buffered writes in append mode, flushed — the same
    atomic-enough discipline `SessionStreamStore` always used, so this
    backend reproduces the PR-9 on-disk layout byte for byte."""

    backend = "file"

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *_check_key(key).split("/"))

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def get(self, key: str) -> bytes | None:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except (FileNotFoundError, IsADirectoryError):
            return None

    def append(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "ab") as f:
            f.write(data)
            f.flush()

    def replace(self, key: str, data: bytes) -> None:
        self.put(key, data)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def rename(self, src: str, dst: str) -> None:
        dpath = self._path(dst)
        os.makedirs(os.path.dirname(dpath), exist_ok=True)
        os.replace(self._path(src), dpath)

    def list(self, prefix: str = "") -> list[str]:
        # Walk only the subtree the prefix's directory part names, not
        # the whole root: a stats-path listing of "quarantine/" must
        # stay proportional to the quarantine, not to every artifact.
        if prefix and ".." in prefix.split("/"):
            raise ValueError(f"bad list prefix {prefix!r}")
        dir_part = prefix.rpartition("/")[0]
        start = (os.path.join(self.root, *dir_part.split("/"))
                 if dir_part else self.root)
        out: list[str] = []
        for dirpath, _, names in os.walk(start):
            rel = os.path.relpath(dirpath, self.root)
            base = "" if rel == "." else rel.replace(os.sep, "/") + "/"
            for n in names:
                key = base + n
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def size(self, key: str) -> int | None:
        try:
            return os.path.getsize(self._path(key))
        except OSError:
            return None

    def stats(self) -> dict:
        return {"backend": self.backend, "root": self.root}


# ---------------------------------------------------------------------------
# Object backends (S3-style flat namespace over a six-call client)
# ---------------------------------------------------------------------------


class InMemoryObjectClient:
    """Dict-backed object client — the stdlib in-process fake. Appends
    are atomic under the lock (the "server-side append" contract the
    ObjectStore docstring describes)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: dict[str, bytes] = {}

    def put_object(self, key: str, data: bytes) -> None:
        with self._lock:
            self._objects[key] = bytes(data)

    def get_object(self, key: str) -> bytes | None:
        with self._lock:
            return self._objects.get(key)

    def append_object(self, key: str, data: bytes) -> None:
        with self._lock:
            self._objects[key] = self._objects.get(key, b"") + bytes(data)

    def delete_object(self, key: str) -> None:
        with self._lock:
            self._objects.pop(key, None)

    def list_objects(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._objects if k.startswith(prefix))

    def head_object(self, key: str) -> int | None:
        with self._lock:
            data = self._objects.get(key)
            return None if data is None else len(data)


class _ObjectHandler(BaseHTTPRequestHandler):
    client: InMemoryObjectClient  # bound by ObjectStoreServer

    protocol_version = "HTTP/1.1"
    timeout = 30.0

    def _key(self) -> str | None:
        path = urllib.parse.urlparse(self.path).path
        if not path.startswith("/o/"):
            return None
        return urllib.parse.unquote(path[len("/o/"):])

    def _respond(self, status: int, body: bytes = b"",
                 extra: dict | None = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", "application/octet-stream")
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n > 0 else b""

    def do_PUT(self):
        key = self._key()
        if key is None:
            self._respond(404)
            return
        self.client.put_object(key, self._body())
        self._respond(200)

    def do_POST(self):  # append
        key = self._key()
        if key is None:
            self._respond(404)
            return
        self.client.append_object(key, self._body())
        self._respond(200)

    def do_GET(self):
        url = urllib.parse.urlparse(self.path)
        if url.path == "/list":
            prefix = (urllib.parse.parse_qs(url.query).get("prefix")
                      or [""])[0]
            body = json.dumps(self.client.list_objects(prefix)).encode()
            self._respond(200, body, {"Content-Type": "application/json"})
            return
        key = self._key()
        data = self.client.get_object(key) if key is not None else None
        if data is None:
            self._respond(404)
        else:
            self._respond(200, data)

    def do_HEAD(self):
        key = self._key()
        n = self.client.head_object(key) if key is not None else None
        if n is None:
            self._respond(404)
        else:
            self.send_response(200)
            self.send_header("Content-Length", str(n))
            self.end_headers()

    def do_DELETE(self):
        key = self._key()
        if key is not None:
            self.client.delete_object(key)
        self._respond(200)

    def log_message(self, fmt, *args):
        log.debug("objectstore: " + fmt, *args)


class ObjectStoreServer:
    """A mini object service over HTTP (stdlib, like every other server
    in this repo): the cross-process fake the fleet smoke runs replicas
    against, so "no shared filesystem" is provable with subprocesses.
    NOT a production store — it exists to exercise the ObjectStore code
    path end to end."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 client: InMemoryObjectClient | None = None):
        self.client = client if client is not None \
            else InMemoryObjectClient()
        handler = type("BoundObjectHandler", (_ObjectHandler,),
                       {"client": self.client})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="objectstore-http",
                                        daemon=True)
        self._started = False

    def start(self) -> "ObjectStoreServer":
        self._thread.start()
        self._started = True
        log.info("object store on :%d", self.port)
        return self

    def stop(self) -> None:
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()


class HTTPObjectClient:
    """Client half of :class:`ObjectStoreServer`'s protocol. Connection
    failures surface as OSError (urllib.error.URLError subclasses it);
    5xx answers become OSError too — both are store faults the callers'
    containment handles."""

    def __init__(self, base_url: str, timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def _request(self, method: str, path: str, body: bytes | None = None
                 ) -> tuple[int, dict, bytes]:
        req = urllib.request.Request(self.base_url + path, data=body,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, dict(r.headers), r.read()
        except urllib.error.HTTPError as e:
            if e.code >= 500:
                raise OSError(f"object store {method} {path}: "
                              f"HTTP {e.code}")
            return e.code, dict(e.headers), e.read()

    @staticmethod
    def _opath(key: str) -> str:
        return "/o/" + urllib.parse.quote(key, safe="/")

    def put_object(self, key: str, data: bytes) -> None:
        self._request("PUT", self._opath(key), data)

    def get_object(self, key: str) -> bytes | None:
        status, _, body = self._request("GET", self._opath(key))
        return body if status == 200 else None

    def append_object(self, key: str, data: bytes) -> None:
        self._request("POST", self._opath(key), data)

    def delete_object(self, key: str) -> None:
        self._request("DELETE", self._opath(key))

    def list_objects(self, prefix: str = "") -> list[str]:
        status, _, body = self._request(
            "GET", "/list?prefix=" + urllib.parse.quote(prefix, safe=""))
        if status != 200:
            raise OSError(f"object store list: HTTP {status}")
        return list(json.loads(body.decode()))

    def head_object(self, key: str) -> int | None:
        status, hdrs, _ = self._request("HEAD", self._opath(key))
        if status != 200:
            return None
        return int(hdrs.get("Content-Length", 0))


class ObjectStore(BlobStore):
    """BlobStore over a six-call object client (in-memory fake, the
    HTTP mini-service, or a real S3-style adapter)."""

    backend = "object"

    def __init__(self, client, prefix: str = ""):
        self.client = client
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        key = _check_key(key)
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, data: bytes) -> None:
        self.client.put_object(self._key(key), data)

    def get(self, key: str) -> bytes | None:
        return self.client.get_object(self._key(key))

    def append(self, key: str, data: bytes) -> None:
        self.client.append_object(self._key(key), data)

    def delete(self, key: str) -> None:
        self.client.delete_object(self._key(key))

    def list(self, prefix: str = "") -> list[str]:
        full = (f"{self.prefix}/{prefix}" if self.prefix else prefix)
        strip = len(self.prefix) + 1 if self.prefix else 0
        return [k[strip:] for k in self.client.list_objects(full)]

    def size(self, key: str) -> int | None:
        return self.client.head_object(self._key(key))

    def stats(self) -> dict:
        return {"backend": self.backend,
                "url": getattr(self.client, "base_url", "memory")}


# ---------------------------------------------------------------------------
# Fault injection (the chaos harness's storage seam)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BlobFaultPlan:
    """Seeded store-fault schedule: a deterministic fraction of
    operations errors (OSError), is delayed, and/or — for writes —
    lands TORN (a truncated payload persisted while the call still
    reports success: the bit the durability counters must absorb and
    the readers' torn-line/size-check tolerance must survive). One RNG
    stream per store — same seed, same fault sequence (hw/faults.py's
    determinism rule applied to storage)."""

    seed: int = 0
    error_rate: float = 0.0       # P(op raises OSError)
    latency_s: float = 0.0        # injected delay when latency fires
    latency_rate: float = 0.0     # P(latency_s is injected)
    torn_write_rate: float = 0.0  # P(a write persists truncated)

    @classmethod
    def from_env(cls, env: str = BLOB_FAULTS_ENV) -> "BlobFaultPlan | None":
        spec = os.environ.get(env)
        if not spec:
            return None
        try:
            doc = json.loads(spec)
        except ValueError as e:
            log.error("ignoring malformed %s=%r: %s", env, spec, e)
            return None
        allowed = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in allowed})


class FaultyBlobStore(BlobStore):
    """Wraps any BlobStore with a :class:`BlobFaultPlan`. ``sleep`` is
    injectable so unit tests assert latency decisions without waiting."""

    backend = "faulty"

    def __init__(self, inner: BlobStore, plan: BlobFaultPlan,
                 sleep=time.sleep):
        self.inner = inner
        self.plan = plan
        self._sleep = sleep
        self._lock = threading.Lock()  # one deterministic RNG stream
        self._rng = random.Random(plan.seed)
        self.errors = 0
        self.delays = 0
        self.torn_writes = 0

    def _roll(self, writing: bool) -> tuple[bool, bool, float]:
        """(error, delay, torn_frac) for one op; torn_frac < 0 = whole."""
        with self._lock:
            error = self._rng.random() < self.plan.error_rate
            delay = (not error
                     and self._rng.random() < self.plan.latency_rate)
            torn = -1.0
            if writing and not error \
                    and self._rng.random() < self.plan.torn_write_rate:
                torn = self._rng.random()
            if error:
                self.errors += 1
            if delay:
                self.delays += 1
            if torn >= 0.0:
                self.torn_writes += 1
        return error, delay, torn

    def _enter(self, writing: bool = False) -> float:
        error, delay, torn = self._roll(writing)
        if delay:
            self._sleep(self.plan.latency_s)
        if error:
            raise OSError("injected blob-store fault")
        return torn

    def _maim(self, data: bytes, torn: float) -> bytes:
        if torn < 0.0 or not data:
            return data
        return data[:int(len(data) * torn)]

    def put(self, key, data):
        self.inner.put(key, self._maim(data, self._enter(writing=True)))

    def get(self, key):
        self._enter()
        return self.inner.get(key)

    def append(self, key, data):
        self.inner.append(key, self._maim(data, self._enter(writing=True)))

    def replace(self, key, data):
        self.inner.replace(key,
                           self._maim(data, self._enter(writing=True)))

    def delete(self, key):
        self._enter()
        self.inner.delete(key)

    def rename(self, src, dst):
        self._enter()
        self.inner.rename(src, dst)

    def list(self, prefix=""):
        self._enter()
        return self.inner.list(prefix)

    def size(self, key):
        self._enter()
        return self.inner.size(key)

    def stats(self) -> dict:
        out = dict(self.inner.stats())
        out.update(backend=f"faulty+{self.inner.backend}",
                   injected_errors=self.errors,
                   injected_delays=self.delays,
                   injected_torn_writes=self.torn_writes)
        return out


# ---------------------------------------------------------------------------
# Spec parsing (the config/CLI seam)
# ---------------------------------------------------------------------------


def open_blob_store(spec: str, allow_faults: bool = True) -> BlobStore:
    """A BlobStore from a spec string: ``http(s)://host:port[/prefix]``
    → :class:`ObjectStore` over the HTTP protocol, ``mem:`` → a private
    in-process object store (unit tests), anything else (optionally
    ``file:``-prefixed) → :class:`LocalDirStore` on that directory —
    which is why every existing ``--handoff-dir /path`` deployment keeps
    its exact on-disk layout. When the chaos harness armed
    ``SL_BLOB_FAULTS`` the store is wrapped in a
    :class:`FaultyBlobStore` (disable with ``allow_faults=False``)."""
    if spec.startswith(("http://", "https://")):
        url = urllib.parse.urlparse(spec)
        base = f"{url.scheme}://{url.netloc}"
        store: BlobStore = ObjectStore(HTTPObjectClient(base),
                                       prefix=url.path.strip("/"))
    elif spec.startswith("mem:"):
        store = ObjectStore(InMemoryObjectClient(),
                            prefix=spec[len("mem:"):].strip("/"))
    else:
        if spec.startswith("file:"):
            spec = spec[len("file:"):]
        store = LocalDirStore(spec)
    if allow_faults:
        plan = BlobFaultPlan.from_env()
        if plan is not None:
            log.warning("blob-store faults armed: %s", plan)
            store = FaultyBlobStore(store, plan)
    return store
