"""Thin fleet front router: consistent-hash admission, sticky sessions.

One :class:`FleetRouter` in front of N replicas (each a normal
`cli serve` process) turns "a durable replica" into "a fleet that loses
a node and doesn't care":

* **health-driven membership** — a background thread polls every
  replica's ``/readyz`` (the PR-8 readiness contract) on a short bounded
  timeout; a 503 or a dead socket removes the replica from the routing
  ring until it answers ready again. No replica-side cooperation beyond
  the endpoint that already exists.
* **consistent-hash admission** — ``POST /submit`` is placed by the
  SHA-256 of the request body over a :class:`~.fleet.HashRing`, so
  duplicate submits land on the replica that already holds the artifact
  (a local content-cache hit). When that replica dies, only its arc of
  keys remaps — and the peer half of the shared cache
  (serve/fleet.py) covers the remapped duplicates.
* **replica-sticky sessions with handoff** — ``POST /session`` pins the
  new session to a ready replica; every later op routes to the pin.
  When the pinned replica dies mid-session, the router walks the ring's
  survivors and asks one to **adopt** the session from the shared
  handoff stream (``POST /session/<id>/adopt``,
  `ReconstructionService.adopt_session`), re-pins, and forwards the op
  — the client sees one slower stop, not a dead scan.
* **transparent proxying** — everything else (``/status``, ``/result``,
  previews, metrics aggregation's per-replica scrape) forwards to the
  owning replica; job→replica placements are remembered (bounded) so
  polling follows the job wherever admission put it.

The router holds NO reconstruction state and never touches a device:
killing it loses nothing but routing memory (job/session pins are
re-learned by probing replicas), which is why one thin process is
enough in front of the fleet. (Importing it still pulls the serve
package — and with it jax — so it runs from the same install as a
replica; it just never initializes a backend.)
docs/SERVING.md § fleet has the deployment recipe; the chaos bars live
in tests/test_fleet.py and bench config [10].
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import events, trace
from ..utils.log import get_logger
from .fleet import HashRing, PeerTransport
from .service import MAX_SUBMIT_BYTES

log = get_logger(__name__)

#: Request headers the router forwards to replicas verbatim.
_FORWARD_HEADERS = ("X-Result-Format", "X-Priority", "X-Deadline-S",
                    "Content-Type")


class FleetRouter:
    """Routing brain (transport-agnostic; the HTTP server is below)."""

    def __init__(self, replicas, check_interval_s: float = 1.0,
                 health_timeout_s: float = 2.0,
                 forward_timeout_s: float = 600.0,
                 transport: PeerTransport | None = None,
                 registry: "trace.MetricsRegistry | None" = None,
                 max_job_pins: int = 65536):
        urls = [u.rstrip("/") for u in replicas]
        if not urls:
            raise ValueError("a router needs at least one replica URL")
        self.replicas = urls
        self.check_interval_s = float(check_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.forward_timeout_s = float(forward_timeout_s)
        self.transport = transport if transport is not None \
            else PeerTransport()
        self.registry = registry if registry is not None \
            else trace.MetricsRegistry()
        self.ring = HashRing(urls)
        self._lock = threading.Lock()
        self._ready: dict[str, bool] = {u: False for u in urls}
        self._reasons: dict[str, str] = {}
        self._jobs: OrderedDict[str, str] = OrderedDict()  # job -> url
        self._max_job_pins = int(max_job_pins)
        self._sessions: dict[str, str] = {}                # sid -> url
        self._rr = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._requests = lambda route: self.registry.counter(
            "router_requests_total", "requests by route", route=route)
        self._failovers = self.registry.counter(
            "router_failovers_total",
            "submits re-placed after the hash owner failed")
        self._repins = self.registry.counter(
            "router_session_repins_total",
            "sessions handed off to a survivor after their pinned "
            "replica died")
        self._unroutable = self.registry.counter(
            "router_unroutable_total",
            "requests refused with no ready replica")
        self._ready_gauge = self.registry.gauge(
            "router_ready_replicas", "replicas currently routable")

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "FleetRouter":
        self._sweep()  # synchronous first sweep: route from request one
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch,
                                        name="router-health", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            self._sweep()

    def _sweep(self) -> None:
        for url in self.replicas:
            ready, reason = self._probe(url)
            self._set_ready(url, ready, reason)
        with self._lock:
            self._ready_gauge.set(sum(self._ready.values()))

    def _probe(self, url: str) -> tuple[bool, str]:
        try:
            status, _, body = self.transport.get(
                f"{url}/readyz", timeout_s=self.health_timeout_s)
        except OSError as e:
            return False, f"unreachable ({e})"
        if status == 200:
            return True, ""
        try:
            reasons = json.loads(body.decode()).get("reasons", [])
        except (ValueError, UnicodeDecodeError):
            reasons = []
        return False, "; ".join(reasons) or f"readyz {status}"

    def _set_ready(self, url: str, ready: bool, reason: str = "") -> None:
        with self._lock:
            was = self._ready.get(url)
            self._ready[url] = ready
            self._reasons[url] = reason
        if was is not None and was != ready:
            log.info("replica %s -> %s%s", url,
                     "ready" if ready else "not ready",
                     f" ({reason})" if reason else "")
            events.record("router_replica_health", severity="info"
                          if ready else "warning", url=url, ready=ready,
                          reason=reason)

    # -- membership views ----------------------------------------------

    def ready_replicas(self) -> list[str]:
        with self._lock:
            return [u for u in self.replicas if self._ready.get(u)]

    def _down(self) -> set[str]:
        with self._lock:
            return {u for u in self.replicas if not self._ready.get(u)}

    # -- placement ------------------------------------------------------

    def place_submit(self, body: bytes) -> list[str]:
        """Candidate replicas for one submit, consistent-hash owner
        first: duplicates of the same bytes keep landing on the same
        replica while it lives, so its local content cache answers."""
        key = hashlib.sha256(body).hexdigest()
        return self.ring.preference(key, avoid=self._down())

    def place_session(self, session_id: str) -> list[str]:
        return self.ring.preference(session_id, avoid=self._down())

    def next_replica(self) -> str | None:
        """Round-robin over ready replicas (session creation spread)."""
        ready = self.ready_replicas()
        if not ready:
            return None
        with self._lock:
            self._rr += 1
            return ready[self._rr % len(ready)]

    # -- pin bookkeeping -------------------------------------------------

    def pin_job(self, job_id: str, url: str) -> None:
        with self._lock:
            self._jobs[job_id] = url
            while len(self._jobs) > self._max_job_pins:
                self._jobs.popitem(last=False)

    def job_url(self, job_id: str) -> str | None:
        with self._lock:
            return self._jobs.get(job_id)

    def pin_session(self, session_id: str, url: str) -> None:
        with self._lock:
            self._sessions[session_id] = url

    def session_url(self, session_id: str) -> str | None:
        with self._lock:
            return self._sessions.get(session_id)

    def unpin_session(self, session_id: str) -> None:
        with self._lock:
            self._sessions.pop(session_id, None)

    # -- forwarding ------------------------------------------------------

    def forward(self, url: str, method: str, path: str,
                body: bytes | None = None, headers: dict | None = None
                ) -> tuple[int, dict, bytes]:
        """One bounded-proxy hop. OSError propagates (connection-level
        death) and flips the replica not-ready immediately — the health
        sweep would notice within a second anyway, but the failing
        request IS the freshest probe we have."""
        try:
            return self.transport.request(
                method, url + path, body=body, headers=headers,
                timeout_s=self.forward_timeout_s)
        except OSError:
            self._set_ready(url, False, "request failed")
            raise

    # -- session handoff --------------------------------------------------

    def adopt_on_survivor(self, session_id: str) -> str | None:
        """Walk the ring's survivors asking each to adopt the session
        from the shared handoff stream; returns the new pin, or None
        when nobody could (no ready replicas, or no handoff volume)."""
        return self._adopt_on_survivor_ex(session_id)[0]

    def _adopt_on_survivor_ex(self, session_id: str
                              ) -> tuple[str | None, bool]:
        """``(new pin, definitively_unknown)``: the second element is
        True only when at least one survivor ANSWERED the adoption and
        every answer was a 404 — no adoptable handoff stream exists
        (the session ended, or never rode a handoff volume), so a
        retry cannot help. Transport failures and busy refusals (503)
        keep it False — those warrant the caller's retryable 503."""
        old = self.session_url(session_id)
        attempted = 0
        uncertain = 0      # transport failures + non-404 refusals
        for url in self.place_session(session_id):
            if url == old:
                continue
            attempted += 1
            try:
                status, _, body = self.forward(
                    url, "POST", f"/session/{session_id}/adopt")
            except OSError:
                uncertain += 1
                continue
            if status == 200:
                self.pin_session(session_id, url)
                self._repins.inc()
                events.record("session_repinned", severity="warning",
                              session_id=session_id, from_url=old,
                              to_url=url)
                log.warning("session %s re-pinned %s -> %s",
                            session_id, old, url)
                return url, False
            if status != 404:
                uncertain += 1
            log.warning("survivor %s refused adoption of %s: %s %s",
                        url, session_id, status, body[:200])
        return None, attempted > 0 and uncertain == 0

    def route_session(self, session_id: str) -> str | None:
        return self.route_session_ex(session_id)[0]

    def route_session_ex(self, session_id: str
                         ) -> tuple[str | None, bool]:
        """The replica a session op should go to: the live pin; for an
        UNKNOWN session (router restart — pins are memory) the replica
        that already holds it live, re-learned by probing; else a
        survivor that successfully adopts. Probing before adopting
        matters: stealing a session from a healthy replica would
        double-host it and pay an adoption replay for a failover that
        never happened.

        Returns ``(replica, definitively_unknown)``. ``(None, True)``
        = every ready replica answered and denied the session AND no
        adoptable handoff stream exists — the caller should 404, not
        tell the client to retry a session that already ended.
        ``(None, False)`` = nowhere to send it right now (no ready
        replicas, or transport failures muddied the sweep) — caller
        503s and the client retries."""
        url = self.session_url(session_id)
        if url is not None:
            with self._lock:
                pinned_ready = self._ready.get(url, False)
            if pinned_ready:
                return url, False
            # The sweep's cached flag can be STALE (one missed probe
            # while the replica was busy). Adoption is expensive and —
            # worse — steals the session; re-probe the pin fresh and
            # believe a live answer before walking the survivors.
            ready, reason = self._probe(url)
            self._set_ready(url, ready, reason)
            if ready:
                return url, False
            # A pin is evidence the session recently lived on a replica
            # we can no longer ask — its fate is UNKNOWN until a
            # survivor adopts or the replica recovers, so never 404.
            return self._adopt_on_survivor_ex(session_id)[0], False
        probed = 0
        uncertain = 0      # transport failures + non-(200|404) answers
        for candidate in self.ready_replicas():
            probed += 1
            try:
                status, _, _ = self.forward(
                    candidate, "GET", f"/session/{session_id}")
            except OSError:
                uncertain += 1
                continue
            if status == 200:
                self.pin_session(session_id, candidate)
                return candidate, False
            if status != 404:
                uncertain += 1
        adopted, adopt_unknown = self._adopt_on_survivor_ex(session_id)
        if adopted is not None:
            return adopted, False
        return None, probed > 0 and uncertain == 0 and adopt_unknown

    # -- inspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": [
                    {"url": u, "ready": self._ready.get(u, False),
                     "reason": self._reasons.get(u, "")}
                    for u in self.replicas],
                "sessions_pinned": dict(self._sessions),
                "jobs_pinned": len(self._jobs),
                "failovers": int(self._failovers.value),
                "session_repins": int(self._repins.value),
            }

    def metrics_text(self) -> str:
        with self._lock:
            self._ready_gauge.set(sum(self._ready.values()))
        return self.registry.prometheus_text()


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


class _RouterHandler(BaseHTTPRequestHandler):
    router: FleetRouter  # bound by RouterHTTPServer

    protocol_version = "HTTP/1.1"
    timeout = 120.0

    def _json(self, obj, status=200):
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _relay(self, status: int, headers: dict, body: bytes) -> None:
        self.send_response(status)
        for k, v in headers.items():
            if k.lower() in ("content-type", "retry-after") \
                    or k.lower().startswith("x-"):
                self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _no_replica(self) -> None:
        self.router._unroutable.inc()
        self._json({"error": {"type": "NoReadyReplicaError",
                              "message": "no ready replica in the "
                                         "fleet; retry shortly"}}, 503)

    def _session_unknown(self, session_id: str) -> None:
        # Definitive (route_session_ex's second element): every ready
        # replica denied the session and no handoff stream exists — a
        # retryable 503 here would have clients polling an ended
        # session forever, each poll costing a full fleet sweep.
        self._json({"error": {"type": "UnknownSessionError",
                              "message": f"unknown session "
                                         f"{session_id!r} on every "
                                         "ready replica"}}, 404)

    def _read_body(self) -> bytes | None:
        length = int(self.headers.get("Content-Length", 0))
        if length < 0 or length > MAX_SUBMIT_BYTES:
            self.close_connection = True
            self._json({"error": {"type": "StackFormatError",
                                  "message": f"Content-Length {length} "
                                             f"outside [0, "
                                             f"{MAX_SUBMIT_BYTES}]"}},
                       400)
            return None
        return self.rfile.read(length) if length else b""

    def _fwd_headers(self) -> dict:
        return {k: self.headers[k] for k in _FORWARD_HEADERS
                if self.headers.get(k)}

    # ------------------------------------------------------------------

    def do_POST(self):
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        body = self._read_body()
        if body is None:
            return
        if url.path == "/submit":
            self._submit(body)
        elif parts and parts[0] == "session":
            self._session_op(parts, body)
        else:
            self._json({"error": "not found"}, 404)

    def _submit(self, body: bytes) -> None:
        r = self.router
        r._requests("submit").inc()
        candidates = r.place_submit(body)
        if not candidates:
            self._no_replica()
            return
        for i, replica in enumerate(candidates):
            try:
                status, hdrs, resp = r.forward(
                    replica, "POST", "/submit", body=body,
                    headers=self._fwd_headers())
            except OSError:
                r._failovers.inc()
                continue
            if status == 503 and i + 1 < len(candidates):
                # Draining/unready replica the sweep hasn't flagged yet:
                # fail over along the ring like a dead one. 429 is NOT
                # failed over — backpressure is load, and shoving the
                # burst onto the next replica just moves the hot spot.
                r._failovers.inc()
                continue
            if status == 200:
                try:
                    job_id = json.loads(resp.decode()).get("job_id")
                except (ValueError, UnicodeDecodeError):
                    job_id = None
                if job_id:
                    r.pin_job(job_id, replica)
            self._relay(status, hdrs, resp)
            return
        self._no_replica()

    def _session_op(self, parts: list, body: bytes) -> None:
        r = self.router
        if len(parts) == 1:
            # POST /session — create on the round-robin pick.
            r._requests("session_create").inc()
            replica = r.next_replica()
            if replica is None:
                self._no_replica()
                return
            try:
                status, hdrs, resp = r.forward(
                    replica, "POST", "/session", body=body,
                    headers=self._fwd_headers())
            except OSError:
                self._no_replica()
                return
            if status == 200:
                try:
                    sid = json.loads(resp.decode()).get("session_id")
                except (ValueError, UnicodeDecodeError):
                    sid = None
                if sid:
                    r.pin_session(sid, replica)
            self._relay(status, hdrs, resp)
            return
        sid = parts[1]
        r._requests("session_op").inc()
        replica, unknown = r.route_session_ex(sid)
        if replica is None:
            self._session_unknown(sid) if unknown else self._no_replica()
            return
        try:
            status, hdrs, resp = r.forward(
                replica, "POST", "/" + "/".join(parts), body=body,
                headers=self._fwd_headers())
        except OSError:
            # The pin died mid-request: one handoff retry, then give up
            # (the client's own retry policy owns anything beyond).
            replica = r.adopt_on_survivor(sid)
            if replica is None:
                self._no_replica()
                return
            try:
                status, hdrs, resp = r.forward(
                    replica, "POST", "/" + "/".join(parts), body=body,
                    headers=self._fwd_headers())
            except OSError:
                self._no_replica()
                return
        if len(parts) == 3 and parts[2] == "finalize" and status == 200:
            try:
                job_id = json.loads(resp.decode()).get("job_id")
            except (ValueError, UnicodeDecodeError):
                job_id = None
            if job_id:
                r.pin_job(job_id, replica)
        self._relay(status, hdrs, resp)

    # ------------------------------------------------------------------

    def do_GET(self):
        url = urlparse(self.path)
        r = self.router
        if url.path == "/healthz":
            self._json({"ok": True, "router": True, **r.stats()})
        elif url.path == "/readyz":
            ready = bool(r.ready_replicas())
            self._json({"ready": ready,
                        "reasons": ([] if ready
                                    else ["no ready replicas"])},
                       200 if ready else 503)
        elif url.path == "/fleet":
            self._json(r.stats())
        elif url.path == "/metrics":
            data = r.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif url.path in ("/status", "/result"):
            self._job_query(url)
        elif url.path.startswith("/session/"):
            parts = [p for p in url.path.split("/") if p]
            if len(parts) < 2:     # bare "/session/" — no id to route
                self._json({"error": "not found"}, 404)
                return
            replica, unknown = r.route_session_ex(parts[1])
            if replica is None:
                self._session_unknown(parts[1]) if unknown \
                    else self._no_replica()
                return
            self._proxy_get(replica, self.path)
        else:
            self._json({"error": "not found"}, 404)

    def _job_query(self, url) -> None:
        r = self.router
        job_id = (parse_qs(url.query).get("id") or [""])[0]
        replica = r.job_url(job_id)
        if replica is not None:
            self._proxy_get(replica, self.path)
            return
        # Unknown placement (router restarted, or the job predates us):
        # probe the fleet — first replica that knows the id wins the pin.
        for candidate in r.ready_replicas():
            try:
                status, hdrs, body = r.forward(candidate, "GET",
                                               self.path)
            except OSError:
                continue
            if status != 404:
                r.pin_job(job_id, candidate)
                self._relay(status, hdrs, body)
                return
        self._json({"error": f"unknown job {job_id!r} on every ready "
                             "replica"}, 404)

    def _proxy_get(self, replica: str, path: str) -> None:
        try:
            status, hdrs, body = self.router.forward(replica, "GET", path)
        except OSError:
            self._json({"error": {"type": "ReplicaUnreachableError",
                                  "message": f"replica {replica} did "
                                             "not answer"}}, 503)
            return
        self._relay(status, hdrs, body)

    def do_DELETE(self):
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if len(parts) == 2 and parts[0] == "session":
            replica, unknown = self.router.route_session_ex(parts[1])
            if replica is None:
                self._session_unknown(parts[1]) if unknown \
                    else self._no_replica()
                return
            try:
                status, hdrs, body = self.router.forward(
                    replica, "DELETE", self.path)
            except OSError:
                self._no_replica()
                return
            if status == 200:
                self.router.unpin_session(parts[1])
            self._relay(status, hdrs, body)
        else:
            self._json({"error": "not found"}, 404)

    def log_message(self, fmt, *args):
        log.debug("router: " + fmt, *args)


class RouterHTTPServer:
    """Owns the router's listener thread (mirrors ServeHTTPServer)."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        handler = type("BoundRouterHandler", (_RouterHandler,),
                       {"router": router})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="router-http", daemon=True)
        self._started = False

    def start(self) -> "RouterHTTPServer":
        self.router.start()
        self._thread.start()
        self._started = True
        log.info("fleet router on :%d (%d replica(s))", self.port,
                 len(self.router.replicas))
        return self

    def stop(self) -> None:
        self.router.stop()
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()
