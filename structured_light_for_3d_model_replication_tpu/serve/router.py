"""Thin fleet front router: consistent-hash admission, sticky sessions.

One :class:`FleetRouter` in front of N replicas (each a normal
`cli serve` process) turns "a durable replica" into "a fleet that loses
a node and doesn't care":

* **health-driven membership** — a background thread polls every
  replica's ``/readyz`` (the PR-8 readiness contract) on a short bounded
  timeout; a 503 or a dead socket removes the replica from the routing
  ring until it answers ready again. No replica-side cooperation beyond
  the endpoint that already exists.
* **consistent-hash admission** — ``POST /submit`` is placed by the
  SHA-256 of the request body over a :class:`~.fleet.HashRing`, so
  duplicate submits land on the replica that already holds the artifact
  (a local content-cache hit). When that replica dies, only its arc of
  keys remaps — and the peer half of the shared cache
  (serve/fleet.py) covers the remapped duplicates.
* **replica-sticky sessions with handoff** — ``POST /session`` pins the
  new session to a ready replica; every later op routes to the pin.
  When the pinned replica dies mid-session, the router walks the ring's
  survivors and asks one to **adopt** the session from the shared
  handoff stream (``POST /session/<id>/adopt``,
  `ReconstructionService.adopt_session`), re-pins, and forwards the op
  — the client sees one slower stop, not a dead scan.
* **transparent proxying** — everything else (``/status``, ``/result``,
  previews, metrics aggregation's per-replica scrape) forwards to the
  owning replica; job→replica placements are remembered (bounded) so
  polling follows the job wherever admission put it.
* **router HA** — 2+ routers share the session-pin map through a
  :class:`~.blobstore.BlobStore` (:class:`PinBoard`: one
  generation-stamped record per session, last-writer-wins, ties broken
  by router id) and probe each other (``router_peers``): a client may
  hit any router, a freshly restarted router RE-LEARNS its pins from
  the board instead of probing the fleet (and therefore never steals a
  live session), and concurrent routers converge on one owner because
  every adoption consults the board for a fresher pin first.
* **proactive re-pin** — a readyz-miss failure detector with hysteresis
  (consecutive misses → suspect → dead; consecutive hits to come back)
  triggers ``adopt_session`` on ring survivors in the BACKGROUND the
  moment a replica is declared dead, so failover is pre-completed work
  instead of the next client op's latency spike (bench [10]'s
  ``fleet_proactive_repin_s``). Among live peered routers, the lowest
  router id is the detector primary — the rest stand by (adoption is
  idempotent and board-converged, so an election race is benign, just
  wasteful).

The router holds NO reconstruction state and never touches a device:
killing it loses only routing memory not yet on the pin board (job pins
are re-learned by probing replicas), which is why thin processes are
enough in front of the fleet. (Importing it still pulls the serve
package — and with it jax — so it runs from the same install as a
replica; it just never initializes a backend.)
docs/SERVING.md § fleet has the deployment recipe; the chaos bars live
in tests/test_fleet.py and bench config [10].
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import events, trace
from ..utils.log import get_logger
from .blobstore import BlobStore, open_blob_store
from .fleet import HashRing, PeerTransport
from .service import MAX_SUBMIT_BYTES

log = get_logger(__name__)

#: Request headers the router forwards to replicas verbatim.
_FORWARD_HEADERS = ("X-Result-Format", "X-Priority", "X-Deadline-S",
                    "X-Tenant", "Content-Type")

#: Failure-detector states (readyz-miss hysteresis).
ALIVE, SUSPECT, DEAD = "alive", "suspect", "dead"


class PinBoard:
    """Session-pin records shared by a router set through a blob store.

    One object per session (``router/pins/<sid>.json``) carrying
    ``{url, gen, router}``. Records are totally ordered by
    ``(gen, router)`` — writers stamp ``gen = known + 1``, the highest
    order wins, and an equal-generation double-write tie-breaks on
    router id — so every reader converges on ONE owner without
    coordination: :meth:`write` refuses to clobber a higher-ranked
    record, readers only adopt records that outrank their local
    knowledge, and the owning router's periodic sync re-asserts a
    record a racing replace landed over. Store failures are contained
    here: a sick board degrades pin SHARING (each router falls back to
    its local memory), never routing."""

    PREFIX = "router/pins/"
    JOB_PREFIX = "router/jobs/"

    def __init__(self, store: BlobStore, router_id: str):
        self.store = store
        self.router_id = router_id
        self.write_failures = 0

    def _key(self, session_id: str) -> str:
        safe = "".join(c for c in session_id
                       if c.isalnum() or c in "-_")
        return f"{self.PREFIX}{safe}.json"

    def _job_key(self, job_id: str) -> str:
        safe = "".join(c for c in job_id if c.isalnum() or c in "-_")
        return f"{self.JOB_PREFIX}{safe}.json"

    # -- job pins -------------------------------------------------------
    #
    # Jobs never MOVE (a job lives and dies on the replica that admitted
    # it), so job records need none of the session records' generation
    # machinery: last-writer-wins trivially because every writer writes
    # the same placement. Sharing them is what lets a freshly restarted
    # (or peer) router answer /status//result without probing the whole
    # fleet — the ROADMAP open item. Records carry t_wall so the board
    # sync can prune ones past their useful life (results are bounded
    # registry entries replica-side anyway).

    def write_job(self, job_id: str, url: str) -> None:
        try:
            rec = json.dumps({"url": url, "router": self.router_id,
                              "t_wall": time.time()}).encode()
            self.store.replace(self._job_key(job_id), rec)
        except OSError as e:
            self.write_failures += 1
            log.warning("pin-board job write for %s failed: %s",
                        job_id, e)

    def read_job(self, job_id: str) -> str | None:
        try:
            data = self.store.get(self._job_key(job_id))
        except OSError:
            return None
        if data is None:
            return None
        try:
            return str(json.loads(data.decode())["url"])
        except (ValueError, KeyError, UnicodeDecodeError):
            return None  # torn record: ignore

    def prune_jobs(self, ttl_s: float, max_records: int = 512) -> int:
        """Drop job records older than ``ttl_s`` (board hygiene — the
        replicas' bounded registries stopped answering for them long
        ago). At most ``max_records`` are READ per sweep: each check
        is a store GET, and this runs on the board-sync thread next to
        session-pin reconciliation — an unbounded sweep over a
        sustained-submit backlog would stall that thread for minutes
        against a slow store (pruning is eventually-consistent by
        design). Returns records dropped; store failures degrade
        pruning only."""
        dropped = 0
        try:
            keys = self.store.list(self.JOB_PREFIX)
        except OSError:
            return 0
        cutoff = time.time() - ttl_s
        for key in keys[:max(1, int(max_records))]:
            if not key.endswith(".json"):
                continue
            try:
                data = self.store.get(key)
                doc = json.loads(data.decode()) if data is not None \
                    else None
            except (OSError, ValueError, UnicodeDecodeError):
                continue
            if doc is None or float(doc.get("t_wall", 0.0)) >= cutoff:
                continue
            try:
                self.store.delete(key)
                dropped += 1
            except OSError:
                continue
        return dropped

    def write(self, session_id: str, url: str, gen: int) -> None:
        """Publish a pin UNLESS the board already holds a higher-ranked
        record ((gen, router) lexicographic — the tie-break that makes
        two routers stamping the same generation deterministic). The
        read-compare-write is not a CAS; a racing replace can still
        land the lower-ranked record last, which the owning router's
        periodic board sync detects and re-asserts."""
        try:
            cur = self.read(session_id)
            if cur is not None \
                    and (cur[1], cur[2]) > (int(gen), self.router_id):
                return
            rec = json.dumps({"url": url, "gen": int(gen),
                              "router": self.router_id,
                              "t_wall": time.time()}).encode()
            self.store.replace(self._key(session_id), rec)
        except OSError as e:
            self.write_failures += 1
            log.warning("pin-board write for %s failed: %s",
                        session_id, e)

    def clear(self, session_id: str) -> None:
        try:
            self.store.delete(self._key(session_id))
        except OSError as e:
            self.write_failures += 1
            log.warning("pin-board clear for %s failed: %s",
                        session_id, e)

    @staticmethod
    def _parse(data: bytes) -> tuple[str, int, str] | None:
        try:
            doc = json.loads(data.decode())
            return str(doc["url"]), int(doc.get("gen", 0)), \
                str(doc.get("router", ""))
        except (ValueError, KeyError, UnicodeDecodeError):
            return None  # torn record (FaultyBlobStore): ignore

    def read(self, session_id: str) -> tuple[str, int, str] | None:
        """(url, gen, router) or None — missing, unreadable or torn."""
        try:
            data = self.store.get(self._key(session_id))
        except OSError:
            return None
        return self._parse(data) if data is not None else None

    def load(self) -> dict:
        """{session_id: (url, gen, router)} — the router-restart
        re-learn path."""
        out: dict = {}
        try:
            keys = self.store.list(self.PREFIX)
        except OSError as e:
            log.warning("pin-board load failed: %s", e)
            return out
        for key in keys:
            if not key.endswith(".json"):
                continue
            try:
                data = self.store.get(key)
            except OSError:
                continue
            rec = self._parse(data) if data is not None else None
            if rec is not None:
                out[key[len(self.PREFIX):-5]] = rec
        return out


class FleetRouter:
    """Routing brain (transport-agnostic; the HTTP server is below)."""

    def __init__(self, replicas, check_interval_s: float = 1.0,
                 health_timeout_s: float = 2.0,
                 forward_timeout_s: float = 600.0,
                 transport: PeerTransport | None = None,
                 registry: "trace.MetricsRegistry | None" = None,
                 max_job_pins: int = 65536,
                 router_id: str | None = None,
                 pin_store: "BlobStore | str | None" = None,
                 router_peers=(),
                 proactive_repin: bool = True,
                 suspect_misses: int = 2, dead_misses: int = 3,
                 recover_hits: int = 2,
                 signals_interval_s: float = 5.0):
        urls = [u.rstrip("/") for u in replicas]
        if not urls:
            raise ValueError("a router needs at least one replica URL")
        self.replicas = urls
        self.check_interval_s = float(check_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.forward_timeout_s = float(forward_timeout_s)
        self.transport = transport if transport is not None \
            else PeerTransport()
        self.registry = registry if registry is not None \
            else trace.MetricsRegistry()
        self.ring = HashRing(urls)
        self.router_id = router_id or f"router-{uuid.uuid4().hex[:8]}"
        self.router_peers = [u.rstrip("/") for u in router_peers]
        if isinstance(pin_store, str):
            pin_store = open_blob_store(pin_store)
        self.pin_board: PinBoard | None = (
            PinBoard(pin_store, self.router_id)
            if pin_store is not None else None)
        self.proactive_repin = bool(proactive_repin)
        self.suspect_misses = max(1, int(suspect_misses))
        self.dead_misses = max(self.suspect_misses, int(dead_misses))
        self.recover_hits = max(1, int(recover_hits))
        # Signal snapshots are a SLOWER cadence than readiness probes:
        # scraping every replica's full /healthz stats at the sweep
        # rate would tax the fleet for data an autoscaler reads every
        # few seconds at most.
        self.signals_interval_s = float(signals_interval_s)
        self._signals_last = -float("inf")
        # Board reconciliation cadence: a load() is list + one store
        # GET per pinned session, so it runs at its own (slower)
        # interval rather than per sweep — pin freshness between
        # routers only needs to beat human/autoscaler reaction time,
        # and the write-through path keeps the board itself current.
        self.board_sync_interval_s = max(float(check_interval_s), 2.0)
        # Job-pin board hygiene: records past this age are pruned by
        # the board-sync thread (replica registries are bounded — a
        # stale pin would just proxy to a 404 anyway). Pruning is a
        # list+read sweep, so it runs far below the sync cadence.
        self.job_pin_ttl_s = 3600.0
        self._job_prune_interval_s = 600.0
        self._job_prune_last = time.monotonic()
        # Job-pin write BACKLOG: pin_job runs on the per-request
        # handler thread, and a slow/hung pin store must never stall
        # the hot submit path (the same hazard the board-sync thread
        # already absorbs for session pins' reconciliation). Writes
        # drain on that thread at its cadence; bounded — overflow
        # drops the OLDEST pins, which merely fall back to the
        # probe-the-fleet path after a router death.
        self._job_pin_backlog: OrderedDict[str, str] = OrderedDict()
        self._max_job_pin_backlog = 4096
        self._lock = threading.Lock()
        self._ready: dict[str, bool] = {u: False for u in urls}
        self._reasons: dict[str, str] = {}
        self._jobs: OrderedDict[str, str] = OrderedDict()  # job -> url
        self._max_job_pins = int(max_job_pins)
        # sid -> (url, generation, writer router id): records are
        # totally ordered by (gen, router) — the order the pin board
        # shares, so concurrent routers converge on ONE owner.
        self._sessions: dict[str, tuple[str, int, str]] = {}
        # Failure detector (readyz-miss hysteresis, per replica).
        self._det_state: dict[str, str] = {u: ALIVE for u in urls}
        self._det_misses: dict[str, int] = {u: 0 for u in urls}
        self._det_hits: dict[str, int] = {u: 0 for u in urls}
        self._repin_inflight: set[str] = set()
        # Peer routers (readyz-driven peering): url -> router_id | None.
        self._peer_ids: dict[str, str | None] = {
            u: None for u in self.router_peers}
        # Per-replica signal snapshots scraped by the sweep (the
        # /fleet/signals + corrupt-aggregation source — request handlers
        # never fan out to replicas themselves).
        self._replica_stats: dict[str, dict] = {}
        self._rr = 0
        # Smooth-weighted round-robin credit per replica (session
        # creation spread): with equal weights it degenerates to plain
        # round-robin; a degraded replica (dead chips, deep queue)
        # accrues credit slower and is picked proportionally less.
        self._wrr_credit: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._board_thread: threading.Thread | None = None
        self._requests = lambda route: self.registry.counter(
            "router_requests_total", "requests by route", route=route)
        self._failovers = self.registry.counter(
            "router_failovers_total",
            "submits re-placed after the hash owner failed")
        self._repins = self.registry.counter(
            "router_session_repins_total",
            "sessions handed off to a survivor after their pinned "
            "replica died")
        self._proactive = self.registry.counter(
            "router_proactive_repins_total",
            "sessions adopted in the background by the failure "
            "detector, before any client op needed them")
        self._unroutable = self.registry.counter(
            "router_unroutable_total",
            "requests refused with no ready replica")
        self._ready_gauge = self.registry.gauge(
            "router_ready_replicas", "replicas currently routable")
        if self.pin_board is not None:
            # Router-restart re-learn: adopt the board's pins as-is.
            # Believing the board (instead of probing/adopting) is what
            # keeps a restarted router from stealing a session that is
            # alive and well on its pinned replica.
            for sid, rec in self.pin_board.load().items():
                self._sessions[sid] = rec

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "FleetRouter":
        self._sweep()  # synchronous first sweep: route from request one
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch,
                                        name="router-health", daemon=True)
        self._thread.start()
        if self.pin_board is not None:
            # Board reconciliation runs on its OWN thread: pin-store
            # I/O (possibly a slow remote object service) must never
            # delay the readiness probes above.
            self._board_thread = threading.Thread(
                target=self._board_watch, name="router-board-sync",
                daemon=True)
            self._board_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in (self._thread, self._board_thread):
            if t is not None:
                t.join(timeout=5.0)
        self._thread = None
        self._board_thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            self._sweep()

    def _sweep(self) -> None:
        for url in self.replicas:
            ready, reason = self._probe(url)
            self._set_ready(url, ready, reason)
            self._detect(url, ready)
        self._probe_router_peers()
        self._scrape_signals()
        if self.proactive_repin:
            # Re-kick while any dead replica still has pinned sessions:
            # covers pins that reached the shared board after the dead
            # transition, a standby promoted to primary by its peer's
            # death, and transiently failed adoptions.
            with self._lock:
                dead = [u for u, s in self._det_state.items()
                        if s == DEAD]
            for url in dead:
                self._kick_proactive(url)
        with self._lock:
            self._ready_gauge.set(sum(self._ready.values()))

    # -- failure detector (proactive re-pin) ---------------------------

    def _detect(self, url: str, ready: bool) -> None:
        """Readyz-miss hysteresis: ``suspect_misses`` consecutive misses
        → suspect, ``dead_misses`` → dead (fires the proactive re-pin);
        ``recover_hits`` consecutive hits to come back — a replica
        flapping at the probe cadence never oscillates the detector."""
        dead_now = False
        with self._lock:
            state = self._det_state.get(url, ALIVE)
            if ready:
                self._det_misses[url] = 0
                self._det_hits[url] = self._det_hits.get(url, 0) + 1
                if state != ALIVE \
                        and self._det_hits[url] >= self.recover_hits:
                    self._det_state[url] = ALIVE
                    log.info("detector: replica %s recovered", url)
            else:
                self._det_hits[url] = 0
                misses = self._det_misses.get(url, 0) + 1
                self._det_misses[url] = misses
                if misses >= self.dead_misses and state != DEAD:
                    self._det_state[url] = DEAD
                    dead_now = True
                elif misses >= self.suspect_misses and state == ALIVE:
                    self._det_state[url] = SUSPECT
        if dead_now:
            events.record("router_replica_dead", severity="warning",
                          url=url, misses=self.dead_misses)
            log.warning("detector: replica %s declared dead after %d "
                        "missed probes", url, self.dead_misses)
            if self.proactive_repin:
                self._kick_proactive(url)

    def _dead_pinned_sessions(self, url: str) -> list:
        """Sessions pinned to ``url`` that a proactive sweep should
        move. LOCAL map only — board records arrive via the board-sync
        thread (`_sync_board`), so the health sweep itself never blocks
        on pin-store I/O: a slow or hung board must not stall readiness
        probing for the whole fleet."""
        with self._lock:
            return [sid for sid, rec in self._sessions.items()
                    if rec[0] == url
                    and sid not in self._repin_inflight]

    def _sync_board(self) -> None:
        """One board reconciliation pass (its own thread, never the
        health sweep): pull peer routers' pins into the local map by
        (gen, router) order — a session created through a PEER router
        must be visible to THIS router's failure detector — and
        re-assert any of our OWN records a racing lower-ranked replace
        clobbered on the board. Deletions win: a record absent from the
        board is never resurrected from local memory."""
        self._flush_job_pins()
        board = self.pin_board.load()
        for sid, (url, gen, stamp) in board.items():
            self._merge_pin(sid, url, gen, stamp)
        with self._lock:
            local = dict(self._sessions)
        for sid, (url, gen, stamp) in local.items():
            rec = board.get(sid)
            if rec is not None and stamp == self.router_id \
                    and (rec[1], rec[2]) < (gen, stamp):
                self.pin_board.write(sid, url, gen)
        now = time.monotonic()
        if now - self._job_prune_last >= self._job_prune_interval_s:
            self._job_prune_last = now
            self.pin_board.prune_jobs(self.job_pin_ttl_s)

    def _board_watch(self) -> None:
        while not self._stop.wait(self.board_sync_interval_s):
            try:
                self._sync_board()
            except Exception as e:  # a sick board degrades pin sharing
                log.warning("pin-board sync failed: %s", e)

    def _kick_proactive(self, url: str) -> None:
        """Adopt the dead replica's pinned sessions on ring survivors in
        a background thread — failover becomes pre-completed work. Only
        the detector PRIMARY (lowest router id among live peered
        routers) sweeps; a standby whose primary just died takes over at
        its own next sweep (the sweep re-kicks while a dead replica
        still has pinned sessions), and any election race is benign:
        the replica-side adopt is idempotent and the pin board
        converges last-writer-wins."""
        if not self._is_detector_primary():
            log.debug("detector: standing by (peer router is primary) "
                      "for dead replica %s", url)
            return
        sids = self._dead_pinned_sessions(url)
        if not sids:
            return
        with self._lock:
            self._repin_inflight.update(sids)
        threading.Thread(target=self._proactive_repin_replica,
                         args=(url, sids), name="router-repin",
                         daemon=True).start()

    def _proactive_repin_replica(self, url: str, sids: list) -> None:
        try:
            # The cached detector flag can be stale — and unlike the
            # lazy path (where a client op needs a home NOW), the
            # proactive path has time to be conservative: re-probe
            # fresh, and adopt ONLY from a replica whose socket is
            # dead. A replica that ANSWERS — even a 503 (drain,
            # warmup-after-restart, watchdog lane swap) — is alive and
            # may be hosting (or recovering) these sessions; stealing
            # them would double-host. route_session_ex still covers
            # the alive-but-unready case when a client op actually
            # needs to move.
            ready, reason = self._probe(url)
            self._set_ready(url, ready, reason)
            if ready or not reason.startswith("unreachable"):
                log.info("proactive re-pin of %s aborted: replica "
                         "answered its probe (%s)", url,
                         reason or "ready")
                return
            for sid in sids:
                t0 = time.monotonic()
                with self._lock:
                    still_dead = self._det_state.get(url) == DEAD
                    rec = self._sessions.get(sid)
                    pin = rec[0] if rec is not None else None
                if not still_dead or pin != url:
                    continue
                new, unknown = self._adopt_on_survivor_ex(sid)
                if new is not None:
                    self._proactive.inc()
                    events.record(
                        "session_proactive_repin", severity="warning",
                        session_id=sid, from_url=url, to_url=new,
                        seconds=round(time.monotonic() - t0, 3))
                elif unknown:
                    # Definitively ended fleet-wide (every survivor
                    # answered 404, no adoptable stream): drop the pin
                    # so the sweep stops hunting a ghost.
                    self.unpin_session(sid)
        finally:
            with self._lock:
                self._repin_inflight.difference_update(sids)

    def detector_state(self, url: str) -> str:
        with self._lock:
            return self._det_state.get(url, ALIVE)

    # -- router peering -------------------------------------------------

    def _probe_router_peers(self) -> None:
        for peer in self.router_peers:
            rid = None
            try:
                status, _, body = self.transport.get(
                    f"{peer}/healthz", timeout_s=self.health_timeout_s)
                if status == 200:
                    doc = json.loads(body.decode())
                    rid = doc.get("router_id")
                    if rid is None:
                        # A 200 WITHOUT a router id is not a router
                        # (a replica URL listed in --router-peers by
                        # mistake) — it must not participate in the
                        # primary election, where a placeholder id
                        # would outrank every real router and silently
                        # disable proactive failover fleet-wide.
                        log.warning(
                            "router peer %s answered /healthz without "
                            "a router_id (a replica URL in "
                            "--router-peers?); ignoring for election",
                            peer)
            except (OSError, ValueError, UnicodeDecodeError):
                rid = None
            with self._lock:
                self._peer_ids[peer] = rid

    def _is_detector_primary(self) -> bool:
        with self._lock:
            alive = [rid for rid in self._peer_ids.values()
                     if rid is not None]
        return all(self.router_id <= rid for rid in alive)

    # -- replica signal scraping (autoscaler + corrupt aggregation) ----

    #: /healthz keys the sweep snapshots per replica.
    _SIGNAL_KEYS = ("replica_id", "queue_depth", "queue_capacity",
                    "workers_alive", "sessions", "governor",
                    "content_cache", "lanes", "store", "handoff")

    def _scrape_signals(self) -> None:
        now = time.monotonic()
        if now - self._signals_last < self.signals_interval_s:
            return
        self._signals_last = now
        for url in self.ready_replicas():
            try:
                status, _, body = self.transport.get(
                    f"{url}/healthz", timeout_s=self.health_timeout_s)
                if status != 200:
                    continue
                doc = json.loads(body.decode())
            except (OSError, ValueError, UnicodeDecodeError):
                continue
            snap = {k: doc.get(k) for k in self._SIGNAL_KEYS
                    if k in doc}
            snap["t_mono"] = time.monotonic()
            with self._lock:
                self._replica_stats[url] = snap

    def signals(self) -> dict:
        """``GET /fleet/signals``: the aggregate an autoscaler consumes
        — queue pressure, lane/session occupancy, shed + overload state,
        device-memory pressure — computed from the sweep's cached
        per-replica snapshots (a scrape never fans out to the fleet)."""
        with self._lock:
            snaps = {u: dict(s) for u, s in self._replica_stats.items()}
            ready = [u for u in self.replicas if self._ready.get(u)]
        queue_depth = queue_cap = sessions_live = lanes_total = 0
        shed_total = devices_dead = 0
        workers = 0
        mem_frac = 0.0
        overload = 0
        revives_total = span_width_total = 0
        for url in ready:
            s = snaps.get(url)
            if not s:
                continue
            queue_depth += int(s.get("queue_depth") or 0)
            queue_cap += int(s.get("queue_capacity") or 0)
            workers += int(s.get("workers_alive") or 0)
            sess = s.get("sessions") or {}
            sessions_live += int(sess.get("live") or 0)
            lane_stats = s.get("lanes") or {}
            lanes = lane_stats.get("lanes") or []
            dead = lane_stats.get("devices_dead") or []
            # A dead chip's lanes are not capacity: the autoscaler must
            # see the fleet as it runs, not as it was provisioned.
            lanes_total += sum(1 for ln in lanes
                               if ln.get("device") not in dead)
            devices_dead += len(dead)
            revives_total += int(lane_stats.get("revives_total") or 0)
            # Span truth, not just a count: the autoscaler (and the
            # weighted placement above) must see how wide the sharded
            # tier actually runs fleet-wide.
            span_width_total += len(lane_stats.get("span_devices")
                                    or [])
            gov = s.get("governor") or {}
            overload = max(overload, int(gov.get("level") or 0))
            mem_frac = max(mem_frac,
                           float(gov.get("memory_pressure") or 0.0))
            shed_total += sum(
                int(v) for v in (gov.get("shed_total") or {}).values())
        return {
            "router_id": self.router_id,
            "ready_replicas": len(ready),
            "replicas_total": len(self.replicas),
            "queue_depth_total": queue_depth,
            "queue_capacity_total": queue_cap,
            "queue_frac": (round(queue_depth / queue_cap, 4)
                           if queue_cap else 0.0),
            "sessions_live_total": sessions_live,
            "worker_lanes_total": workers,
            "device_lanes_total": lanes_total,
            "devices_dead_total": devices_dead,
            "device_revives_total": revives_total,
            "span_devices_total": span_width_total,
            "overload_level_max": overload,
            "memory_pressure_max": round(mem_frac, 4),
            "shed_total": shed_total,
            "unroutable_total": int(self._unroutable.value),
        }

    def corrupt_blob_summary(self) -> dict:
        """Fleet-wide content-cache corruption view for ``/fleet``:
        quarantined-blob counters summed over ready replicas (blob
        corruption is a VOLUME problem — per-replica counters hide a
        shared sick disk)."""
        with self._lock:
            snaps = {u: dict(s) for u, s in self._replica_stats.items()}
        per = {}
        corrupt = quarantined = 0
        for url, s in snaps.items():
            cc = s.get("content_cache") or {}
            c = int(cc.get("corrupt_quarantined") or 0)
            q = int(cc.get("quarantined_objects") or 0)
            per[url] = {"corrupt_quarantined": c,
                        "quarantined_objects": q}
            corrupt += c
            quarantined += q
        return {"corrupt_quarantined_total": corrupt,
                "quarantined_objects_total": quarantined,
                "per_replica": per}

    def _probe(self, url: str) -> tuple[bool, str]:
        try:
            status, _, body = self.transport.get(
                f"{url}/readyz", timeout_s=self.health_timeout_s)
        except OSError as e:
            return False, f"unreachable ({e})"
        if status == 200:
            return True, ""
        try:
            reasons = json.loads(body.decode()).get("reasons", [])
        except (ValueError, UnicodeDecodeError):
            reasons = []
        return False, "; ".join(reasons) or f"readyz {status}"

    def _set_ready(self, url: str, ready: bool, reason: str = "") -> None:
        with self._lock:
            was = self._ready.get(url)
            self._ready[url] = ready
            self._reasons[url] = reason
        if was is not None and was != ready:
            log.info("replica %s -> %s%s", url,
                     "ready" if ready else "not ready",
                     f" ({reason})" if reason else "")
            events.record("router_replica_health", severity="info"
                          if ready else "warning", url=url, ready=ready,
                          reason=reason)

    # -- membership views ----------------------------------------------

    def ready_replicas(self) -> list[str]:
        with self._lock:
            return [u for u in self.replicas if self._ready.get(u)]

    def _down(self) -> set[str]:
        with self._lock:
            return {u for u in self.replicas if not self._ready.get(u)}

    # -- placement ------------------------------------------------------

    def replica_weight(self, url: str) -> float:
        """Health-aware load weight from the sweep's cached /healthz
        snapshot: the replica's live-device fraction (a 7/8-chip
        replica weighs 0.875 — it IS 7/8ths of a replica) scaled down
        by its queue fill. 1.0 with no snapshot yet (cold start must
        not zero anybody out), floored above 0 so a ready-but-strained
        replica stays reachable rather than starved."""
        with self._lock:
            s = self._replica_stats.get(url)
        if not s:
            return 1.0
        w = 1.0
        lane_stats = s.get("lanes") or {}
        devices = lane_stats.get("devices") or []
        dead = lane_stats.get("devices_dead")
        # Dead-count over the pool's full device list — NOT the
        # "devices_live" field, which counts only health-TRACKED chips
        # (lane devices + convicted span members) and would read a
        # healthy 8-chip/2-lane replica as 2/8 alive.
        if devices and isinstance(dead, list):
            w *= max(0.0, 1.0 - min(1.0, len(dead) / len(devices)))
        try:
            depth = float(s.get("queue_depth") or 0)
            cap = float(s.get("queue_capacity") or 0)
        except (TypeError, ValueError):
            depth = cap = 0.0
        if cap > 0:
            w *= max(0.0, 1.0 - min(1.0, depth / cap))
        return max(w, 0.05)

    def place_submit(self, body: bytes) -> list[str]:
        """Candidate replicas for one submit: the consistent-hash
        preference list, load-weighted. Each candidate keeps its ring
        rank with probability ``weight / max_weight``, decided by a
        DETERMINISTIC per-(key, replica) draw — so duplicates of the
        same bytes still land on the same replica (the content-cache
        affinity contract holds exactly), equal weights reproduce the
        pure ring order bit-for-bit, and a degraded replica sheds a
        proportional slice of its keyspace to the next preference
        instead of all (thundering re-key) or none (7/8 chips, 8/8
        load)."""
        key = hashlib.sha256(body).hexdigest()
        pref = self.ring.preference(key, avoid=self._down())
        if len(pref) < 2:
            return pref
        weights = {u: self.replica_weight(u) for u in pref}
        w_max = max(weights.values())
        if w_max <= 0:
            return pref
        kept, demoted = [], []
        for u in pref:
            # Uniform in [0, 1) from the (key, replica) pair — stable
            # across calls, independent across replicas.
            draw = int(hashlib.sha256(
                f"{key}|{u}".encode()).hexdigest()[:8], 16) / 0x100000000
            (kept if draw < weights[u] / w_max else demoted).append(u)
        return kept + demoted

    def place_session(self, session_id: str) -> list[str]:
        return self.ring.preference(session_id, avoid=self._down())

    def next_replica(self) -> str | None:
        """Session creation spread: smooth weighted round-robin over
        ready replicas. Equal weights (no signals scraped yet) cycle
        exactly like the historical round-robin; a replica reporting
        dead chips or a deep queue (``replica_weight``) is picked
        proportionally less often."""
        ready = self.ready_replicas()
        if not ready:
            return None
        weights = {u: self.replica_weight(u) for u in ready}
        total = sum(weights.values())
        with self._lock:
            credit = self._wrr_credit
            for gone in [u for u in credit if u not in weights]:
                credit.pop(gone)
            for u in ready:
                credit[u] = credit.get(u, 0.0) + weights[u]
            pick = max(ready, key=lambda u: credit[u])
            credit[pick] -= total
            return pick

    # -- pin bookkeeping -------------------------------------------------

    def pin_job(self, job_id: str, url: str) -> None:
        with self._lock:
            self._jobs[job_id] = url
            while len(self._jobs) > self._max_job_pins:
                self._jobs.popitem(last=False)
            if self.pin_board is not None:
                # Enqueue only: the board write is store I/O and this
                # is the per-submit hot path — the board-sync thread
                # drains the backlog (_flush_job_pins). Sharing the
                # placement is what spares a restarted or peer router
                # the probe-the-whole-fleet /status sweep.
                self._job_pin_backlog[job_id] = url
                while len(self._job_pin_backlog) \
                        > self._max_job_pin_backlog:
                    self._job_pin_backlog.popitem(last=False)

    def _flush_job_pins(self) -> int:
        """Drain the job-pin backlog to the board (board-sync thread;
        also called directly by tests). Store failures are counted by
        write_job and the pin simply isn't shared — routing never
        depends on it."""
        with self._lock:
            pending = list(self._job_pin_backlog.items())
            self._job_pin_backlog.clear()
        for job_id, url in pending:
            self.pin_board.write_job(job_id, url)
        return len(pending)

    def job_url(self, job_id: str) -> str | None:
        with self._lock:
            url = self._jobs.get(job_id)
        if url is not None:
            return url
        if self.pin_board is not None:
            # Local miss (router restart, or the job was admitted
            # through a peer): believe the shared board before the
            # caller falls back to probing every ready replica.
            url = self.pin_board.read_job(job_id)
            if url is not None:
                with self._lock:
                    self._jobs[job_id] = url
                    while len(self._jobs) > self._max_job_pins:
                        self._jobs.popitem(last=False)
        return url

    def _merge_pin(self, session_id: str, url: str, gen: int,
                   stamp: str) -> bool:
        """Adopt one pin record into the local map iff it outranks what
        we know ((gen, router) lexicographic — the pin board's total
        order; re-checked INSIDE the lock so a concurrent higher-ranked
        adoption can never be rolled back). True when adopted."""
        with self._lock:
            known = self._sessions.get(session_id)
            if known is not None and (known[1], known[2]) >= (gen, stamp):
                return False
            self._sessions[session_id] = (url, gen, stamp)
            return True

    def pin_session(self, session_id: str, url: str) -> None:
        with self._lock:
            known = self._sessions.get(session_id)
            gen = (known[1] if known is not None else 0) + 1
            self._sessions[session_id] = (url, gen, self.router_id)
        if self.pin_board is not None:
            # Write-through OUTSIDE the lock (board I/O must never
            # stall routing); the board's (gen, router) order keeps
            # concurrent routers convergent.
            self.pin_board.write(session_id, url, gen)

    def session_url(self, session_id: str) -> str | None:
        with self._lock:
            pin = self._sessions.get(session_id)
        if pin is not None:
            return pin[0]
        if self.pin_board is not None:
            # Local miss (pin created through a peer router after our
            # restart re-learn): believe the shared board.
            rec = self.pin_board.read(session_id)
            if rec is not None:
                self._merge_pin(session_id, *rec)
                return rec[0]
        return None

    def _fresher_board_pin(self, session_id: str,
                           avoid: str | None) -> str | None:
        """A pin-board record OUTRANKING our local knowledge, pointing
        at a READY replica that is not ``avoid`` — the peer router
        already moved this session; believe it instead of adopting a
        second time."""
        if self.pin_board is None:
            return None
        rec = self.pin_board.read(session_id)
        if rec is None:
            return None
        url, gen, stamp = rec
        with self._lock:
            ready = self._ready.get(url, False)
        if url == avoid or not ready:
            return None
        if not self._merge_pin(session_id, url, gen, stamp):
            return None
        return url

    def unpin_session(self, session_id: str) -> None:
        with self._lock:
            self._sessions.pop(session_id, None)
        if self.pin_board is not None:
            self.pin_board.clear(session_id)

    # -- forwarding ------------------------------------------------------

    def forward(self, url: str, method: str, path: str,
                body: bytes | None = None, headers: dict | None = None
                ) -> tuple[int, dict, bytes]:
        """One bounded-proxy hop. OSError propagates (connection-level
        death) and flips the replica not-ready immediately — the health
        sweep would notice within a second anyway, but the failing
        request IS the freshest probe we have."""
        try:
            return self.transport.request(
                method, url + path, body=body, headers=headers,
                timeout_s=self.forward_timeout_s)
        except OSError:
            self._set_ready(url, False, "request failed")
            raise

    # -- session handoff --------------------------------------------------

    def adopt_on_survivor(self, session_id: str) -> str | None:
        """Walk the ring's survivors asking each to adopt the session
        from the shared handoff stream; returns the new pin, or None
        when nobody could (no ready replicas, or no handoff volume)."""
        return self._adopt_on_survivor_ex(session_id)[0]

    def _adopt_on_survivor_ex(self, session_id: str
                              ) -> tuple[str | None, bool]:
        """``(new pin, definitively_unknown)``: the second element is
        True only when at least one survivor ANSWERED the adoption and
        every answer was a 404 — no adoptable handoff stream exists
        (the session ended, or never rode a handoff volume), so a
        retry cannot help. Transport failures and busy refusals (503)
        keep it False — those warrant the caller's retryable 503."""
        old = self.session_url(session_id)
        fresher = self._fresher_board_pin(session_id, avoid=old)
        if fresher is not None:
            # A peer router already re-pinned this session (its board
            # record outran our knowledge): converge on ITS owner
            # instead of adopting a second copy.
            return fresher, False
        attempted = 0
        uncertain = 0      # transport failures + non-404 refusals
        for url in self.place_session(session_id):
            if url == old:
                continue
            attempted += 1
            try:
                status, _, body = self.forward(
                    url, "POST", f"/session/{session_id}/adopt")
            except OSError:
                uncertain += 1
                continue
            if status == 200:
                self.pin_session(session_id, url)
                self._repins.inc()
                events.record("session_repinned", severity="warning",
                              session_id=session_id, from_url=old,
                              to_url=url)
                log.warning("session %s re-pinned %s -> %s",
                            session_id, old, url)
                return url, False
            if status != 404:
                uncertain += 1
            log.warning("survivor %s refused adoption of %s: %s %s",
                        url, session_id, status, body[:200])
        return None, attempted > 0 and uncertain == 0

    def route_session(self, session_id: str) -> str | None:
        return self.route_session_ex(session_id)[0]

    def route_session_ex(self, session_id: str
                         ) -> tuple[str | None, bool]:
        """The replica a session op should go to: the live pin; for an
        UNKNOWN session (router restart — pins are memory) the replica
        that already holds it live, re-learned by probing; else a
        survivor that successfully adopts. Probing before adopting
        matters: stealing a session from a healthy replica would
        double-host it and pay an adoption replay for a failover that
        never happened.

        Returns ``(replica, definitively_unknown)``. ``(None, True)``
        = every ready replica answered and denied the session AND no
        adoptable handoff stream exists — the caller should 404, not
        tell the client to retry a session that already ended.
        ``(None, False)`` = nowhere to send it right now (no ready
        replicas, or transport failures muddied the sweep) — caller
        503s and the client retries."""
        url = self.session_url(session_id)
        if url is not None:
            with self._lock:
                pinned_ready = self._ready.get(url, False)
            if pinned_ready:
                return url, False
            # The sweep's cached flag can be STALE (one missed probe
            # while the replica was busy). Adoption is expensive and —
            # worse — steals the session; re-probe the pin fresh and
            # believe a live answer before walking the survivors.
            ready, reason = self._probe(url)
            self._set_ready(url, ready, reason)
            if ready:
                return url, False
            # A pin is evidence the session recently lived on a replica
            # we can no longer ask — its fate is UNKNOWN until a
            # survivor adopts or the replica recovers, so never 404.
            return self._adopt_on_survivor_ex(session_id)[0], False
        probed = 0
        uncertain = 0      # transport failures + non-(200|404) answers
        for candidate in self.ready_replicas():
            probed += 1
            try:
                status, _, _ = self.forward(
                    candidate, "GET", f"/session/{session_id}")
            except OSError:
                uncertain += 1
                continue
            if status == 200:
                self.pin_session(session_id, candidate)
                return candidate, False
            if status != 404:
                uncertain += 1
        adopted, adopt_unknown = self._adopt_on_survivor_ex(session_id)
        if adopted is not None:
            return adopted, False
        return None, probed > 0 and uncertain == 0 and adopt_unknown

    # -- inspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = {
                "router_id": self.router_id,
                "replicas": [
                    {"url": u, "ready": self._ready.get(u, False),
                     "detector": self._det_state.get(u, ALIVE),
                     "reason": self._reasons.get(u, "")}
                    for u in self.replicas],
                "routers": [
                    {"url": u, "router_id": rid, "alive": rid is not None}
                    for u, rid in self._peer_ids.items()],
                "sessions_pinned": {sid: rec[0] for sid, rec
                                    in self._sessions.items()},
                "jobs_pinned": len(self._jobs),
                "failovers": int(self._failovers.value),
                "session_repins": int(self._repins.value),
                "proactive_repins": int(self._proactive.value),
                "pin_board": (None if self.pin_board is None else {
                    "backend": self.pin_board.store.stats()
                    .get("backend"),
                    "write_failures": self.pin_board.write_failures}),
            }
        out["content_cache"] = self.corrupt_blob_summary()
        return out

    def metrics_text(self) -> str:
        with self._lock:
            self._ready_gauge.set(sum(self._ready.values()))
        return self.registry.prometheus_text()


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


class _RouterHandler(BaseHTTPRequestHandler):
    router: FleetRouter  # bound by RouterHTTPServer

    protocol_version = "HTTP/1.1"
    timeout = 120.0

    def _json(self, obj, status=200):
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _relay(self, status: int, headers: dict, body: bytes) -> None:
        self.send_response(status)
        for k, v in headers.items():
            if k.lower() in ("content-type", "retry-after") \
                    or k.lower().startswith("x-"):
                self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _no_replica(self) -> None:
        self.router._unroutable.inc()
        self._json({"error": {"type": "NoReadyReplicaError",
                              "message": "no ready replica in the "
                                         "fleet; retry shortly"}}, 503)

    def _session_unknown(self, session_id: str) -> None:
        # Definitive (route_session_ex's second element): every ready
        # replica denied the session and no handoff stream exists — a
        # retryable 503 here would have clients polling an ended
        # session forever, each poll costing a full fleet sweep.
        self._json({"error": {"type": "UnknownSessionError",
                              "message": f"unknown session "
                                         f"{session_id!r} on every "
                                         "ready replica"}}, 404)

    def _read_body(self) -> bytes | None:
        length = int(self.headers.get("Content-Length", 0))
        if length < 0 or length > MAX_SUBMIT_BYTES:
            self.close_connection = True
            self._json({"error": {"type": "StackFormatError",
                                  "message": f"Content-Length {length} "
                                             f"outside [0, "
                                             f"{MAX_SUBMIT_BYTES}]"}},
                       400)
            return None
        return self.rfile.read(length) if length else b""

    def _fwd_headers(self) -> dict:
        return {k: self.headers[k] for k in _FORWARD_HEADERS
                if self.headers.get(k)}

    # ------------------------------------------------------------------

    def do_POST(self):
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        body = self._read_body()
        if body is None:
            return
        if url.path == "/submit":
            self._submit(body)
        elif parts and parts[0] == "session":
            self._session_op(parts, body)
        else:
            self._json({"error": "not found"}, 404)

    def _submit(self, body: bytes) -> None:
        r = self.router
        r._requests("submit").inc()
        candidates = r.place_submit(body)
        if not candidates:
            self._no_replica()
            return
        for i, replica in enumerate(candidates):
            try:
                status, hdrs, resp = r.forward(
                    replica, "POST", "/submit", body=body,
                    headers=self._fwd_headers())
            except OSError:
                r._failovers.inc()
                continue
            if status == 503 and i + 1 < len(candidates):
                # Draining/unready replica the sweep hasn't flagged yet:
                # fail over along the ring like a dead one. 429 is NOT
                # failed over — backpressure is load, and shoving the
                # burst onto the next replica just moves the hot spot.
                r._failovers.inc()
                continue
            if status == 200:
                try:
                    job_id = json.loads(resp.decode()).get("job_id")
                except (ValueError, UnicodeDecodeError):
                    job_id = None
                if job_id:
                    r.pin_job(job_id, replica)
            self._relay(status, hdrs, resp)
            return
        self._no_replica()

    def _session_op(self, parts: list, body: bytes) -> None:
        r = self.router
        if len(parts) == 1:
            # POST /session — create on the round-robin pick.
            r._requests("session_create").inc()
            replica = r.next_replica()
            if replica is None:
                self._no_replica()
                return
            try:
                status, hdrs, resp = r.forward(
                    replica, "POST", "/session", body=body,
                    headers=self._fwd_headers())
            except OSError:
                self._no_replica()
                return
            if status == 200:
                try:
                    sid = json.loads(resp.decode()).get("session_id")
                except (ValueError, UnicodeDecodeError):
                    sid = None
                if sid:
                    r.pin_session(sid, replica)
            self._relay(status, hdrs, resp)
            return
        sid = parts[1]
        r._requests("session_op").inc()
        replica, unknown = r.route_session_ex(sid)
        if replica is None:
            self._session_unknown(sid) if unknown else self._no_replica()
            return
        try:
            status, hdrs, resp = r.forward(
                replica, "POST", "/" + "/".join(parts), body=body,
                headers=self._fwd_headers())
        except OSError:
            # The pin died mid-request: one handoff retry, then give up
            # (the client's own retry policy owns anything beyond).
            replica = r.adopt_on_survivor(sid)
            if replica is None:
                self._no_replica()
                return
            try:
                status, hdrs, resp = r.forward(
                    replica, "POST", "/" + "/".join(parts), body=body,
                    headers=self._fwd_headers())
            except OSError:
                self._no_replica()
                return
        if len(parts) == 3 and parts[2] == "finalize" and status == 200:
            try:
                job_id = json.loads(resp.decode()).get("job_id")
            except (ValueError, UnicodeDecodeError):
                job_id = None
            if job_id:
                r.pin_job(job_id, replica)
        self._relay(status, hdrs, resp)

    # ------------------------------------------------------------------

    def do_GET(self):
        url = urlparse(self.path)
        r = self.router
        if url.path == "/healthz":
            self._json({"ok": True, "router": True, **r.stats()})
        elif url.path == "/readyz":
            ready = bool(r.ready_replicas())
            self._json({"ready": ready,
                        "reasons": ([] if ready
                                    else ["no ready replicas"])},
                       200 if ready else 503)
        elif url.path == "/fleet":
            self._json(r.stats())
        elif url.path == "/fleet/signals":
            self._json(r.signals())
        elif url.path == "/metrics":
            data = r.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif url.path in ("/status", "/result"):
            self._job_query(url)
        elif url.path.startswith("/session/"):
            parts = [p for p in url.path.split("/") if p]
            if len(parts) < 2:     # bare "/session/" — no id to route
                self._json({"error": "not found"}, 404)
                return
            replica, unknown = r.route_session_ex(parts[1])
            if replica is None:
                self._session_unknown(parts[1]) if unknown \
                    else self._no_replica()
                return
            self._proxy_get(replica, self.path)
        else:
            self._json({"error": "not found"}, 404)

    def _job_query(self, url) -> None:
        r = self.router
        job_id = (parse_qs(url.query).get("id") or [""])[0]
        replica = r.job_url(job_id)
        if replica is not None:
            self._proxy_get(replica, self.path)
            return
        # Unknown placement (router restarted, or the job predates us):
        # probe the fleet — first replica that knows the id wins the pin.
        for candidate in r.ready_replicas():
            try:
                status, hdrs, body = r.forward(candidate, "GET",
                                               self.path)
            except OSError:
                continue
            if status != 404:
                r.pin_job(job_id, candidate)
                self._relay(status, hdrs, body)
                return
        self._json({"error": f"unknown job {job_id!r} on every ready "
                             "replica"}, 404)

    def _proxy_get(self, replica: str, path: str) -> None:
        try:
            status, hdrs, body = self.router.forward(replica, "GET", path)
        except OSError:
            self._json({"error": {"type": "ReplicaUnreachableError",
                                  "message": f"replica {replica} did "
                                             "not answer"}}, 503)
            return
        self._relay(status, hdrs, body)

    def do_DELETE(self):
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if len(parts) == 2 and parts[0] == "session":
            replica, unknown = self.router.route_session_ex(parts[1])
            if replica is None:
                self._session_unknown(parts[1]) if unknown \
                    else self._no_replica()
                return
            try:
                status, hdrs, body = self.router.forward(
                    replica, "DELETE", self.path)
            except OSError:
                self._no_replica()
                return
            if status == 200:
                self.router.unpin_session(parts[1])
            self._relay(status, hdrs, body)
        else:
            self._json({"error": "not found"}, 404)

    def log_message(self, fmt, *args):
        log.debug("router: " + fmt, *args)


class RouterHTTPServer:
    """Owns the router's listener thread (mirrors ServeHTTPServer)."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        handler = type("BoundRouterHandler", (_RouterHandler,),
                       {"router": router})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="router-http", daemon=True)
        self._started = False

    def start(self) -> "RouterHTTPServer":
        self.router.start()
        self._thread.start()
        self._started = True
        log.info("fleet router on :%d (%d replica(s))", self.port,
                 len(self.router.replicas))
        return self

    def stop(self) -> None:
        self.router.stop()
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()
