"""Continuous-batching reconstruction service.

The compute core (`models/pipeline.reconstruct`) is serving-shaped — one
static-shape XLA program whose batch-8 lane amortizes to a fraction of the
single-shot latency — but every other entry point in the repo is a one-shot
CLI. This package is the missing layer between "a fast kernel" and "a
service": admission control in front, batched static-shape programs behind,
the same shape the high-throughput pipelines in the related literature use
(Gaussian-Plus-SDF SLAM's decoupled 150+ fps pipeline, AGS's admission
gating — PAPERS.md).

Data path::

    client ── POST /submit ──▶ AdmissionQueue (bounded; priorities,
                               deadlines, reject-with-retry-after)
                   │
                   ▼
             BucketBatcher     pads (H, W) up to a configured bucket,
                               coalesces same-bucket jobs into B ∈
                               {1, 2, 4, 8} batches, flushes on
                               batch-full or max-linger
                   │
                   ▼
             ProgramCache      AOT-compiled executables keyed by
                               (B, F, H, W, bits, configs); startup
                               warmup, LRU eviction, hit/miss counters
                   │
                   ▼
             DeviceWorker(s)   run the batch, per-job postprocess
                               (compact → PLY, or the models/meshing
                               tail → STL), per-job fault containment
                               on the PR-3 health taxonomy

Everything is stdlib + the existing pipeline: the HTTP front end is a
``ThreadingHTTPServer`` like `hw/command_server.py`, metrics ride
`utils/trace.MetricsRegistry`, and errors are `health.ScanFault` subclasses
so one poisoned stack degrades that job, not the process.

Entry points: ``python -m structured_light_for_3d_model_replication_tpu.cli
serve`` (front end), :class:`~.service.ReconstructionService` (in-process),
:class:`~.client.ServeClient` (stdlib client). docs/SERVING.md has the
architecture and tuning guide.
"""

from .batcher import Batch, BucketBatcher, BucketKey, bucket_for
from .blobstore import (
    BlobFaultPlan,
    BlobStore,
    FaultyBlobStore,
    HTTPObjectClient,
    InMemoryObjectClient,
    LocalDirStore,
    ObjectStore,
    ObjectStoreServer,
    open_blob_store,
)
from .cache import ContentCache, ProgramCache, ProgramKey, content_key
from .client import ServeClient, TransportError
from .fleet import (
    FaultyPeerTransport,
    HashRing,
    PeerCacheClient,
    PeerFaultPlan,
    PeerTransport,
)
from .governor import BreakerOpenError, CircuitBreaker, GovernorParams, \
    LoadShedError, OverloadGovernor
from .jobs import (
    AdmissionQueue,
    Job,
    JobRejected,
    QueueClosedError,
    QueueFullError,
    ServeError,
    StackFormatError,
)
from .lanes import DeviceLane, DeviceLanePool
from .router import FleetRouter, PinBoard, RouterHTTPServer
from .service import ReconstructionService, ServeConfig, ServeHTTPServer
from .sessions import SessionLimitError, SessionManager, UnknownSessionError
from .store import JournalStore, RecoveredState, SessionStreamStore, \
    read_live_state
from .tenants import TenantQuotaError, TenantQuotas
from .worker import DeviceWorker

__all__ = [
    "AdmissionQueue",
    "Batch",
    "BlobFaultPlan",
    "BlobStore",
    "BreakerOpenError",
    "BucketBatcher",
    "BucketKey",
    "CircuitBreaker",
    "ContentCache",
    "FaultyBlobStore",
    "HTTPObjectClient",
    "InMemoryObjectClient",
    "LocalDirStore",
    "ObjectStore",
    "ObjectStoreServer",
    "PinBoard",
    "TenantQuotaError",
    "TenantQuotas",
    "open_blob_store",
    "DeviceLane",
    "DeviceLanePool",
    "DeviceWorker",
    "FaultyPeerTransport",
    "FleetRouter",
    "GovernorParams",
    "HashRing",
    "Job",
    "JobRejected",
    "JournalStore",
    "LoadShedError",
    "OverloadGovernor",
    "PeerCacheClient",
    "PeerFaultPlan",
    "PeerTransport",
    "ProgramCache",
    "ProgramKey",
    "QueueClosedError",
    "QueueFullError",
    "ReconstructionService",
    "RecoveredState",
    "RouterHTTPServer",
    "ServeClient",
    "ServeConfig",
    "ServeError",
    "ServeHTTPServer",
    "SessionLimitError",
    "SessionManager",
    "SessionStreamStore",
    "StackFormatError",
    "TransportError",
    "UnknownSessionError",
    "bucket_for",
    "content_key",
    "read_live_state",
]
