"""Per-tenant admission quotas: token buckets at the service door.

The governor (serve/governor.py) protects the service from AGGREGATE
overload; this module protects tenants from EACH OTHER — one hot client
replaying scans in a loop must not eat the whole queue and starve
everyone. Each tenant (the ``X-Tenant`` request header; ``anon`` when
absent) gets a token bucket: ``rate_per_s`` sustained admissions per
second with ``burst`` of headroom. An empty bucket refuses the
admission with :class:`TenantQuotaError` — a retryable
:class:`~.jobs.JobRejected` (HTTP 429 + Retry-After carrying the exact
refill wait), so well-behaved clients back off with the same taxonomy
machinery every other rejection uses.

Accounting rules:

* the token spend sits AFTER the governor and BEFORE the queue: a
  fleet-side refusal (breaker open, shedding) must not drain a
  tenant's bucket for work that never ran, an over-budget tenant must
  not occupy queue headroom, and a queue/session-registry rejection
  after the spend is REFUNDED (:meth:`TenantQuotas.refund`) for the
  same reason. The HTTP layer's headers-time probe uses the
  non-spending :meth:`TenantQuotas.check` (leading with the cheapest
  gate), so the authoritative spend happens exactly once;
* content-cache hits are exempt by placement (the service consults the
  cache upstream of every admission gate — a cached answer costs the
  fleet nothing, charging for it would punish deduplication);
* per-tenant traffic is visible as ``serve_tenant_admitted_total`` /
  ``serve_tenant_rejected_total`` {tenant=...} counters. Tenant label
  cardinality is bounded: ids are sanitized to ``[A-Za-z0-9_-]{1,32}``
  (anything else collapses to ``other``) and the bucket table is a
  bounded LRU — an attacker minting random tenant ids recycles buckets
  instead of growing memory.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..utils.log import get_logger
from .jobs import JobRejected

log = get_logger(__name__)

#: The tenant every unlabelled request bills to.
DEFAULT_TENANT = "anon"

#: Floor on a cost-weighted spend: even a thumbnail stack pays
#: something (a zero-cost admission would make the quota a no-op for
#: tiny-stack floods, the exact abuse quotas exist for).
MIN_STACK_COST = 0.125

_ALLOWED = set("abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-")


def stack_cost(height: int, width: int) -> float:
    """Cost-weighted token spend for one capture stack: its MEGAPIXELS
    (floored at :data:`MIN_STACK_COST`), so a 1080p stack (~2.07 MP)
    spends ~2 tokens where a 240p one spends the 0.125 floor —
    ``rate_per_s`` becomes sustained megapixels/s per tenant instead of
    submits/s. Refunds must pass the SAME cost back
    (:meth:`TenantQuotas.refund` — the refund-parity contract)."""
    return max(MIN_STACK_COST, (int(height) * int(width)) / 1.0e6)


def sanitize_tenant(raw: str | None) -> str:
    """Metric-label-safe tenant id: empty/None → ``anon``; anything
    outside ``[A-Za-z0-9_-]{1,32}`` → ``other`` (bounded label
    cardinality beats per-tenant fidelity for hostile ids)."""
    if not raw:
        return DEFAULT_TENANT
    if len(raw) > 32 or any(c not in _ALLOWED for c in raw):
        return "other"
    return raw


class TenantQuotaError(JobRejected):
    """Tenant over its admission budget — retry after the bucket
    refills (or spread load over more time; the fleet is fine, YOUR
    lane is full)."""

    retryable = True

    def __init__(self, tenant: str, retry_after_s: float):
        super().__init__(
            f"tenant {tenant!r} admission quota exhausted; retry in "
            f"{retry_after_s:.2f}s")
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class TenantQuotas:
    """Bounded table of per-tenant token buckets.

    ``rate_per_s`` tokens accrue continuously up to ``burst``; one
    admission spends one token. ``clock`` is injectable (monotonic
    seconds) so tests drive time deterministically."""

    def __init__(self, rate_per_s: float, burst: int,
                 registry, max_tenants: int = 1024,
                 clock=time.monotonic):
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        self.rate_per_s = float(rate_per_s)
        self.burst = max(1, int(burst))
        self.max_tenants = max(1, int(max_tenants))
        self._clock = clock
        self._lock = threading.Lock()
        # tenant -> [tokens, last_refill_t]; LRU-bounded.
        self._buckets: OrderedDict[str, list] = OrderedDict()
        self._admitted = lambda tenant: registry.counter(
            "serve_tenant_admitted_total",
            "admissions accepted per tenant", tenant=tenant)
        self._rejected = lambda tenant: registry.counter(
            "serve_tenant_rejected_total",
            "admissions refused by the tenant quota", tenant=tenant)

    def _bucket(self, tenant: str, now: float) -> list:
        b = self._buckets.get(tenant)
        if b is None:
            b = [float(self.burst), now]
            self._buckets[tenant] = b
            while len(self._buckets) > self.max_tenants:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(tenant)
            tokens, last = b
            b[0] = min(float(self.burst),
                       tokens + (now - last) * self.rate_per_s)
            b[1] = now
        return b

    def admit(self, tenant: str | None, cost: float = 1.0) -> str:
        """Spend ``cost`` tokens for ``tenant`` (sanitized; returned so
        the caller can stamp the job). ``cost`` defaults to the
        historical 1-per-submit; cost-weighted services pass
        :func:`stack_cost` so spend tracks megapixels. Raises
        :class:`TenantQuotaError` when the bucket can't cover it."""
        return self._admit(tenant, spend=True, cost=cost)

    def check(self, tenant: str | None, cost: float = 1.0) -> str:
        """The refusal :meth:`admit` WOULD raise right now, without
        spending a token — the HTTP layer's headers-time probe (reject
        an over-budget tenant before buffering its ~95 MB body; the
        authoritative spend happens at the real admission, where the
        weighted cost is known). Advisory: counts only rejections."""
        return self._admit(tenant, spend=False, cost=cost)

    def _need(self, cost: float) -> float:
        # Capped at burst: a stack costing more than the whole bucket
        # must still be admittable at full burst, else it is rejected
        # forever no matter how patient the tenant.
        return min(float(self.burst), max(MIN_STACK_COST, float(cost)))

    def _admit(self, tenant: str | None, spend: bool,
               cost: float = 1.0) -> str:
        tenant = sanitize_tenant(tenant)
        need = self._need(cost)
        now = self._clock()
        with self._lock:
            b = self._bucket(tenant, now)
            if b[0] >= need:
                if spend:
                    b[0] -= need
                admitted = True
                wait = 0.0
            else:
                admitted = False
                wait = (need - b[0]) / self.rate_per_s
        if admitted:
            if spend:
                self._admitted(tenant).inc()
            return tenant
        self._rejected(tenant).inc()
        raise TenantQuotaError(tenant, max(0.05, wait))

    def refund(self, tenant: str | None, cost: float = 1.0) -> None:
        """Return the spend (capped at burst): the admission tokens were
        spent on was refused FURTHER DOWN the gate chain (queue full,
        session registry full) — nothing ran, so the tenant's budget
        must not be charged. ``cost`` must be the SAME value the paired
        :meth:`admit` spent (refund parity — asserted in tests). The
        ``serve_tenant_admitted_total`` counter keeps token-SPEND
        semantics (monotonic counters can't decrement); a refunded
        spend shows up as a paired queue-level rejection on the same
        scrape."""
        tenant = sanitize_tenant(tenant)
        need = self._need(cost)
        with self._lock:
            b = self._buckets.get(tenant)
            if b is not None:
                b[0] = min(float(self.burst), b[0] + need)

    def stats(self) -> dict:
        with self._lock:
            return {
                "rate_per_s": self.rate_per_s,
                "burst": self.burst,
                "tenants_tracked": len(self._buckets),
                "tokens": {t: round(b[0], 2)
                           for t, b in self._buckets.items()},
            }
