"""Crash-safe journal + snapshot store for the reconstruction service.

The paper's own pipeline persists every stage to disk so any step can be
re-run offline; this module restores that property to the serving stack.
A :class:`JournalStore` is one directory (the "journal volume" of
docs/SERVING.md's deployment recipe):

    <root>/journal.jsonl     append-only op log (one JSON object per line)
    <root>/stacks/           .npy capture-stack blobs referenced by ops
    <root>/content/          content-hash result cache (serve/cache.py)

**What is journaled.** Job admissions (with the stack blob), job
terminal transitions, session creations, every ACCEPTED session stop
(with its stack blob; a ``stop_failed`` op marks one whose job later
failed service-side — the live session never fused it, so replay skips
it), and session endings (finalized / deleted / expired / evicted).
After a ``kill -9``, :meth:`recover` rebuilds the live set:
non-terminal jobs are re-queued and live sessions are replayed stop by
stop through the already-compiled B=1 program lane
(`ReconstructionService.start(recover_from=...)`) — the replay is
deterministic, so a recovered session finalizes bitwise-identically to
an uninterrupted one (tests/test_durability.py).

**Group commit.** A single writer thread owns the file: ``append``
enqueues the serialized op and (by default) blocks until its batch is
written + flushed, so concurrent submitters amortize one write/flush per
batch instead of serializing on the file lock — and no service lock is
ever held across journal I/O (the jaxlint blocking-under-lock rule).
``flush`` is the ``kill -9`` durability bar (the bytes survive the
process in the page cache); ``fsync`` is batched on a timer
(``fsync_interval_s``) as the cheap host-crash hedge.

**Compaction.** Terminal jobs and ended sessions are dead weight; when
enough dead ops accumulate the writer rewrites the journal from the live
mirror (tmp file + atomic rename) and deletes unreferenced stack blobs.
A fresh open compacts immediately, so restart cost is O(live), not
O(history).
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from ..utils import events
from ..utils.log import get_logger
from .blobstore import BlobStore, open_blob_store

log = get_logger(__name__)

JOURNAL_NAME = "journal.jsonl"
STACKS_DIR = "stacks"
CONTENT_DIR = "content"


# ---------------------------------------------------------------------------
# Recovered state
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RecoveredJob:
    """One non-terminal one-shot job found in the journal."""

    job_id: str
    stack_path: str
    result_format: str = "ply"
    priority: int = 1
    deadline_s: float | None = None
    submitted_wall: float = 0.0
    content_key: str | None = None


@dataclasses.dataclass
class RecoveredSession:
    """One live (never-ended) streaming session + its accepted stops in
    submission order. ``replica`` is the replica id that journaled the
    session head (fleet tier: ownership comparisons against the handoff
    stream decide whether a recovering replica still owns it)."""

    session_id: str
    scan_id: str
    options: dict
    stop_paths: list = dataclasses.field(default_factory=list)
    replica: str | None = None
    # (job_id, blob) pairs for handoff streams, where blob identity (not
    # a journal-relative path) names the shared-volume copy.
    stops: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class RecoveredState:
    jobs: list          # [RecoveredJob] in admission order
    sessions: list      # [RecoveredSession] in creation order
    ops: int = 0
    corrupt_lines: int = 0

    @property
    def empty(self) -> bool:
        return not self.jobs and not self.sessions


def _parse_journal(path: str) -> RecoveredState:
    """Tolerant replay of one journal file: unknown ops are ignored
    (forward compatibility), a torn final line (crash mid-write of an
    unacked op) is skipped and counted."""
    jobs: dict[str, RecoveredJob] = {}
    done: set[str] = set()
    sessions: dict[str, RecoveredSession] = {}
    ended: set[str] = set()
    stops: dict[str, list] = {}        # sid -> [(job_id, path)]
    failed_stops: set[str] = set()     # stop job_ids that never fused
    ops = corrupt = 0
    if not os.path.exists(path):
        return RecoveredState(jobs=[], sessions=[])
    with open(path, "rb") as f:
        for raw in f:
            line = raw.strip()
            if not line:
                continue
            try:
                op = json.loads(line)
            except ValueError:
                corrupt += 1
                continue
            ops += 1
            kind = op.get("op")
            if kind == "job":
                jobs[op["job_id"]] = RecoveredJob(
                    job_id=op["job_id"], stack_path=op["stack"],
                    result_format=op.get("result_format", "ply"),
                    priority=int(op.get("priority", 1)),
                    deadline_s=op.get("deadline_s"),
                    submitted_wall=float(op.get("t_wall", 0.0)),
                    content_key=op.get("content_key"))
            elif kind == "job_done":
                done.add(op["job_id"])
            elif kind == "session":
                sid = op["session_id"]
                sessions[sid] = RecoveredSession(
                    session_id=sid, scan_id=op.get("scan_id", sid),
                    options=dict(op.get("options") or {}),
                    replica=op.get("replica"))
            elif kind == "stop":
                if op["session_id"] in sessions:
                    stops.setdefault(op["session_id"], []).append(
                        (op.get("job_id"), op["stack"]))
            elif kind == "stop_failed":
                # The stop's job failed service-side: the live session
                # never fused it, so replay must skip it (set-based —
                # tolerates the op landing before its stop op).
                if op.get("job_id"):
                    failed_stops.add(op["job_id"])
            elif kind == "session_end":
                ended.add(op["session_id"])
            # "note" and unknown ops carry no recoverable state.
    for sid, entries in stops.items():
        live = [(jid, p) for jid, p in entries
                if jid not in failed_stops]
        sessions[sid].stop_paths = [p for _, p in live]
        sessions[sid].stops = live
    live_jobs = [j for jid, j in jobs.items() if jid not in done]
    live_sessions = [s for sid, s in sessions.items() if sid not in ended]
    return RecoveredState(jobs=live_jobs, sessions=live_sessions,
                          ops=ops, corrupt_lines=corrupt)


# ---------------------------------------------------------------------------
# Session handoff streams (the fleet tier's shared volume)
# ---------------------------------------------------------------------------

#: WAL ops the sink mirrors to the shared volume.
SESSION_STREAM_OPS = ("session", "stop", "stop_failed", "session_end",
                      "session_owner")


class SessionStreamStore:
    """Per-session op streams on a shared volume — the
    :class:`JournalStore` **sink abstraction** of the fleet tier
    (docs/SERVING.md § fleet).

    Layout::

        <root>/<session_id>.jsonl    the session's op stream
        <root>/blobs/                stack blobs (one per accepted stop)

    The owning :class:`JournalStore` mirrors session-scoped WAL ops here
    from its writer thread **inside the group commit** (before the
    commit event fires), so an acked session stop is on the shared
    volume by the time the client sees its HTTP 200 — the property that
    lets the router re-pin a SIGKILLed replica's live sessions to a
    survivor (`ReconstructionService.adopt_session`) with zero acked
    stops lost.

    Appends are lock-free single ``write`` calls in append mode, so the
    writer thread and an adopting service can interleave safely;
    :meth:`read_session` is tolerant by construction — duplicate heads
    take the LAST (ownership moved), duplicate stops dedup by job id
    keeping the FIRST (an adopter's replayed stops mirror again with the
    same ids), ``stop_failed`` removes its stop, torn tails are skipped.
    A mirrored ``session_end`` deletes the stream file and its blobs:
    an empty stream directory after drain is the fleet-level
    journal-clean signal.

    Storage rides the :class:`~.blobstore.BlobStore` seam: ``root`` may
    be a local directory (the historical shared-POSIX-volume layout,
    preserved byte for byte) or an object-store spec
    (``http://host:port[/prefix]`` — replicas then share NO filesystem;
    serve/blobstore.py). Every store failure is an OSError the callers'
    containment already absorbs: a sick store degrades handoff
    durability, never serving.
    """

    BLOBS_DIR = "blobs"

    def __init__(self, root: str, store: BlobStore | None = None):
        self.root = root
        self.store = store if store is not None else open_blob_store(root)
        self.mirror_failures = 0

    # -- keys -----------------------------------------------------------

    def _stream_key(self, session_id: str) -> str:
        # Session ids are uuid hex (ours) but defend the key join
        # anyway: a traversal-shaped id must not escape the volume.
        safe = "".join(c for c in session_id if c.isalnum() or c in "-_")
        return f"{safe}.jsonl"

    def _blob_key(self, name: str) -> str:
        return f"{self.BLOBS_DIR}/{name}"

    # -- writing --------------------------------------------------------

    def append(self, op: dict) -> None:
        """Append one op line to its session's stream (atomic-enough
        single write; readers tolerate interleaves)."""
        line = json.dumps(op) + "\n"
        self.store.append(self._stream_key(op["session_id"]),
                          line.encode("utf-8"))

    def put_blob(self, name: str, data: bytes) -> str:
        """Store one stack blob by content bytes (atomic whole-object
        write); returns the blob name."""
        self.store.put(self._blob_key(name), data)
        return name

    def mirror(self, op: dict, store: "JournalStore") -> None:
        """Sink entry point, called by ``store``'s writer thread per
        session-scoped WAL op. Blob copy FIRST (an op must never
        reference a blob that is not there), then the op line. A failing
        shared volume degrades handoff — loudly — never local serving;
        the caller wraps this in the OSError containment."""
        kind = op.get("op")
        out = dict(op)
        if kind == "stop" and op.get("stack"):
            blob = f"{op['session_id']}-{op.get('job_id') or 'stop'}.npy"
            if self.store.size(self._blob_key(blob)) is None:
                with open(os.path.join(store.root, op["stack"]),
                          "rb") as f:
                    self.put_blob(blob, f.read())
            out["blob"] = blob
            out.pop("stack", None)
        if kind == "session_end":
            if op.get("scope") == "local":
                # A handed-off tombstone: the stream now belongs to the
                # adopting replica — leave it alone.
                return
            ender = op.get("replica")
            owner = self.owner(op["session_id"])
            if ender is not None and owner is not None \
                    and ender != owner:
                # A NON-OWNER's end (e.g. the origin replica's idle-TTL
                # expiry of its stale double-hosted copy after a
                # handoff): the stream belongs to the adopter — nuking
                # it would lose the adopter's acked stops at its next
                # recovery.
                log.info("ignoring session_end from non-owner %s for "
                         "%s (owner %s)", ender, op["session_id"],
                         owner)
                return
            self.end_session(op["session_id"],
                             reason=op.get("reason", "ended"))
            return
        self.append(out)

    def end_session(self, session_id: str,
                    reason: str = "ended") -> None:
        """The session ended fleet-wide (finalized/deleted/expired/
        evicted): free its blobs and rewrite the stream to ONE
        tombstone line. The tombstone is POSITIVE evidence of the end —
        recovery must distinguish "ended somewhere" (tombstone) from
        "the mirror never wrote" (missing stream), because the latter
        means the local WAL is the only copy and must recover.

        Tombstone FIRST, blob unlinks after: a concurrent reader (the
        router's adoption sweep) must see either the fully-live stream
        or the tombstone — never a live head whose blobs are already
        gone, which an adopter would dutifully "adopt" as an all-
        degraded empty session."""
        info = self._read(session_id, include_failed=True)
        line = json.dumps({"op": "session_end",
                           "session_id": session_id,
                           "reason": reason,
                           "t_wall": time.time()}) + "\n"
        self.store.replace(self._stream_key(session_id),
                           line.encode("utf-8"))
        if info is not None:
            for _, blob in info.stops:
                try:
                    self.store.delete(self._blob_key(blob))
                except OSError:
                    log.debug("handoff blob %s already gone", blob)

    def drop_session(self, session_id: str) -> None:
        """Hard-remove a stream file (the origin replica calls this
        after consuming an end tombstone at recovery, bounding
        tombstone accumulation on long-lived volumes)."""
        try:
            self.store.delete(self._stream_key(session_id))
        except OSError:
            log.debug("handoff stream %s already gone", session_id)

    # -- reading --------------------------------------------------------

    def _scan(self, session_id: str, include_failed: bool = False
              ) -> tuple[bool, "RecoveredSession | None"]:
        """(ended, info) for one stream; info is None when the file is
        missing, unreadable or headless. ``ended`` True = an end
        tombstone is present (positive evidence the session finished
        SOMEWHERE in the fleet)."""
        head = None
        owner = None
        ended = False
        stops: "OrderedDict[str, str]" = OrderedDict()
        anon: list[tuple[None, str]] = []
        failed: set[str] = set()
        try:
            data = self.store.get(self._stream_key(session_id))
        except OSError as e:
            log.warning("handoff stream %s unreadable: %s", session_id, e)
            return False, None
        if data is None:
            return False, None
        for raw in data.splitlines():
            line = raw.strip()
            if not line:
                continue
            try:
                op = json.loads(line)
            except ValueError:
                continue  # torn/interleaved line: skip, keep reading
            kind = op.get("op")
            if kind == "session":
                head = op                      # last head wins
                owner = op.get("replica", owner)
            elif kind == "session_owner":
                owner = op.get("replica", owner)
            elif kind == "session_end":
                ended = True
            elif kind == "stop" and op.get("blob"):
                jid = op.get("job_id")
                if jid is None:
                    anon.append((None, op["blob"]))
                elif jid not in stops:         # dedup: first wins
                    stops[jid] = op["blob"]
            elif kind == "stop_failed" and op.get("job_id"):
                failed.add(op["job_id"])
        if head is None:
            return ended, None
        pairs = [(jid, blob) for jid, blob in stops.items()
                 if include_failed or jid not in failed] + anon
        return ended, RecoveredSession(
            session_id=session_id,
            scan_id=head.get("scan_id", session_id),
            options=dict(head.get("options") or {}),
            replica=owner, stops=pairs)

    def _read(self, session_id: str,
              include_failed: bool = False) -> RecoveredSession | None:
        ended, info = self._scan(session_id, include_failed)
        return None if ended else info

    def stream_state(self, session_id: str) -> str:
        """``"live"`` (adoptable stream), ``"ended"`` (tombstoned — the
        session finished somewhere in the fleet), or ``"missing"`` (no
        stream: never mirrored, or the mirror failed — the caller's
        local WAL may be the ONLY copy)."""
        ended, info = self._scan(session_id, include_failed=True)
        if ended:
            return "ended"
        return "live" if info is not None else "missing"

    def read_session(self, session_id: str) -> RecoveredSession | None:
        """The session's replayable state: head options/scan id, current
        owner, and (job_id, blob) stop pairs with service-side-failed
        stops excluded — replay must skip exactly what the live session
        never fused."""
        return self._read(session_id, include_failed=False)

    def owner(self, session_id: str) -> str | None:
        """Current owner replica id, or None when the stream is
        missing/ended or carries no replica stamps."""
        info = self._read(session_id, include_failed=True)
        return info.replica if info is not None else None

    def has_session(self, session_id: str) -> bool:
        """True while a LIVE (adoptable, un-ended) stream exists."""
        return self.stream_state(session_id) == "live"

    def load_blob(self, name: str) -> np.ndarray:
        data = self.store.get(self._blob_key(name))
        if data is None:
            raise FileNotFoundError(f"handoff blob {name} missing")
        return np.load(io.BytesIO(data), allow_pickle=False)

    def list_sessions(self) -> list[str]:
        """Session ids with LIVE streams (end tombstones excluded) —
        the fleet-level "journal clean?" probe."""
        try:
            names = self.store.list("")
        except OSError:
            return []
        out = []
        for n in names:
            if "/" in n or not n.endswith(".jsonl"):
                continue
            sid = n[:-6]
            if self.stream_state(sid) == "live":
                out.append(sid)
        return out

    def stats(self) -> dict:
        # Parse-free on purpose: this rides every /healthz scrape, and
        # the shared volume may be remote (NFS or an object service).
        # ``streams`` counts stream OBJECTS — live sessions plus
        # not-yet-consumed end tombstones; the exact live set is
        # ``list_sessions()``, which parses every stream and belongs in
        # probes, not health scrapes.
        try:
            names = self.store.list("")
        except OSError:
            names = []
        streams = sum(1 for n in names
                      if "/" not in n and n.endswith(".jsonl"))
        blobs = sum(1 for n in names
                    if n.startswith(f"{self.BLOBS_DIR}/")
                    and ".tmp" not in n)   # temp suffix is .tmp-<pid>
        return {"root": self.root, "streams": streams, "blobs": blobs,
                "mirror_failures": self.mirror_failures,
                "backend": self.store.stats().get("backend")}


# ---------------------------------------------------------------------------
# The store
# ---------------------------------------------------------------------------


class JournalStore:
    """Write-ahead journal + stack-blob store over one directory.

    ``sink`` (fleet tier): a :class:`SessionStreamStore` that receives
    every session-scoped op from the writer thread as part of the group
    commit — the journal *streams* session state to the shared volume so
    a survivor replica can adopt a dead replica's live sessions."""

    def __init__(self, root: str, fsync_interval_s: float = 0.25,
                 compact_min_dead: int = 256,
                 compact_on_open: bool = True,
                 sink: "SessionStreamStore | None" = None):
        self.root = root
        self.sink = sink
        self.fsync_interval_s = float(fsync_interval_s)
        self.compact_min_dead = int(compact_min_dead)
        os.makedirs(os.path.join(root, STACKS_DIR), exist_ok=True)
        os.makedirs(os.path.join(root, CONTENT_DIR), exist_ok=True)
        self._path = os.path.join(root, JOURNAL_NAME)
        # Live mirror (writer-thread-owned after start; seeded here from
        # whatever a previous process left behind).
        state = _parse_journal(self._path)
        self._jobs: dict[str, dict] = {}
        self._sessions: dict[str, dict] = {}
        self._early_done: set[str] = set()
        self._early_failed_stops: set[str] = set()
        self._purge: list[str] = []  # blob relpaths freed by dead ops
        self._sync_timeouts = 0
        self._write_failures = 0
        self._seed_mirror(state)
        self._recovered = state
        self._dead_ops = 0
        self._compactions = 0
        self._last_fsync = time.monotonic()
        # Group-commit plumbing: callers enqueue serialized lines under
        # the condition, the writer thread swaps the batch out and does
        # ALL file I/O lock-free (no caller-visible lock spans I/O).
        self._cond = threading.Condition()
        self._batch: list[tuple[str, dict]] = []
        self._commit_ev = threading.Event()
        self._closing = False
        self._closed = False
        self._f = open(self._path, "a", encoding="utf-8")
        self._writer = threading.Thread(target=self._run,
                                        name="journal-writer", daemon=True)
        self._writer.start()
        if compact_on_open and (state.ops > len(self._live_ops())
                                or state.corrupt_lines):
            self._request_compact()

    # -- mirror ------------------------------------------------------------

    def _seed_mirror(self, state: RecoveredState) -> None:
        for j in state.jobs:
            self._jobs[j.job_id] = {
                "op": "job", "job_id": j.job_id, "stack": j.stack_path,
                "result_format": j.result_format, "priority": j.priority,
                "deadline_s": j.deadline_s, "t_wall": j.submitted_wall,
                "content_key": j.content_key}
        for s in state.sessions:
            # Carry replica + stop job_ids through compaction: the
            # rewritten journal must preserve the ownership stamp (the
            # handoff-aware recovery compares it against the stream's
            # owner) and the ids stop_failed ops match against.
            self._sessions[s.session_id] = {
                "head": {"op": "session", "session_id": s.session_id,
                         "scan_id": s.scan_id, "options": s.options,
                         "replica": s.replica},
                "stops": [{"op": "stop", "session_id": s.session_id,
                           "job_id": jid, "stack": p}
                          for jid, p in s.stops]}

    def _live_ops(self) -> list[dict]:
        out = list(self._jobs.values())
        for s in self._sessions.values():
            out.append(s["head"])
            out.extend(s["stops"])
        return out

    def _apply(self, op: dict) -> None:
        """Writer-thread mirror update; terminal/end ops mark their blob
        paths dead for the next compaction."""
        kind = op.get("op")
        if kind == "job":
            if op["job_id"] in self._early_done:
                # Terminal transition journaled BEFORE the admission op
                # (a worker can outrun the submitter's append): dead on
                # arrival, never live in the mirror.
                self._early_done.discard(op["job_id"])
                self._dead_ops += 2
            else:
                self._jobs[op["job_id"]] = op
        elif kind == "job_done":
            prior = self._jobs.pop(op["job_id"], None)
            if prior is None:
                self._early_done.add(op["job_id"])
            elif prior.get("stack"):
                # Free the blob the moment its terminal op commits — at
                # 1080p every retained stack is ~95 MB, and waiting for
                # compaction would let a busy service pin GBs of dead
                # inputs on the journal volume.
                self._purge.append(prior["stack"])
            self._dead_ops += 1 + (1 if prior else 0)
        elif kind == "session":
            self._sessions[op["session_id"]] = {"head": op, "stops": []}
        elif kind == "stop":
            if op.get("job_id") in self._early_failed_stops:
                # Failure op outran the admission append: dead on
                # arrival (mirrors the job _early_done handling).
                self._early_failed_stops.discard(op["job_id"])
                self._dead_ops += 2
                if op.get("stack"):
                    self._purge.append(op["stack"])
            else:
                sess = self._sessions.get(op["session_id"])
                if sess is not None:
                    sess["stops"].append(op)
        elif kind == "stop_failed":
            sess = self._sessions.get(op.get("session_id"))
            matched = None
            if sess is not None and op.get("job_id"):
                for s in sess["stops"]:
                    if s.get("job_id") == op["job_id"]:
                        matched = s
                        break
            if matched is not None:
                sess["stops"].remove(matched)
                self._dead_ops += 2
                if matched.get("stack"):
                    self._purge.append(matched["stack"])
            elif op.get("job_id"):
                self._early_failed_stops.add(op["job_id"])
                self._dead_ops += 1
        elif kind == "session_end":
            prior = self._sessions.pop(op["session_id"], None)
            self._dead_ops += 1
            if prior:
                self._dead_ops += 1 + len(prior["stops"])
                self._purge.extend(s["stack"] for s in prior["stops"]
                                   if s.get("stack"))
        else:
            self._dead_ops += 1  # notes are never live

    # -- appending ---------------------------------------------------------

    def append(self, op: dict, sync: bool = True) -> None:
        """Append one op. ``sync=True`` blocks until the op's batch is
        written + flushed (the durability promise an HTTP 200 rides on);
        ``sync=False`` is fire-and-forget for low-stakes ops (terminal
        transitions, notes) that recovery treats as advisory."""
        op = dict(op)
        op.setdefault("t_wall", time.time())
        line = json.dumps(op)
        with self._cond:
            if self._closed or self._closing:
                log.debug("journal append after close dropped: %s",
                          op.get("op"))
                return
            self._batch.append((line, op))
            ev = self._commit_ev
            self._cond.notify()
        if sync and not ev.wait(timeout=10.0):
            # The caller proceeds (an overloaded volume must not wedge
            # the serving path), but the durability promise is broken
            # for this op — say so loudly and count it, so "acked but
            # lost after crash" is diagnosable instead of silent.
            with self._cond:
                self._sync_timeouts += 1
            log.error("journal sync append timed out after 10s "
                      "(op=%s) — volume stalled; this op may not "
                      "survive a crash", op.get("op"))

    def note(self, kind: str, sync: bool = False, **fields) -> None:
        """Journal an advisory marker (worker restarts, drains) — dropped
        at compaction, but present in the raw log for post-mortems."""
        self.append({"op": "note", "kind": kind, **fields}, sync=sync)

    # -- stack blobs -------------------------------------------------------

    def put_stack(self, name: str, stack: np.ndarray) -> str:
        """Persist one capture stack; returns the journal-relative path.
        tmp + rename so a torn write can never be mistaken for a blob."""
        rel = os.path.join(STACKS_DIR, f"{name}.npy")
        path = os.path.join(self.root, rel)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.save(f, stack)
        os.replace(tmp, path)
        return rel

    def load_stack(self, rel: str) -> np.ndarray:
        with open(os.path.join(self.root, rel), "rb") as f:
            return np.load(io.BytesIO(f.read()), allow_pickle=False)

    @property
    def content_dir(self) -> str:
        return os.path.join(self.root, CONTENT_DIR)

    # -- recovery ----------------------------------------------------------

    def recover(self) -> RecoveredState:
        """The live set as parsed at open() — what a fresh service must
        re-queue/replay. (Re-parse with :func:`_parse_journal` for the
        current on-disk state of a FOREIGN store, e.g. post-drain
        journal-clean assertions.)"""
        return self._recovered

    # -- writer thread -----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._batch and not self._closing:
                    self._cond.wait(0.5)
                batch, self._batch = self._batch, []
                ev, self._commit_ev = self._commit_ev, threading.Event()
                closing = self._closing and not batch
            if batch:
                try:
                    for line, _ in batch:
                        self._f.write(line + "\n")
                    self._f.flush()
                    now = time.monotonic()
                    if now - self._last_fsync >= self.fsync_interval_s:
                        os.fsync(self._f.fileno())
                        self._last_fsync = now
                except OSError as e:
                    # A full/broken volume must degrade durability, not
                    # take the serving path down with it — but LOUDLY:
                    # the commit event below still fires (callers must
                    # not wedge), so the flight journal + the stats
                    # counter are the only record that acked ops are not
                    # actually on disk.
                    with self._cond:
                        self._write_failures += 1
                    log.error("journal write failed: %s", e)
                    events.record("journal_write_failed",
                                  severity="error", message=str(e),
                                  ops=len(batch))
                if self.sink is not None:
                    # Handoff mirroring is part of the group commit: an
                    # acked session op is on the shared volume before
                    # the commit event fires. A failing shared volume
                    # degrades HANDOFF (survivors adopt a shorter
                    # stream), never local serving — loudly.
                    for _, op in batch:
                        if op.get("op") not in SESSION_STREAM_OPS:
                            continue
                        try:
                            self.sink.mirror(op, self)
                        except OSError as e:
                            self.sink.mirror_failures += 1
                            log.error("handoff mirror failed: %s", e)
                            events.record(
                                "handoff_mirror_failed",
                                severity="error", message=str(e),
                                session_id=op.get("session_id"))
                with self._cond:  # mirror updates visible to stats()
                    for _, op in batch:
                        self._apply(op)
                    compact_due = self._dead_ops >= self.compact_min_dead
                    purge, self._purge = self._purge, []
                ev.set()
                for rel in purge:  # blob deletes outside the lock
                    try:
                        os.remove(os.path.join(self.root, rel))
                    except OSError:
                        pass
                if compact_due:
                    self._compact()
                continue
            if closing:
                try:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                except OSError:
                    pass
                self._f.close()
                ev.set()  # release any racer that grabbed this event
                return

    def _request_compact(self) -> None:
        # Make the open-time compaction ride the writer thread like every
        # other journal mutation: a no-op note trips the dead-op check.
        with self._cond:
            self._dead_ops = max(self._dead_ops, self.compact_min_dead)
            self._batch.append((json.dumps(
                {"op": "note", "kind": "open_compact",
                 "t_wall": time.time()}), {"op": "note"}))
            self._cond.notify()

    def _compact(self) -> None:
        """Rewrite the journal from the live mirror (writer thread only:
        it owns the file handle and the mirror)."""
        tmp = self._path + ".tmp"
        with self._cond:
            live = self._live_ops()
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for op in live:
                    f.write(json.dumps(op) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self._path)
            self._f = open(self._path, "a", encoding="utf-8")
        except OSError as e:
            log.error("journal compaction failed: %s", e)
            if self._f.closed:  # keep appending SOMEWHERE
                self._f = open(self._path, "a", encoding="utf-8")
            return
        # Blob hygiene: anything on disk no live op references (dead
        # jobs/sessions, orphans from crashes between put_stack and
        # append) is deleted.
        referenced = {op["stack"] for op in live if op.get("stack")}
        stacks_dir = os.path.join(self.root, STACKS_DIR)
        for fname in os.listdir(stacks_dir):
            rel = os.path.join(STACKS_DIR, fname)
            if rel not in referenced:
                try:
                    os.remove(os.path.join(stacks_dir, fname))
                except OSError:
                    pass
        with self._cond:
            dead = self._dead_ops
            self._dead_ops = 0
            self._compactions += 1
        log.info("journal compacted: %d live ops kept, %d dead dropped",
                 len(live), dead)

    # -- lifecycle / inspection --------------------------------------------

    def close(self) -> None:
        """Flush every acked op and stop the writer. Idempotent; appends
        after close are dropped (a crashing service may race its own
        teardown)."""
        with self._cond:
            if self._closed:
                return
            self._closing = True
            self._cond.notify_all()
        self._writer.join(timeout=10.0)
        with self._cond:
            self._closed = True

    def stats(self) -> dict:
        with self._cond:
            live_jobs = len(self._jobs)
            live_sessions = len(self._sessions)
            dead = self._dead_ops
        try:
            journal_bytes = os.path.getsize(self._path)
        except OSError:
            journal_bytes = 0
        return {
            "root": self.root,
            "live_jobs": live_jobs,
            "live_sessions": live_sessions,
            "dead_ops": dead,
            "journal_bytes": journal_bytes,
            "compactions": self._compactions,
            "sync_timeouts": self._sync_timeouts,
            "write_failures": self._write_failures,
        }


def read_live_state(root: str) -> RecoveredState:
    """Parse a journal volume WITHOUT opening a store (no writer thread,
    no compaction): the post-drain "journal clean?" probe used by the
    soak bench and the durability tests."""
    return _parse_journal(os.path.join(root, JOURNAL_NAME))
