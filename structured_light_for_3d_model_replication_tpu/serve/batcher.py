"""Continuous batcher: bucket by padded shape, coalesce, flush on full/linger.

The pipeline's throughput lane is the vmapped batch program
(`models/pipeline.reconstruct_batch_fn`): at batch 8 a 1080p scan
amortizes to ~23 ms vs ~137 ms single-shot (bench config [5]). But XLA
programs are static-shape, so mixed traffic only rides that lane if the
server first makes shapes equal. This module does exactly two things:

* **bucketing** — a job's (H, W) is padded up to the smallest configured
  bucket that fits (else to a ``pad_quantum`` multiple, so arbitrary
  shapes still batch among themselves instead of each minting a new
  program). Padding is zero-fill: black pixels fail the decode validity
  threshold, so padded lanes triangulate to nothing and cost only
  bandwidth. The bucket key carries everything that selects a program
  (shape, bits, decode/tri configs), mirroring the jit static-arg set.

* **coalescing** — per-bucket pending lists; a bucket flushes when it
  holds ``max_batch`` jobs OR its oldest job has lingered past
  ``linger_s``. Flush size rounds UP to the next power of two in
  ``batch_sizes`` (padded slots are zero stacks), so the program cache
  holds at most ``len(batch_sizes)`` executables per bucket and a burst
  of 5 runs as one B=8 launch, not 4+1.

This is the "continuous batching" shape every serving stack converges on
(vLLM-style): admission is decoupled from launch, and the linger timer
bounds the latency cost of waiting for company.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from ..config import DecodeConfig, TriangulationConfig
from ..utils import events
from ..utils.log import get_logger
from .jobs import AdmissionQueue, DeadlineExceededError, Job

log = get_logger(__name__)

DEFAULT_BATCH_SIZES = (1, 2, 4, 8)


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """Everything that selects one compiled program family (all batch
    sizes of one shape/config combination). Frozen/hashable — it is a
    dict key here and the trunk of `cache.ProgramKey`."""

    height: int          # padded camera rows
    width: int           # padded camera cols
    frames: int          # protocol length (2 + 2*col_bits + 2*row_bits)
    col_bits: int
    row_bits: int
    decode_cfg: DecodeConfig = DecodeConfig()
    tri_cfg: TriangulationConfig = TriangulationConfig()
    downsample: int = 1

    def label(self) -> str:
        return f"{self.height}x{self.width}x{self.frames}"


def bucket_for(h: int, w: int, buckets: tuple,
               pad_quantum: int = 64) -> tuple[int, int]:
    """Smallest configured (H, W) bucket containing (h, w); off-menu
    shapes round up to ``pad_quantum`` multiples so they still coalesce
    with equals instead of compiling per-resolution."""
    best = None
    for bh, bw in buckets:
        if bh >= h and bw >= w:
            area = bh * bw
            if best is None or area < best[0]:
                best = (area, bh, bw)
    if best is not None:
        return best[1], best[2]
    q = pad_quantum
    return ((h + q - 1) // q * q, (w + q - 1) // q * q)


def batch_size_for(n: int, batch_sizes: tuple) -> int:
    """Smallest allowed batch size >= n (callers cap n at max first)."""
    for b in sorted(batch_sizes):
        if b >= n:
            return b
    return max(batch_sizes)


@dataclasses.dataclass
class Batch:
    """One flush: jobs + the padded device-ready array.

    ``occupancy`` is the number of REAL jobs; ``size`` the padded program
    batch dimension. The (B, F, H, W) array is assembled host-side here
    (cheap memcpy) so workers only own device interaction.
    """

    key: BucketKey
    jobs: list
    size: int
    lane: "int | None" = None   # device-lane affinity the flush honored
    # The ProgramKey the worker launched this batch through (stamped in
    # DeviceWorker._process): failure handling needs to know whether
    # the launch was lane-pinned or sharded cross-chip.
    program_key: object = None

    @property
    def occupancy(self) -> int:
        return len(self.jobs)

    def stacked(self) -> np.ndarray:
        k = self.key
        out = np.zeros((self.size, k.frames, k.height, k.width), np.uint8)
        for i, job in enumerate(self.jobs):
            f, h, w = job.stack.shape
            out[i, :f, :h, :w] = job.stack
        return out


class BucketBatcher:
    """Pulls from the admission queue, buckets, and hands coalesced
    batches to whichever worker asks next.

    Multiple workers share one batcher: ``next_batch`` is the
    synchronization point (internal lock), so batch assembly is
    single-writer per bucket while independent buckets drain in
    parallel across workers.
    """

    def __init__(self, queue: AdmissionQueue,
                 buckets: tuple = ((1080, 1920),),
                 batch_sizes: tuple = DEFAULT_BATCH_SIZES,
                 linger_s: float = 0.01,
                 pad_quantum: int = 64):
        if not batch_sizes:
            raise ValueError("batch_sizes must be non-empty")
        self.queue = queue
        self.buckets = tuple((int(h), int(w)) for h, w in buckets)
        self.batch_sizes = tuple(sorted(int(b) for b in batch_sizes))
        self.max_batch = self.batch_sizes[-1]
        self.linger_s = float(linger_s)
        self.pad_quantum = int(pad_quantum)
        self._lock = threading.Lock()
        # (lane | None, BucketKey) -> list[(enqueue_t, Job)]. The lane
        # half is device-lane AFFINITY (serve/lanes.py): jobs with
        # lane=None coalesce freely and any worker may flush them; a
        # session stop pinned to lane k only flushes to the worker on
        # that lane (sticky sessions — its jit programs live on that
        # chip). Affine and free jobs never share a batch: they launch
        # through different executables.
        self._pending: dict[tuple, list] = {}
        # Device-loss hook (serve/service.py): maps a job to the lane it
        # should ride NOW — a stop whose session re-pinned after its
        # device died must land in the adopting lane's buckets, not wait
        # forever in a dead lane's. Applied at absorb time and by
        # repin_pending(); None = affinity is taken as stamped.
        self.lane_resolver = None  # callable(Job) -> int | None

    # ------------------------------------------------------------------

    def key_for(self, job: Job) -> BucketKey:
        f, h, w = job.stack.shape
        bh, bw = bucket_for(h, w, self.buckets, self.pad_quantum)
        return BucketKey(height=bh, width=bw, frames=f,
                         col_bits=job.col_bits, row_bits=job.row_bits,
                         decode_cfg=job.decode_cfg, tri_cfg=job.tri_cfg,
                         downsample=job.downsample)

    def pending_depth(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._pending.values())

    # ------------------------------------------------------------------

    def _absorb(self, job: Job) -> None:
        bkey = self.key_for(job)
        with self._lock:
            # Resolve INSIDE the lock: repin_pending() re-keys under
            # this lock, so a job resolved to a lane in its last
            # healthy instant either lands before the re-key (and is
            # re-keyed with the rest) or resolves after the death (and
            # sees the dead lane) — never inserted-after-re-key into a
            # bucket no worker will ever flush. The resolver takes the
            # pool/session locks; that order (batcher → pool/session)
            # matches repin_pending and is never reversed.
            if self.lane_resolver is not None:
                job.lane = self.lane_resolver(job)
            self._pending.setdefault((job.lane, bkey), []).append(
                (time.monotonic(), job))

    def requeue(self, job: Job) -> None:
        """Re-absorb a job whose batch died under it (the device-loss
        cross-lane retry, serve/worker.py): the lane resolver re-routes
        it to a surviving lane. Original enqueue order is NOT preserved
        — the retry is new work from the batcher's point of view."""
        self._absorb(job)

    def repin_pending(self) -> int:
        """Re-key every pending job through the lane resolver (the
        device-dead path: jobs parked in a dead lane's buckets would
        never flush — its workers are gone). Returns jobs moved."""
        if self.lane_resolver is None:
            return 0
        moved = 0
        with self._lock:
            items = [(key, entry) for key, lst in self._pending.items()
                     for entry in lst]
            self._pending.clear()
            for (old_lane, bkey), (t, job) in items:
                lane = self.lane_resolver(job)
                job.lane = lane
                if lane != old_lane:
                    moved += 1
                self._pending.setdefault((lane, bkey), []).append(
                    (t, job))
            for lst in self._pending.values():
                lst.sort(key=lambda e: e[0])
        return moved

    def _flushable(self, now: float, force: bool,
                   lane: "int | None") -> tuple | None:
        """Pending key due for flush: full beats lingering; among
        lingering ones, the oldest wait wins. A worker on ``lane`` may
        flush free (lane=None) buckets and its own lane's buckets;
        ``lane=None`` (no lane pool) flushes everything."""
        best = None
        with self._lock:
            for key, items in self._pending.items():
                if not items:
                    continue
                if not (lane is None or key[0] is None or key[0] == lane):
                    continue
                if len(items) >= self.max_batch:
                    return key
                age = now - items[0][0]
                if force or age >= self.linger_s:
                    if best is None or age > best[0]:
                        best = (age, key)
        return best[1] if best else None

    def _take(self, key: tuple) -> Batch | None:
        with self._lock:
            items = self._pending.get(key, [])
            take, rest = items[:self.max_batch], items[self.max_batch:]
            if rest:
                self._pending[key] = rest
            else:
                self._pending.pop(key, None)
        jobs = [j for _, j in take if not j.expired()]
        for _, j in take:
            if j not in jobs:
                # Context so the fault event the constructor records
                # carries the scrubbed job's id (same rule as the
                # queue-side scrub in jobs.pop).
                with events.context(job_id=j.job_id):
                    j.fail(DeadlineExceededError(
                        "deadline lapsed while batching"))
        if not jobs:
            return None
        return Batch(key=key[1], jobs=jobs,
                     size=batch_size_for(len(jobs), self.batch_sizes),
                     lane=key[0])

    # ------------------------------------------------------------------

    def next_batch(self, timeout: float = 0.1, force: bool = False,
                   lane: "int | None" = None) -> Batch | None:
        """Next coalesced batch, or None after ``timeout``.

        ``force=True`` flushes partial buckets immediately (drain path:
        linger is pointless when no more work is coming). ``lane``
        restricts the flush to free buckets plus that lane's affine
        ones (the caller is a lane-pinned worker); absorption from the
        queue is unrestricted — a worker may absorb another lane's job
        into the shared pending state, where its own worker picks it up
        within one loop tick."""
        deadline = time.monotonic() + timeout
        while True:
            # Absorb everything already queued without blocking.
            while True:
                job = self.queue.pop(timeout=0.0)
                if job is None:
                    break
                self._absorb(job)
            now = time.monotonic()
            key = self._flushable(now, force, lane)
            if key is not None:
                batch = self._take(key)
                if batch is not None:
                    return batch
                continue  # bucket was all-expired; rescan
            remaining = deadline - now
            if remaining <= 0:
                return None
            # Sleep until new work, but never past the nearest linger
            # expiry of a pending bucket (or the caller's deadline).
            wait = min(remaining, self._nearest_linger(now))
            job = self.queue.pop(timeout=max(wait, 0.0))
            if job is not None:
                self._absorb(job)

    def _nearest_linger(self, now: float) -> float:
        with self._lock:
            ages = [now - items[0][0]
                    for items in self._pending.values() if items]
        if not ages:
            # Nothing pending ⇒ no linger deadline to honor: let the
            # caller sleep its full remaining timeout on the queue
            # instead of waking every linger_s while idle.
            return float("inf")
        return max(0.0, self.linger_s - max(ages))
