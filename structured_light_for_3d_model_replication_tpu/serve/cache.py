"""Explicit compiled-executable cache: warmup, LRU, hit/miss counters.

Relying on `jax.jit`'s implicit cache is how serving stacks get surprise
multi-second stalls: the first request of a new shape compiles inline, on
the request thread, with no way to see it coming on a dashboard. Here the
executable for every (batch, frames, H, W, bits, configs) program is
compiled EXPLICITLY via the AOT path
(``reconstruct_batch_fn(...).lower(shapes).compile()``) and held in a
bounded LRU:

* **warmup** precompiles the configured buckets × batch sizes at startup,
  so steady-state traffic never sees a compile (the zero-recompile
  acceptance bar; asserted in tests via these counters AND the jit cache
  sizes — AOT executables bypass the jit cache entirely, so those sizes
  staying flat proves no request slipped onto the implicit path);
* **hit/miss/compile-time counters** land in the metrics registry
  (``serve_program_cache_*`` on /metrics), so a miss storm is visible as
  a counter spike, not a latency mystery;
* **LRU eviction** bounds device/host program memory when a service sees
  many one-off shapes; evicting drops the executable, and the next use
  recompiles (counted).

A compile happens at most once per key even under concurrent misses: the
per-key entry holds an event that racers wait on while the first caller
compiles outside the registry lock.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict

from ..utils import trace
from ..utils.log import get_logger
from .batcher import BucketKey

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class ProgramKey:
    """BucketKey + batch size: one compiled executable."""

    bucket: BucketKey
    batch: int

    def label(self) -> str:
        return f"B{self.batch}:{self.bucket.label()}"


class _Entry:
    __slots__ = ("ready", "compiled", "error", "compile_s")

    def __init__(self):
        self.ready = threading.Event()
        self.compiled = None
        self.error: BaseException | None = None
        self.compile_s = 0.0


class ProgramCache:
    """LRU of AOT-compiled batch-reconstruction executables.

    ``calib_provider(height, width)`` returns the device Calibration for a
    bucket; its array shapes (not values) parameterize the compile, so one
    cache serves any rig whose calibration matches the bucket geometry.
    """

    def __init__(self, calib_provider, max_entries: int = 32,
                 registry: "trace.MetricsRegistry | None" = None):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.calib_provider = calib_provider
        self.max_entries = max_entries
        self.registry = registry if registry is not None else trace.REGISTRY
        self._lock = threading.Lock()
        self._entries: OrderedDict[ProgramKey, _Entry] = OrderedDict()
        self._hits = self.registry.counter(
            "serve_program_cache_hits_total",
            "program-cache lookups served without compiling")
        self._misses = self.registry.counter(
            "serve_program_cache_misses_total",
            "program-cache lookups that triggered a compile")
        self._evictions = self.registry.counter(
            "serve_program_cache_evictions_total",
            "programs dropped by LRU bounding")
        self._compile_s = self.registry.counter(
            "serve_program_cache_compile_seconds_total",
            "cumulative wall-clock spent compiling programs")
        self._entries_gauge = self.registry.gauge(
            "serve_program_cache_entries", "resident compiled programs")

    # ------------------------------------------------------------------

    def _compile(self, key: ProgramKey):
        import jax
        import jax.numpy as jnp

        from ..models import pipeline

        b = key.bucket
        calib = self.calib_provider(b.height, b.width)
        fn = pipeline.reconstruct_batch_fn(
            b.col_bits, b.row_bits, decode_cfg=b.decode_cfg,
            tri_cfg=b.tri_cfg, downsample=b.downsample)
        stack_spec = jax.ShapeDtypeStruct(
            (key.batch, b.frames, b.height, b.width), jnp.uint8)
        t0 = time.monotonic()
        compiled = fn.lower(stack_spec, calib).compile()
        dt = time.monotonic() - t0
        self._compile_s.inc(dt)
        log.info("compiled %s in %.2fs", key.label(), dt)
        return compiled, dt

    def get(self, key: ProgramKey):
        """The compiled executable for ``key`` — compiling (and counting a
        miss) if absent, else a counted hit. Raises the original compile
        error on every lookup of a key whose compile failed (failed
        entries are not cached)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                owner = False
            else:
                entry = _Entry()
                self._entries[key] = entry
                owner = True
        if owner:
            self._misses.inc()
            try:
                entry.compiled, entry.compile_s = self._compile(key)
            except BaseException as e:
                entry.error = e
                with self._lock:
                    self._entries.pop(key, None)
                raise
            finally:
                entry.ready.set()
            self._bound()
        else:
            entry.ready.wait()
            if entry.error is not None:
                raise entry.error
            self._hits.inc()
        with self._lock:
            self._entries_gauge.set(len(self._entries))
        return entry.compiled

    def _bound(self) -> None:
        with self._lock:
            while len(self._entries) > self.max_entries:
                # Victim = oldest READY entry: an in-flight compile must
                # not be popped (its executable would be dropped the
                # moment it finishes, forcing a duplicate compile on the
                # next lookup of that key).
                victim = next((k for k, e in self._entries.items()
                               if e.ready.is_set()), None)
                if victim is None:
                    break  # everything resident is mid-compile
                self._entries.pop(victim)
                self._evictions.inc()
                log.info("evicted %s (LRU, max_entries=%d)",
                         victim.label(), self.max_entries)

    # ------------------------------------------------------------------

    def warmup(self, bucket_keys, batch_sizes) -> dict:
        """Precompile every (bucket, batch) program; returns
        {label: compile_s}. Called at service start so the first real
        request of any configured shape is a hit."""
        out = {}
        for bucket in bucket_keys:
            for b in batch_sizes:
                key = ProgramKey(bucket=bucket, batch=int(b))
                with trace.span("serve.warmup", program=key.label()):
                    t0 = time.monotonic()
                    self.get(key)
                    out[key.label()] = round(time.monotonic() - t0, 3)
        # Warmup compiles are misses by construction; zero them out of the
        # steady-state signal? No — they stay counted (honest totals), and
        # the zero-recompile assertion compares counters AFTER warmup.
        return out

    def stats(self) -> dict:
        with self._lock:
            entries = [k.label() for k in self._entries]
        return {
            "entries": entries,
            "size": len(entries),
            "max_entries": self.max_entries,
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "evictions": int(self._evictions.value),
            "compile_seconds_total": round(self._compile_s.value, 3),
        }
