"""Explicit compiled-executable cache: warmup, LRU, hit/miss counters.

Relying on `jax.jit`'s implicit cache is how serving stacks get surprise
multi-second stalls: the first request of a new shape compiles inline, on
the request thread, with no way to see it coming on a dashboard. Here the
executable for every (batch, frames, H, W, bits, configs) program is
compiled EXPLICITLY via the AOT path
(``reconstruct_batch_fn(...).lower(shapes).compile()``) and held in a
bounded LRU:

* **warmup** precompiles the configured buckets × batch sizes at startup,
  so steady-state traffic never sees a compile (the zero-recompile
  acceptance bar; asserted in tests via these counters AND the jit cache
  sizes — AOT executables bypass the jit cache entirely, so those sizes
  staying flat proves no request slipped onto the implicit path);
* **hit/miss/compile-time counters** land in the metrics registry
  (``serve_program_cache_*`` on /metrics), so a miss storm is visible as
  a counter spike, not a latency mystery;
* **LRU eviction** bounds device/host program memory when a service sees
  many one-off shapes; evicting drops the executable, and the next use
  recompiles (counted).

A compile happens at most once per key even under concurrent misses: the
per-key entry holds an event that racers wait on while the first caller
compiles outside the registry lock.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict

from ..utils import trace
from ..utils.log import get_logger
from .batcher import BucketKey
from .blobstore import BlobStore, open_blob_store

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class ProgramKey:
    """BucketKey + batch size (+ placement): one compiled executable.

    ``device`` pins the program to one chip (a `lanes.DeviceLane`
    label like ``"cpu:0"``) — lanes on different devices hold DISTINCT
    executables, so the zero-recompile steady-state assertion is
    per-chip. ``shards`` > 0 selects the sharded cross-chip tier
    instead: one program whose camera rows span that many devices
    (`parallel/mesh.py`). ``span`` is the sharded program's device-SET
    identity — the sorted labels of the exact chips the mesh is built
    over — so a span re-formed around a dead member
    (``lanes.DeviceLanePool.span_devices``) is a distinct executable
    from the full-width one, and reviving the member brings the
    still-cached full-span program back without a compile. An empty
    span with ``shards`` > 0 is the historical count-prefix program
    (first ``shards`` devices in enumeration order). ``device=None,
    shards=0`` is the historical single-default-device program.
    """

    bucket: BucketKey
    batch: int
    device: str | None = None
    shards: int = 0
    span: tuple = ()

    def label(self) -> str:
        base = f"B{self.batch}:{self.bucket.label()}"
        if self.shards:
            if self.span:
                return f"{base}@mesh{self.shards}[{'+'.join(self.span)}]"
            return f"{base}@mesh{self.shards}"
        if self.device is not None:
            return f"{base}@{self.device}"
        return base


class _Entry:
    __slots__ = ("ready", "compiled", "error", "compile_s")

    def __init__(self):
        self.ready = threading.Event()
        self.compiled = None
        self.error: BaseException | None = None
        self.compile_s = 0.0


class ProgramCache:
    """LRU of AOT-compiled batch-reconstruction executables.

    ``calib_provider(height, width)`` returns the device Calibration for a
    bucket; its array shapes (not values) parameterize the compile, so one
    cache serves any rig whose calibration matches the bucket geometry.
    """

    def __init__(self, calib_provider, max_entries: int = 32,
                 registry: "trace.MetricsRegistry | None" = None):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.calib_provider = calib_provider
        self.max_entries = max_entries
        self.registry = registry if registry is not None else trace.REGISTRY
        self._lock = threading.Lock()
        self._entries: OrderedDict[ProgramKey, _Entry] = OrderedDict()
        self._hits = self.registry.counter(
            "serve_program_cache_hits_total",
            "program-cache lookups served without compiling")
        self._misses = self.registry.counter(
            "serve_program_cache_misses_total",
            "program-cache lookups that triggered a compile")
        self._evictions = self.registry.counter(
            "serve_program_cache_evictions_total",
            "programs dropped by LRU bounding")
        self._compile_s = self.registry.counter(
            "serve_program_cache_compile_seconds_total",
            "cumulative wall-clock spent compiling programs")
        self._entries_gauge = self.registry.gauge(
            "serve_program_cache_entries", "resident compiled programs")
        # Placement memos (tiny, bounded by devices × buckets): the
        # sharding an input batch is staged with, and the device-placed
        # calibration the executable was LOWERED against — AOT
        # executables bake argument placement in, so the exact placed
        # arrays must be reused at every call.
        self._placements: dict = {}
        self._placed_calibs: dict = {}
        self._meshes: dict = {}

    # -- placement (device lanes / sharded tier) -----------------------

    def _mesh_for(self, key: ProgramKey):
        """The mesh a sharded ``key`` stages over, memoized per
        (shards, span) so the replicated-calib and batch shardings of
        one program share one Mesh object. Span-keyed keys resolve
        their exact device set (`parallel/mesh.serve_span_mesh`);
        span-less sharded keys keep the historical enumeration prefix."""
        memo = (key.shards, key.span)
        m = self._meshes.get(memo)
        if m is None:
            import jax

            from ..parallel import mesh as pmesh

            if key.span:
                m = pmesh.serve_span_mesh(key.span)
            else:
                m = pmesh.serve_space_mesh(
                    key.shards, devices=jax.local_devices()[:key.shards])
            self._meshes[memo] = m
        return m

    def _sharding_for(self, key: ProgramKey):
        """The input-batch sharding for ``key``: a SingleDeviceSharding
        for a lane-pinned program, the rows-over-space NamedSharding for
        a sharded one (over the key's exact device span when it carries
        one), None for the historical default placement."""
        memo = (key.device, key.shards, key.span)
        if memo in self._placements:
            return self._placements[memo]
        import jax

        sharding = None
        if key.shards:
            from ..parallel import mesh as pmesh

            sharding = pmesh.stack_batch_sharding(self._mesh_for(key))
        elif key.device is not None:
            dev = next((d for d in jax.local_devices()
                        if f"{d.platform}:{d.id}" == key.device), None)
            if dev is None:
                raise ValueError(
                    f"ProgramKey names device {key.device!r} but no "
                    "such local device exists")
            sharding = jax.sharding.SingleDeviceSharding(dev)
        self._placements[memo] = sharding
        return sharding

    def placed_calib(self, key: ProgramKey):
        """The calibration pytree placed where ``key``'s program
        expects it: on the lane's device, replicated over the sharded
        tier's mesh, or wherever the provider left it (default keys).
        Memoized per (bucket geometry, placement) — the arrays' identity
        must persist so AOT calls always see the lowered placement."""
        b = key.bucket
        memo = (b.height, b.width, key.device, key.shards, key.span)
        with self._lock:
            placed = self._placed_calibs.get(memo)
        if placed is not None:
            return placed
        calib = self.calib_provider(b.height, b.width)
        if key.shards:
            import jax

            from ..parallel import mesh as pmesh

            calib = jax.device_put(
                calib, pmesh.replicated(self._mesh_for(key)))
        elif key.device is not None:
            import jax

            # SingleDeviceSharding is itself a device_put target.
            calib = jax.device_put(calib, self._sharding_for(key))
        with self._lock:
            self._placed_calibs[memo] = calib
        return calib

    def stage(self, key: ProgramKey, batch):
        """Stage one host batch array where ``key``'s executable expects
        its input: the lane device, the sharded mesh, or default."""
        import jax
        import jax.numpy as jnp

        sharding = self._sharding_for(key)
        if sharding is None:
            return jnp.asarray(batch)
        return jax.device_put(batch, sharding)

    # ------------------------------------------------------------------

    def _compile(self, key: ProgramKey):
        import jax
        import jax.numpy as jnp

        from ..models import pipeline

        b = key.bucket
        calib = self.placed_calib(key)
        fn = pipeline.reconstruct_batch_fn(
            b.col_bits, b.row_bits, decode_cfg=b.decode_cfg,
            tri_cfg=b.tri_cfg, downsample=b.downsample)
        stack_spec = jax.ShapeDtypeStruct(
            (key.batch, b.frames, b.height, b.width), jnp.uint8,
            sharding=self._sharding_for(key))
        t0 = time.monotonic()
        compiled = fn.lower(stack_spec, calib).compile()
        dt = time.monotonic() - t0
        self._compile_s.inc(dt)
        log.info("compiled %s in %.2fs", key.label(), dt)
        return compiled, dt

    def get(self, key: ProgramKey):
        """The compiled executable for ``key`` — compiling (and counting a
        miss) if absent, else a counted hit. Raises the original compile
        error on every lookup of a key whose compile failed (failed
        entries are not cached)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                owner = False
            else:
                entry = _Entry()
                self._entries[key] = entry
                owner = True
        if owner:
            self._misses.inc()
            try:
                entry.compiled, entry.compile_s = self._compile(key)
            except BaseException as e:
                entry.error = e
                with self._lock:
                    self._entries.pop(key, None)
                raise
            finally:
                entry.ready.set()
            self._bound()
        else:
            entry.ready.wait()
            if entry.error is not None:
                raise entry.error
            self._hits.inc()
        with self._lock:
            self._entries_gauge.set(len(self._entries))
        return entry.compiled

    def _bound(self) -> None:
        with self._lock:
            while len(self._entries) > self.max_entries:
                # Victim = oldest READY entry: an in-flight compile must
                # not be popped (its executable would be dropped the
                # moment it finishes, forcing a duplicate compile on the
                # next lookup of that key).
                victim = next((k for k, e in self._entries.items()
                               if e.ready.is_set()), None)
                if victim is None:
                    break  # everything resident is mid-compile
                self._entries.pop(victim)
                self._evictions.inc()
                log.info("evicted %s (LRU, max_entries=%d)",
                         victim.label(), self.max_entries)

    # ------------------------------------------------------------------

    def warmup(self, bucket_keys, batch_sizes=(),
               program_keys=()) -> dict:
        """Precompile every (bucket, batch) program — plus any explicit
        ``program_keys`` (the per-device / sharded lane set the service
        routes to); returns {label: compile_s}. Called at service start
        so the first real request of any configured shape is a hit."""
        out = {}
        keys = [ProgramKey(bucket=bucket, batch=int(b))
                for bucket in bucket_keys for b in batch_sizes]
        keys.extend(program_keys)
        for key in keys:
            with trace.span("serve.warmup", program=key.label()):
                t0 = time.monotonic()
                self.get(key)
                out[key.label()] = round(time.monotonic() - t0, 3)
        # Warmup compiles are misses by construction; zero them out of the
        # steady-state signal? No — they stay counted (honest totals), and
        # the zero-recompile assertion compares counters AFTER warmup.
        return out

    def stats(self) -> dict:
        with self._lock:
            entries = [k.label() for k in self._entries]
        return {
            "entries": entries,
            "size": len(entries),
            "max_entries": self.max_entries,
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "evictions": int(self._evictions.value),
            "compile_seconds_total": round(self._compile_s.value, 3),
        }


# ---------------------------------------------------------------------------
# Content-hash result cache
# ---------------------------------------------------------------------------


def content_key(stack, config_sig: str) -> str:
    """SHA-256 over the capture stack (shape + dtype + raw bytes) and
    the reconstruction config signature: two submits with identical
    pixels AND identical processing settings name the same artifact.
    Shape/dtype are part of the key — raw bytes alone would let two
    different-shaped stacks over the same buffer collide."""
    import numpy as np

    h = hashlib.sha256()
    h.update(config_sig.encode())
    h.update(f"{stack.shape}/{stack.dtype}".encode())
    h.update(np.ascontiguousarray(stack).tobytes())
    return h.hexdigest()


class ContentCache:
    """Byte-bounded LRU of finished result artifacts keyed by content
    hash — the admission-time duplicate detector.

    A duplicate submit returns the cached mesh without touching the
    queue, which makes it independent of BOTH bounds the job registry
    enforces (``completed_cap`` / ``result_cache_bytes``): a result
    evicted from the registry's byte budget still answers a resubmit
    with 200 instead of 410. With a directory (the journal volume's
    ``content/``) the cache also survives restarts: payloads live on
    disk (``<key>.bin`` + ``<key>.json`` sidecar, tmp + atomic rename),
    the in-memory index is rebuilt from the sidecars at open, and hits
    read the payload back lazily. Without a directory it is memory-only
    with the same budget.

    Persistence rides the :class:`~.blobstore.BlobStore` seam: ``dir``
    opens the historical local layout (byte-for-byte identical), or
    pass ``store=`` an :class:`~.blobstore.ObjectStore` to persist
    artifacts in an S3-style service instead of a POSIX volume. Either
    way every store failure is absorbed here (quarantine + miss) —
    corruption and outages degrade the cache, never admission.

    Failed jobs are never cached (their taxonomy payload is the honest
    answer), and session stops never consult it (a duplicate stop is the
    covisibility gate's decision, not the cache's).
    """

    def __init__(self, max_bytes: int = 256 << 20, dir: str | None = None,
                 registry: "trace.MetricsRegistry | None" = None,
                 store: BlobStore | None = None):
        self.max_bytes = int(max_bytes)
        self.dir = dir
        # allow_faults=False: SL_BLOB_FAULTS targets the SHARED fleet
        # stores (handoff streams, pin board); silently injecting env
        # faults into every replica's private artifact cache would skew
        # the duplicate-hit ratios the fleet gates assert on. Chaos
        # coverage for this class passes a FaultyBlobStore explicitly.
        self._blob: BlobStore | None = (
            store if store is not None
            else (open_blob_store(dir, allow_faults=False)
                  if dir is not None else None))
        self.registry = registry if registry is not None else trace.REGISTRY
        self._lock = threading.Lock()
        # key -> {"bytes": int, "format": str, "meta": dict,
        #         "payload": bytes | None}   (payload None = on disk)
        self._index: OrderedDict[str, dict] = OrderedDict()
        self._held = 0
        self._hits = self.registry.counter(
            "serve_content_cache_hits_total",
            "admissions answered from the content-hash result cache")
        self._misses = self.registry.counter(
            "serve_content_cache_misses_total",
            "admissions that found no cached artifact")
        self._evictions = self.registry.counter(
            "serve_content_cache_evictions_total",
            "artifacts dropped by the byte budget")
        self._corrupt = self.registry.counter(
            "serve_content_cache_corrupt_total",
            "corrupt/truncated disk blobs quarantined at load or hit")
        self._bytes_gauge = self.registry.gauge(
            "serve_content_cache_bytes", "retained artifact bytes")
        # Cached quarantine-object count: stats() rides every /healthz
        # scrape (and the router's per-second signal sweep), so it must
        # never pay a store listing — seeded once at open, bumped per
        # quarantine move.
        self._quarantined_objects = 0
        if self._blob is not None:
            if dir is not None:
                os.makedirs(os.path.join(dir, "quarantine"),
                            exist_ok=True)
            try:
                self._quarantined_objects = sum(
                    1 for k in self._blob.list("quarantine/")
                    if k.endswith(".bin"))
            except OSError:
                pass
            self._load_index()

    # ------------------------------------------------------------------

    def _payload_key(self, key: str) -> str:
        return f"{key}.bin"

    def _quarantine(self, key: str, reason: str) -> None:
        """Move a corrupt entry's objects aside (never delete evidence —
        the quarantine prefix is what a post-mortem inspects) and count
        it. The entry is already out of the index when this runs; a
        quarantined key simply misses, it NEVER raises into admission."""
        self._corrupt.inc()
        log.warning("content cache entry %s quarantined: %s",
                    key[:12], reason)
        for suffix in (".bin", ".json"):
            try:
                self._blob.rename(f"{key}{suffix}",
                                  f"quarantine/{key}{suffix}")
                if suffix == ".bin":
                    with self._lock:
                        self._quarantined_objects += 1
            except OSError:
                log.debug("quarantine move of %s%s failed", key[:12],
                          suffix)

    def _load_index(self) -> None:
        """Rebuild the index from sidecars, oldest first (so LRU order
        approximates the previous process's write order)."""
        sidecars = []
        try:
            names = self._blob.list("")
        except OSError as e:
            log.warning("content cache index unreadable: %s", e)
            names = []
        for fname in names:
            if "/" in fname or not fname.endswith(".json"):
                continue
            try:
                raw = self._blob.get(fname)
                doc = json.loads(raw.decode()) if raw is not None \
                    else None
            except (OSError, ValueError, UnicodeDecodeError):
                continue
            if doc is None:
                continue
            key = fname[:-5]
            try:
                size = self._blob.size(self._payload_key(key))
            except OSError:
                size = None
            if size is None:
                continue  # no payload: sidecar-only orphan
            if size != int(doc.get("bytes", -1)):
                # Truncated/grown blob (torn write, disk fault): a miss
                # and a quarantine, never an entry that would raise —
                # or serve garbage — at hit time.
                self._quarantine(key, f"size {size} != sidecar "
                                      f"{doc.get('bytes')}")
                continue
            sidecars.append((float(doc.get("t", 0.0)), key, doc))
        for _, key, doc in sorted(sidecars):
            n = int(doc.get("bytes", 0))
            self._index[key] = {"bytes": n,
                                "format": doc.get("format", "ply"),
                                "meta": dict(doc.get("meta") or {}),
                                "sha256": doc.get("sha256"),
                                "payload": None}
            self._held += n
        # Enforce the budget at load too: a lowered max_bytes (or a
        # previous process's fuller budget) must not survive the
        # restart — evict oldest exactly like put() does.
        while self._held > self.max_bytes and len(self._index) > 1:
            victim, entry = self._index.popitem(last=False)
            self._held -= entry["bytes"]
            self._evictions.inc()
            for suffix in (".bin", ".json"):
                try:
                    self._blob.delete(f"{victim}{suffix}")
                except OSError:
                    pass
        self._bytes_gauge.set(self._held)
        if self._index:
            log.info("content cache: %d artifacts (%d MB) recovered "
                     "from %s", len(self._index), self._held >> 20,
                     self.dir or self._blob.stats())

    # ------------------------------------------------------------------

    def get(self, key: str) -> tuple[bytes, dict, str] | None:
        """(payload, meta, format) for ``key``, or None. Counts the
        hit/miss; disk reads happen outside the index lock. A corrupt
        or truncated disk blob counts as a MISS and is quarantined —
        admission never sees an exception from this path."""
        return self._get(key, count=True)

    def peek(self, key: str) -> tuple[bytes, dict, str] | None:
        """``get`` without touching the hit/miss counters — the peer
        protocol's export path (serve/fleet.py), so fleet probes don't
        masquerade as admission traffic on this replica's dashboards.
        Corruption handling is identical (quarantine, miss)."""
        return self._get(key, count=False)

    def _get(self, key: str, count: bool) -> tuple[bytes, dict, str] | None:
        with self._lock:
            entry = self._index.get(key)
            if entry is not None:
                self._index.move_to_end(key)
                payload = entry["payload"]
                meta, fmt = dict(entry["meta"]), entry["format"]
                want_bytes = entry["bytes"]
                want_sha = entry.get("sha256")
        if entry is None:
            if count:
                self._misses.inc()
            return None
        if payload is None:
            try:
                payload = self._blob.get(self._payload_key(key))
            except OSError as e:
                payload = None
                reason = f"unreadable ({e})"
            else:
                reason = "payload object missing"
            if payload is None:
                self._drop(key)
                self._quarantine(key, reason)
                if count:
                    self._misses.inc()
                return None
            # Integrity gate: a bit-flipped or truncated blob must never
            # reach a client (or a fleet peer) as a "cached artifact".
            corrupt = (len(payload) != want_bytes
                       or (want_sha is not None
                           and hashlib.sha256(payload).hexdigest()
                           != want_sha))
            if corrupt:
                self._drop(key)
                self._quarantine(
                    key, f"payload {len(payload)}B fails integrity "
                         f"check (want {want_bytes}B)")
                if count:
                    self._misses.inc()
                return None
        if count:
            self._hits.inc()
        return payload, meta, fmt

    def _drop(self, key: str) -> None:
        with self._lock:
            gone = self._index.pop(key, None)
            if gone is not None:
                self._held -= gone["bytes"]
                self._bytes_gauge.set(self._held)

    def put(self, key: str, payload: bytes, meta: dict, fmt: str) -> None:
        """Retain one finished artifact; evicts oldest past the byte
        budget. File writes happen before the index insert so a hit can
        never race a half-written payload."""
        if len(payload) > self.max_bytes:
            return  # one artifact over the whole budget: not cacheable
        stored: bytes | None = payload
        # Digest only for store-backed caches: memory-held payloads are
        # never re-read, so hashing them would be pure wasted CPU on
        # the job-completion path.
        sha = (hashlib.sha256(payload).hexdigest()
               if self._blob is not None else None)
        if self._blob is not None:
            side = json.dumps({"format": fmt, "meta": meta,
                               "bytes": len(payload), "sha256": sha,
                               "t": time.time()}).encode()
            try:
                self._blob.put(self._payload_key(key), payload)
                self._blob.put(f"{key}.json", side)
            except OSError as e:
                log.warning("content cache write failed: %s", e)
                return
            stored = None
        victims: list[str] = []
        with self._lock:
            prior = self._index.pop(key, None)
            if prior is not None:
                self._held -= prior["bytes"]
            self._index[key] = {"bytes": len(payload), "format": fmt,
                                "meta": dict(meta), "sha256": sha,
                                "payload": stored}
            self._held += len(payload)
            while self._held > self.max_bytes and len(self._index) > 1:
                victim, entry = self._index.popitem(last=False)
                self._held -= entry["bytes"]
                victims.append(victim)
            self._bytes_gauge.set(self._held)
        for victim in victims:
            self._evictions.inc()
            if self._blob is not None:
                for suffix in (".bin", ".json"):
                    try:
                        self._blob.delete(f"{victim}{suffix}")
                    except OSError:
                        pass

    def stats(self) -> dict:
        with self._lock:
            quarantined = self._quarantined_objects
            return {
                "entries": len(self._index),
                "bytes": self._held,
                "max_bytes": self.max_bytes,
                "persistent": self._blob is not None,
                "backend": (self._blob.stats().get("backend")
                            if self._blob is not None else None),
                "hits": int(self._hits.value),
                "misses": int(self._misses.value),
                "evictions": int(self._evictions.value),
                "corrupt_quarantined": int(self._corrupt.value),
                "quarantined_objects": quarantined,
            }
