"""Fleet tier: replica identity, peer protocol, shared content cache.

PR 8 hardened a *single* replica — journal, content cache and governor
all die with the process's disk and port. This module is the peer half
of the fleet tier (the front router lives in serve/router.py): every
:class:`~.service.ReconstructionService` can now carry a **replica
identity** and a **peer table**, and consult its peers' content caches
at admission time, so a mesh computed on replica A answers a duplicate
submit on replica B without touching B's queue or device.

The peer protocol is deliberately tiny — ``GET /cache/<key>`` over the
existing stdlib HTTP front end — and defended on every axis a sick peer
could hurt us through:

* **bounded timeouts** — one per-peer request bound
  (``peer_timeout_s``) and one whole-lookup budget (``peer_budget_s``);
  a slow peer degrades to a local miss, never a stall in admission;
* **per-peer circuit breakers** — the PR-8 governor's
  :class:`~.governor.CircuitBreaker` machinery, one per peer, so a
  persistently failing peer is skipped for a cooldown instead of being
  probed on every admission;
* **jittered exponential backoff** — transient transport failures back
  the peer off (base × 2^n, ±50% jitter, capped) so N replicas don't
  hammer a restarting peer in lockstep;
* **single-flight dedup** — concurrent admissions of the same content
  key share ONE peer fetch; racers wait (bounded) instead of fanning N
  identical requests across the fleet;
* **negative-result TTL** — a fleet-wide miss is remembered for a few
  seconds, so a burst of novel submits does not re-sweep every peer per
  request.

:class:`PeerTransport` is the single seam to the network; the
fault-injecting :class:`FaultyPeerTransport` (seeded drops + latency,
``SL_PEER_FAULTS`` env for subprocess replicas) is how the fleet chaos
harness (tests/test_fleet.py, bench config [10]) proves the degraded
modes. :class:`HashRing` is the consistent-hash used by the router for
content-key admission placement and session preference order.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import json
import os
import random
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict

from ..utils import trace
from ..utils.log import get_logger
from .governor import CircuitBreaker

log = get_logger(__name__)

#: Env var carrying a JSON :class:`PeerFaultPlan` for subprocess replicas
#: (the chaos harness sets it; production never does).
PEER_FAULTS_ENV = "SL_PEER_FAULTS"


# ---------------------------------------------------------------------------
# Transport (the single network seam — and the fault-injection point)
# ---------------------------------------------------------------------------


class PeerTransport:
    """Stdlib HTTP with a bounded timeout. Connection-level failures
    surface as OSError (``urllib.error.URLError`` subclasses it); HTTP
    error statuses are returned, not raised — the caller decides what a
    404 vs a 503 means for the peer's health."""

    def request(self, method: str, url: str, body: bytes | None = None,
                headers: dict | None = None,
                timeout_s: float = 5.0) -> tuple[int, dict, bytes]:
        req = urllib.request.Request(url, data=body,
                                     headers=dict(headers or {}),
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, dict(r.headers), r.read()
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), e.read()

    def get(self, url: str,
            timeout_s: float = 5.0) -> tuple[int, dict, bytes]:
        return self.request("GET", url, timeout_s=timeout_s)


@dataclasses.dataclass(frozen=True)
class PeerFaultPlan:
    """Seeded peer-network fault schedule: a deterministic fraction of
    requests is dropped (connection error) and/or delayed. One shared
    RNG stream per transport — the same seed reproduces the same fault
    sequence, the chaos-harness determinism rule (hw/faults.py applied
    to the peer network)."""

    seed: int = 0
    drop_rate: float = 0.0      # P(request raises URLError instead)
    latency_s: float = 0.0      # injected delay when latency fires
    latency_rate: float = 0.0   # P(latency_s is injected)

    @classmethod
    def from_env(cls, env: str = PEER_FAULTS_ENV) -> "PeerFaultPlan | None":
        spec = os.environ.get(env)
        if not spec:
            return None
        try:
            doc = json.loads(spec)
        except ValueError as e:
            log.error("ignoring malformed %s=%r: %s", env, spec, e)
            return None
        allowed = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in allowed})


class FaultyPeerTransport(PeerTransport):
    """Wraps a transport with a :class:`PeerFaultPlan`. ``sleep`` is
    injectable so unit tests assert latency decisions without waiting."""

    def __init__(self, plan: PeerFaultPlan,
                 inner: PeerTransport | None = None, sleep=time.sleep):
        self.plan = plan
        self.inner = inner if inner is not None else PeerTransport()
        self._sleep = sleep
        self._lock = threading.Lock()  # one deterministic RNG stream
        self._rng = random.Random(plan.seed)
        self.drops = 0
        self.delays = 0

    def request(self, method, url, body=None, headers=None,
                timeout_s=5.0):
        with self._lock:
            drop = self._rng.random() < self.plan.drop_rate
            delay = (not drop
                     and self._rng.random() < self.plan.latency_rate)
            if drop:
                self.drops += 1
            if delay:
                self.delays += 1
        if drop:
            raise urllib.error.URLError(
                ConnectionResetError("injected peer-network drop"))
        if delay:
            self._sleep(self.plan.latency_s)
        return self.inner.request(method, url, body=body, headers=headers,
                                  timeout_s=timeout_s)


def transport_from_env() -> PeerTransport:
    """The transport a real replica should use: fault-injecting when the
    chaos harness armed ``SL_PEER_FAULTS``, plain otherwise."""
    plan = PeerFaultPlan.from_env()
    if plan is None:
        return PeerTransport()
    log.warning("peer transport faults armed: %s", plan)
    return FaultyPeerTransport(plan)


# ---------------------------------------------------------------------------
# Consistent-hash ring
# ---------------------------------------------------------------------------


def _h64(s: str) -> int:
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring with virtual nodes.

    ``node_for(key)`` is stable under membership changes: removing one
    node remaps only the keys that hashed to it (its vnode arcs), which
    is exactly the duplicate-hit-friendly property the router's
    content-key admission needs — a replica death must not reshuffle
    every key to a new (cache-cold) replica. Thread-safe."""

    def __init__(self, nodes=(), vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._lock = threading.Lock()
        self._ring: list[tuple[int, str]] = []
        self._nodes: set[str] = set()
        for n in nodes:
            self.add(n)

    def add(self, node: str) -> None:
        with self._lock:
            if node in self._nodes:
                return
            self._nodes.add(node)
            for v in range(self.vnodes):
                bisect.insort(self._ring, (_h64(f"{node}#{v}"), node))

    def remove(self, node: str) -> None:
        with self._lock:
            if node not in self._nodes:
                return
            self._nodes.discard(node)
            self._ring = [(h, n) for h, n in self._ring if n != node]

    @property
    def nodes(self) -> set[str]:
        with self._lock:
            return set(self._nodes)

    def preference(self, key: str, avoid=()) -> list[str]:
        """Distinct nodes in ring order from ``key``'s position — the
        failover order: preference[0] is the consistent-hash owner,
        preference[1] the node keys fall over to when it dies."""
        avoid = set(avoid)
        with self._lock:
            if not self._ring:
                return []
            out: list[str] = []
            start = bisect.bisect_left(self._ring, (_h64(key), ""))
            for i in range(len(self._ring)):
                node = self._ring[(start + i) % len(self._ring)][1]
                if node not in avoid and node not in out:
                    out.append(node)
            return out

    def node_for(self, key: str, avoid=()) -> str | None:
        pref = self.preference(key, avoid=avoid)
        return pref[0] if pref else None


# ---------------------------------------------------------------------------
# Peer table (breaker + backoff per peer)
# ---------------------------------------------------------------------------


class _PeerState:
    """One peer's health bookkeeping: a circuit breaker for persistent
    failure, exponential backoff for transient failure. Both answer one
    question — "should we spend a request on this peer right now?"."""

    def __init__(self, url: str, breaker: CircuitBreaker,
                 backoff_base_s: float, backoff_cap_s: float,
                 rng: random.Random):
        self.url = url
        self.breaker = breaker
        self._base = backoff_base_s
        self._cap = backoff_cap_s
        self._rng = rng
        self._lock = threading.Lock()
        self._fails = 0
        self._backoff_until = 0.0

    def usable(self) -> bool:
        if self.breaker.open_remaining() is not None:
            return False
        with self._lock:
            return time.monotonic() >= self._backoff_until

    def note_ok(self) -> None:
        self.breaker.note_ok()
        with self._lock:
            self._fails = 0
            self._backoff_until = 0.0

    def note_failure(self) -> bool:
        """Record one failed request; bumps the jittered exponential
        backoff. Returns True when this failure tripped the breaker."""
        tripped, _, _ = self.breaker.note_failure()
        with self._lock:
            self._fails += 1
            delay = min(self._cap, self._base * (2 ** (self._fails - 1)))
            self._backoff_until = (time.monotonic()
                                   + delay * self._rng.uniform(0.5, 1.5))
        return tripped

    def stats(self) -> dict:
        remaining = self.breaker.open_remaining()
        with self._lock:
            backoff = max(0.0, self._backoff_until - time.monotonic())
        return {"url": self.url,
                "breaker_open_s": (round(remaining, 2)
                                   if remaining is not None else None),
                "backoff_s": round(backoff, 2),
                "consecutive_failures": self._fails}


# ---------------------------------------------------------------------------
# Peer content-cache client
# ---------------------------------------------------------------------------


class PeerCacheClient:
    """Admission-time peer lookup for the shared content cache.

    ``lookup(key)`` returns ``(payload, meta, format)`` from the first
    peer that holds the artifact, or None. The calling admission path
    treats None exactly like a local miss — every degraded mode (slow
    peer, dead peer, open breaker, spent budget) converges on "compute
    it locally", never on a stall or an error."""

    def __init__(self, peers, transport: PeerTransport | None = None,
                 timeout_s: float = 2.0, budget_s: float = 3.0,
                 negative_ttl_s: float = 5.0,
                 breaker_window: int = 8, breaker_min_samples: int = 4,
                 breaker_failure_rate: float = 0.5,
                 breaker_cooldown_s: float = 10.0,
                 backoff_base_s: float = 0.5, backoff_cap_s: float = 30.0,
                 registry: "trace.MetricsRegistry | None" = None,
                 rng: random.Random | None = None):
        self.timeout_s = float(timeout_s)
        self.budget_s = float(budget_s)
        self.negative_ttl_s = float(negative_ttl_s)
        self.transport = (transport if transport is not None
                          else transport_from_env())
        self.registry = registry if registry is not None else trace.REGISTRY
        rng = rng if rng is not None else random.Random()
        self._peers = [
            _PeerState(url.rstrip("/"),
                       CircuitBreaker(window=breaker_window,
                                      min_samples=breaker_min_samples,
                                      failure_rate=breaker_failure_rate,
                                      cooldown_s=breaker_cooldown_s),
                       backoff_base_s, backoff_cap_s, rng)
            for url in peers]
        self._lock = threading.Lock()
        # Single-flight: key -> {"ev": Event, "result": tuple | None}.
        self._inflight: dict[str, dict] = {}
        # Negative TTL: key -> monotonic expiry (bounded FIFO).
        self._negative: OrderedDict[str, float] = OrderedDict()
        self._negative_cap = 4096
        self._hits = self.registry.counter(
            "serve_peer_cache_hits_total",
            "admissions answered from a peer's content cache")
        self._misses = self.registry.counter(
            "serve_peer_cache_misses_total",
            "peer lookups that found no artifact fleet-wide")
        self._failures = self.registry.counter(
            "serve_peer_fetch_failures_total",
            "peer requests that failed at the transport level")
        self._skips = self.registry.counter(
            "serve_peer_skips_total",
            "peer requests not attempted (breaker open or backing off)")
        self._breaker_trips = self.registry.counter(
            "serve_peer_breaker_trips_total",
            "per-peer circuit-breaker openings")

    @property
    def peer_urls(self) -> list[str]:
        return [p.url for p in self._peers]

    # ------------------------------------------------------------------

    def _peer_order(self, key: str) -> list[_PeerState]:
        """Rendezvous order: peers sorted by hash(key, peer) — the same
        key probes peers in the same order fleet-wide (the likely owner
        first under the router's consistent-hash placement), different
        keys spread their first probes across peers."""
        return sorted(self._peers,
                      key=lambda p: _h64(f"{key}@{p.url}"))

    def lookup(self, key: str) -> tuple[bytes, dict, str] | None:
        if not self._peers:
            return None
        now = time.monotonic()
        with self._lock:
            exp = self._negative.get(key)
            if exp is not None:
                if now < exp:
                    return None
                del self._negative[key]
            rec = self._inflight.get(key)
            if rec is None:
                rec = {"ev": threading.Event(), "result": None}
                self._inflight[key] = rec
                owner = True
            else:
                owner = False
        if not owner:
            # Single-flight racer: share the owner's fetch. A timeout
            # here (wedged owner) is just a miss — never a stall.
            rec["ev"].wait(self.budget_s)
            return rec["result"]
        result = None
        try:
            result = self._fetch(key)
        finally:
            with self._lock:
                if result is None:
                    self._prune_negative_locked(now)
                    self._negative[key] = (time.monotonic()
                                           + self.negative_ttl_s)
                self._inflight.pop(key, None)
            rec["result"] = result
            rec["ev"].set()
        return result

    def _prune_negative_locked(self, now: float) -> None:
        while self._negative:
            k, exp = next(iter(self._negative.items()))
            if exp >= now and len(self._negative) < self._negative_cap:
                break
            del self._negative[k]

    def _fetch(self, key: str) -> tuple[bytes, dict, str] | None:
        deadline = time.monotonic() + self.budget_s
        for peer in self._peer_order(key):
            if time.monotonic() >= deadline:
                break
            if not peer.usable():
                self._skips.inc()
                continue
            timeout = min(self.timeout_s,
                          max(0.05, deadline - time.monotonic()))
            try:
                status, hdrs, body = self.transport.get(
                    f"{peer.url}/cache/{key}", timeout_s=timeout)
            except OSError as e:
                self._failures.inc()
                if peer.note_failure():
                    self._breaker_trips.inc()
                    log.warning("peer %s breaker opened (%s)",
                                peer.url, e)
                continue
            if status == 200:
                peer.note_ok()
                try:
                    meta = json.loads(hdrs.get("X-Content-Meta") or "{}")
                except ValueError:
                    meta = {}
                fmt = hdrs.get("X-Content-Format", "ply")
                self._hits.inc()
                return body, meta, fmt
            if status == 404:
                peer.note_ok()   # healthy peer, honest miss
                continue
            # Draining (503) or confused (4xx/5xx) peer: a failure for
            # backoff purposes so we stop hammering it, but not a
            # transport error.
            self._failures.inc()
            if peer.note_failure():
                self._breaker_trips.inc()
        self._misses.inc()
        return None

    def stats(self) -> dict:
        with self._lock:
            negative = len(self._negative)
            inflight = len(self._inflight)
        return {
            "peers": [p.stats() for p in self._peers],
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "fetch_failures": int(self._failures.value),
            "skips": int(self._skips.value),
            "breaker_trips": int(self._breaker_trips.value),
            "negative_entries": negative,
            "inflight": inflight,
        }
