"""Stdlib client for the reconstruction service.

The wire format is deliberately primitive — an ``.npy`` body plus a few
``X-`` headers — so anything that can HTTP-POST a file can submit a scan
(curl included; docs/SERVING.md shows the one-liner). This class wraps
the submit → poll → fetch dance for tests, the bench offered-load sweep
(config [7]) and the CI smoke script, with honest error surfacing:
backpressure (429/503) raises :class:`BackpressureError` carrying the
server's retry-after hint instead of burying it in response prose.

Submitting calls (``submit`` / ``submit_stop`` / ``create_session``)
retry backpressure themselves by default: the server's ``Retry-After``
hint is honored when present (else exponential backoff), jittered so a
rejected burst does not re-arrive as the same burst, and bounded by BOTH
an attempt count (``retries``) and a wall-clock budget
(``retry_budget_s``). ``retries=0`` restores surface-immediately
semantics.

Fleet failover: ``base_url`` may be a LIST of replica (or router) URLs.
Connection-level failures (refused/reset/timeout — a dead replica)
raise :class:`TransportError`, which is retryable under the same backoff
policy and rotates the client to the next URL first, so the retry lands
on a live replica instead of hammering the corpse.
"""

from __future__ import annotations

import io
import json
import random
import time
import urllib.error
import urllib.request

import numpy as np


class ServeClientError(RuntimeError):
    """Non-retryable client-visible failure (4xx, failed job, timeout)."""

    def __init__(self, message: str, payload: dict | None = None):
        super().__init__(message)
        self.payload = payload or {}


class BackpressureError(ServeClientError):
    """Queue full (429) or draining (503) — retry after ``retry_after_s``."""

    def __init__(self, message: str, retry_after_s: float | None,
                 payload: dict | None = None):
        super().__init__(message, payload)
        self.retry_after_s = retry_after_s


class TransportError(ServeClientError):
    """Connection-level failure — refused, reset, DNS, timeout. A dead
    replica looks exactly like this, so it is RETRYABLE under the same
    backoff policy as backpressure, and a client constructed with
    several base URLs rotates to the next one before the retry."""


class ServeClient:
    def __init__(self, base_url, timeout_s: float = 30.0,
                 retries: int = 4, retry_backoff_s: float = 0.25,
                 retry_budget_s: float = 30.0,
                 unknown_grace_s: float = 0.0,
                 tenant: str | None = None):
        # One URL or a list: with a list, connection-level failures
        # rotate to the next replica (failover), while HTTP-level
        # answers (including 429/503) stay on the current one.
        urls = ([base_url] if isinstance(base_url, str)
                else list(base_url))
        if not urls:
            raise ValueError("base_url must name at least one replica")
        self._urls = [u.rstrip("/") for u in urls]
        self._url_idx = 0
        self.timeout_s = timeout_s
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_budget_s = float(retry_budget_s)
        # How long wait() keeps polling through "unknown job" 404s
        # before trusting them. Behind a fleet router, an acked job can
        # 404 transiently while its replica is dead-awaiting-recovery
        # (every survivor answers 404); clients that poll recoverable
        # jobs across failover set this to their recovery budget.
        # Default 0.0 keeps the honest fast 404.
        self.unknown_grace_s = float(unknown_grace_s)
        # Tenant identity: stamped as X-Tenant on every admission call
        # (submit / session create / stop) so per-tenant quotas and the
        # serve_tenant_* metrics attribute this client's load. None =
        # the server's "anon" bucket.
        self.tenant = tenant
        # Injectable for deterministic tests.
        self._sleep = time.sleep
        self._rng = random.Random()

    def _tenant_headers(self, headers: dict) -> dict:
        if self.tenant:
            headers["X-Tenant"] = self.tenant
        return headers

    @property
    def base_url(self) -> str:
        """The replica currently in rotation."""
        return self._urls[self._url_idx % len(self._urls)]

    def _rotate(self) -> None:
        if len(self._urls) > 1:
            self._url_idx = (self._url_idx + 1) % len(self._urls)

    # ------------------------------------------------------------------

    def _retrying(self, fn):
        """Run ``fn`` with jittered backoff on backpressure AND on
        connection-level failure (a dead/restarting replica): the
        server's Retry-After hint (when present) sets the base delay,
        otherwise exponential from ``retry_backoff_s``; every delay is
        jittered ±50% so N rejected clients don't re-arrive in lockstep.
        Bounded by attempts AND wall clock; the LAST error is re-raised
        intact (hint included) when the budget is spent. Transport
        failures have already rotated the base URL, so the retry lands
        on the next replica in the list."""
        deadline = time.monotonic() + self.retry_budget_s
        attempt = 0
        while True:
            try:
                return fn()
            except (BackpressureError, TransportError) as e:
                if attempt >= self.retries:
                    raise
                hint = getattr(e, "retry_after_s", None)
                base = (hint if hint
                        else self.retry_backoff_s * (2 ** attempt))
                delay = base * self._rng.uniform(0.5, 1.5)
                if time.monotonic() + delay > deadline:
                    raise
                self._sleep(delay)
                attempt += 1

    def _request(self, req: urllib.request.Request):
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, dict(r.headers), r.read()
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), e.read()
        except OSError as e:
            # urllib.error.URLError (connection refused/reset/DNS) and
            # raw socket timeouts are all OSError. Rotate FIRST so even
            # a non-retrying caller's next call tries the next replica.
            self._rotate()
            raise TransportError(
                f"replica unreachable ({e}); "
                f"next base URL: {self.base_url}") from e

    @staticmethod
    def _payload(body: bytes) -> dict:
        try:
            return json.loads(body.decode())
        except (ValueError, UnicodeDecodeError):
            return {"raw": body[:200].decode(errors="replace")}

    # ------------------------------------------------------------------

    def submit(self, stack: np.ndarray, result_format: str = "ply",
               priority: str = "normal",
               deadline_s: float | None = None) -> str:
        """POST one capture stack; returns the job id. Backpressure
        (429/503) is retried per the client's retry policy before a
        :class:`BackpressureError` surfaces."""
        stack = np.asarray(stack)
        if stack.dtype != np.uint8:
            # No silent coercion: casting float [0,1] data (or aliasing
            # int16 mod 256) would upload a well-formed but meaningless
            # stack that fails server-side with a misleading coverage
            # error. The caller converts explicitly or fixes the source.
            raise ServeClientError(
                f"stack must be uint8, got {stack.dtype} — convert "
                "explicitly (e.g. (x * 255).astype(np.uint8))")
        buf = io.BytesIO()
        np.save(buf, stack)
        headers = self._tenant_headers(
            {"Content-Type": "application/octet-stream",
             "X-Result-Format": result_format,
             "X-Priority": priority})
        if deadline_s is not None:
            headers["X-Deadline-S"] = str(deadline_s)
        data = buf.getvalue()

        def once():
            req = urllib.request.Request(self.base_url + "/submit",
                                         data=data, headers=headers,
                                         method="POST")
            status, hdrs, body = self._request(req)
            payload = self._payload(body)
            if status in (429, 503):
                msg = payload.get("error", {}).get("message", "overloaded")
                raise BackpressureError(
                    f"submit refused ({status}): {msg}",
                    self._retry_hint(payload, hdrs), payload)
            if status != 200:
                raise ServeClientError(
                    f"submit failed ({status}): {payload}", payload)
            return payload["job_id"]

        return self._retrying(once)

    @staticmethod
    def _retry_hint(payload: dict, hdrs: dict) -> float | None:
        retry = payload.get("error", {}).get("retry_after_s")
        if retry is None and hdrs.get("Retry-After"):
            retry = float(hdrs["Retry-After"])
        return retry

    def status(self, job_id: str) -> dict:
        status, _, body = self._request(urllib.request.Request(
            f"{self.base_url}/status?id={job_id}"))
        payload = self._payload(body)
        if status != 200:
            raise ServeClientError(f"status failed ({status}): {payload}",
                                   payload)
        return payload

    def wait(self, job_id: str, timeout_s: float = 60.0,
             poll_s: float = 0.05) -> dict:
        """Poll until the job is terminal; returns its final status dict.
        A FAILED job returns normally — callers inspect ``error`` (its
        taxonomy chain tells retryable congestion from poisoned input)."""
        deadline = time.monotonic() + timeout_s
        grace_end = time.monotonic() + self.unknown_grace_s
        while True:
            try:
                st = self.status(job_id)
            except TransportError:
                # A restarting/failing-over replica mid-poll: keep
                # polling (the base URL already rotated) until the
                # caller's own deadline says stop.
                if time.monotonic() > deadline:
                    raise
                self._sleep(poll_s)
                continue
            except ServeClientError as e:
                # An "unknown job" 404 can be a wrong-replica answer
                # rather than a terminal fact: multi-URL clients after
                # a transport rotation (the job lives on the replica
                # that admitted it — rotate onward), and router
                # clients while the admitting replica is dead awaiting
                # recovery (poll through within unknown_grace_s).
                now = time.monotonic()
                if "unknown job" in str(e) and now <= deadline \
                        and (len(self._urls) > 1 or now < grace_end):
                    self._rotate()
                    self._sleep(poll_s)
                    continue
                raise
            if st["status"] in ("done", "failed"):
                return st
            if time.monotonic() > deadline:
                raise ServeClientError(
                    f"job {job_id} still {st['status']} after "
                    f"{timeout_s}s", st)
            time.sleep(poll_s)

    def result(self, job_id: str) -> bytes:
        status, _, body = self._request(urllib.request.Request(
            f"{self.base_url}/result?id={job_id}"))
        if status != 200:
            raise ServeClientError(
                f"result not available ({status})", self._payload(body))
        return body

    def run(self, stack: np.ndarray, result_format: str = "ply",
            timeout_s: float = 60.0) -> tuple[bytes, dict]:
        """submit + wait + fetch; raises on a failed job."""
        job_id = self.submit(stack, result_format=result_format)
        st = self.wait(job_id, timeout_s=timeout_s)
        if st["status"] != "done":
            raise ServeClientError(
                f"job {job_id} failed: {st.get('error')}", st)
        return self.result(job_id), st

    # -- streaming sessions (docs/STREAMING.md) ------------------------

    def create_session(self, **options) -> str:
        """POST /session → session id. ``options`` are the per-session
        overrides the server allows (preview_depth, expected_stops, …)."""
        def once():
            req = urllib.request.Request(
                self.base_url + "/session",
                data=json.dumps(options).encode(),
                headers=self._tenant_headers(
                    {"Content-Type": "application/json"}),
                method="POST")
            status, hdrs, body = self._request(req)
            payload = self._payload(body)
            if status in (429, 503):
                raise BackpressureError(
                    f"session refused ({status})",
                    self._retry_hint(payload, hdrs), payload)
            if status != 200:
                raise ServeClientError(
                    f"create_session failed ({status}): {payload}",
                    payload)
            return payload["session_id"]

        return self._retrying(once)

    def submit_stop(self, session_id: str, stack: np.ndarray) -> str:
        """POST one stop's capture stack into a session; returns the
        stop job id (poll with :meth:`wait` — its result meta carries
        the fuse/skip decision)."""
        stack = np.asarray(stack)
        if stack.dtype != np.uint8:
            raise ServeClientError(
                f"stack must be uint8, got {stack.dtype}")
        buf = io.BytesIO()
        np.save(buf, stack)
        data = buf.getvalue()

        def once():
            req = urllib.request.Request(
                f"{self.base_url}/session/{session_id}/stop",
                data=data,
                headers=self._tenant_headers(
                    {"Content-Type": "application/octet-stream"}),
                method="POST")
            status, hdrs, body = self._request(req)
            payload = self._payload(body)
            if status in (429, 503):
                raise BackpressureError(
                    f"stop refused ({status})",
                    self._retry_hint(payload, hdrs), payload)
            if status != 200:
                raise ServeClientError(
                    f"submit_stop failed ({status}): {payload}", payload)
            return payload["job_id"]

        return self._retrying(once)

    def session_status(self, session_id: str) -> dict:
        status, _, body = self._request(urllib.request.Request(
            f"{self.base_url}/session/{session_id}"))
        payload = self._payload(body)
        if status != 200:
            raise ServeClientError(
                f"session_status failed ({status}): {payload}", payload)
        return payload

    def preview(self, session_id: str) -> tuple[bytes, dict] | None:
        """Latest progressive preview STL, or None before the first
        preview (HTTP 409)."""
        status, hdrs, body = self._request(urllib.request.Request(
            f"{self.base_url}/session/{session_id}/preview"))
        if status == 409:
            return None
        if status != 200:
            raise ServeClientError(
                f"preview failed ({status})", self._payload(body))
        meta = {k[2:].lower().replace("-", "_"): v
                for k, v in hdrs.items() if k.startswith("X-")}
        return body, meta

    def render(self, session_id: str, azim: float = 30.0,
               elev: float = 20.0,
               size: tuple | None = None) -> tuple[bytes, dict] | None:
        """GET /session/<id>/render → (PNG bytes, meta) novel view of
        the session's splat scene (``representation="splat"``), or None
        before the first fused stop (HTTP 409). ``size`` must be one of
        the server's configured (W, H) render sizes."""
        q = f"?az={float(azim)}&el={float(elev)}"
        if size is not None:
            q += f"&w={int(size[0])}&h={int(size[1])}"
        status, hdrs, body = self._request(urllib.request.Request(
            f"{self.base_url}/session/{session_id}/render{q}"))
        if status == 409:
            return None
        if status != 200:
            raise ServeClientError(
                f"render failed ({status})", self._payload(body))
        meta = {k[2:].lower().replace("-", "_"): v
                for k, v in hdrs.items() if k.startswith("X-")}
        return body, meta

    def splats(self, session_id: str) -> bytes | None:
        """GET /session/<id>/splats → the scene .npz (``cli render``
        re-renders it offline), or None before the first stop."""
        status, _, body = self._request(urllib.request.Request(
            f"{self.base_url}/session/{session_id}/splats"))
        if status == 409:
            return None
        if status != 200:
            raise ServeClientError(
                f"splats failed ({status})", self._payload(body))
        return body

    def finalize_session(self, session_id: str,
                         result_format: str = "stl") -> dict:
        """POST finalize; returns {"job_id", "status", "result"} — fetch
        the artifact with :meth:`result`."""
        req = urllib.request.Request(
            f"{self.base_url}/session/{session_id}/finalize",
            data=json.dumps({"result_format": result_format}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        status, _, body = self._request(req)
        payload = self._payload(body)
        if status != 200:
            raise ServeClientError(
                f"finalize failed ({status}): {payload}", payload)
        return payload

    def delete_session(self, session_id: str) -> None:
        req = urllib.request.Request(
            f"{self.base_url}/session/{session_id}", method="DELETE")
        status, _, body = self._request(req)
        if status != 200:
            raise ServeClientError(
                f"delete_session failed ({status})", self._payload(body))

    # ------------------------------------------------------------------

    def healthz(self) -> dict:
        """Liveness: 200 with stats while the process answers."""
        _, _, body = self._request(urllib.request.Request(
            self.base_url + "/healthz"))
        return self._payload(body)

    def readyz(self) -> dict:
        """Readiness: ``{"ready": bool, "reasons": [...]}`` — 503-bodied
        during warmup/recovery, drain, or with no worker lanes alive."""
        _, _, body = self._request(urllib.request.Request(
            self.base_url + "/readyz"))
        return self._payload(body)

    def metrics(self) -> str:
        status, _, body = self._request(urllib.request.Request(
            self.base_url + "/metrics"))
        if status != 200:
            raise ServeClientError(f"metrics failed ({status})")
        return body.decode()
